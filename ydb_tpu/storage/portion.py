"""Immutable indexed portions with per-column statistics.

The unit of storage in a column shard — analog of the reference's portion
(`ydb/core/tx/columnshard/engines/portions/`): an immutable columnar chunk
with min/max stats per column used for scan pruning, stamped with the MVCC
write version that committed it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ydb_tpu.core.block import HostBlock
from ydb_tpu.storage.mvcc import WriteVersion


class _IdGen:
    """Monotonic portion ids; recovery advances past ids found on disk so
    new portions never collide with persisted files."""

    def __init__(self):
        self.n = 0

    def __next__(self) -> int:
        self.n += 1
        return self.n

    def ensure_above(self, m: int) -> None:
        self.n = max(self.n, m)


_portion_ids = _IdGen()


@dataclass
class ColumnStats:
    min: object = None
    max: object = None
    null_count: int = 0


@dataclass
class DeleteMark:
    """MVCC delete of specific rows of a portion — the reference keeps
    per-row delete versions inside portions for transactional OLAP DML
    (`ydb/core/tx/columnshard/engines/` MVCC portions); here a mark is a
    row-index set stamped with its commit version. Uncommitted marks
    (version None) belong to an open interactive tx and are visible only
    through its tx_view — the InsertEntry model, mirrored for deletes."""
    rows: np.ndarray                   # sorted unique row indices
    version: Optional[WriteVersion] = None
    tx: Optional[int] = None
    seq: int = 0                       # unique per portion (cache keys)


@dataclass
class Portion:
    block: HostBlock
    version: WriteVersion
    stats: dict = field(default_factory=dict)   # col name -> ColumnStats
    id: int = field(default_factory=lambda: next(_portion_ids))
    deletes: list = field(default_factory=list)  # [DeleteMark]
    _mark_seq: int = 0

    @property
    def num_rows(self) -> int:
        return self.block.length

    # -- MVCC deletes -------------------------------------------------------

    def add_delete(self, rows: np.ndarray,
                   version: Optional[WriteVersion] = None,
                   tx: Optional[int] = None) -> "DeleteMark":
        self._mark_seq += 1
        mark = DeleteMark(np.unique(np.asarray(rows, np.int64)), version,
                          tx, self._mark_seq)
        # single rebind: lock-free readers see the old or new list whole
        self.deletes = self.deletes + [mark]
        return mark

    def drop_delete(self, mark: "DeleteMark") -> None:
        self.deletes = [m for m in self.deletes if m is not mark]

    def visible_dead(self, snapshot) -> Optional[np.ndarray]:
        """Union of row indices deleted as of `snapshot` (None = none):
        committed marks at or before the snapshot, plus the snapshot's own
        open tx's staged marks."""
        dead = None
        for m in self.deletes:
            vis = (m.version is not None and snapshot.includes(m.version)) \
                or (m.version is None and m.tx is not None
                    and m.tx == snapshot.tx_view)
            if vis:
                dead = m.rows if dead is None \
                    else np.union1d(dead, m.rows)
        return dead if dead is not None and len(dead) else None

    def delete_sig(self, snapshot) -> tuple:
        """Cache-key component: which marks the snapshot sees."""
        return tuple(m.seq for m in self.deletes
                     if (m.version is not None
                         and snapshot.includes(m.version))
                     or (m.version is None and m.tx is not None
                         and m.tx == snapshot.tx_view))

    def visible_block(self, snapshot) -> HostBlock:
        dead = self.visible_dead(snapshot)
        if dead is None:
            return self.block
        sig = self.delete_sig(snapshot)
        cached = getattr(self, "_vb_cache", None)
        if cached is not None and cached[0] == sig:
            return cached[1]
        keep = np.setdiff1d(np.arange(self.num_rows, dtype=np.int64), dead)
        blk = self.block.take(keep)
        self._vb_cache = (sig, blk)      # one filtered view per mark set
        return blk

    @staticmethod
    def from_block(block: HostBlock, version: WriteVersion,
                   id: Optional[int] = None) -> "Portion":
        """`id`: recovery restores the persisted portion id (a fresh one
        would alias a different on-disk file)."""
        stats = {}
        for c in block.schema:
            cd = block.columns[c.name]
            st = ColumnStats()
            if cd.valid is not None:
                st.null_count = int((~cd.valid).sum())
                vals = cd.data[cd.valid]
            else:
                vals = cd.data
            if len(vals) and not c.dtype.is_string:
                st.min = vals.min()
                st.max = vals.max()
            stats[c.name] = st
        if id is not None:
            return Portion(block, version, stats, id)
        return Portion(block, version, stats)


def prune_by_range(portion: Portion, col: str, op: str, value) -> bool:
    """True if the portion can be skipped for `col <op> value` (no row matches).

    The pruning analog of the reference's early-filter index checks
    (`engines/reader/.../fetching.h` TApplyIndexStep / TPredicateFilter)."""
    st = portion.stats.get(col)
    if st is None or st.min is None:
        return False
    lo, hi = st.min, st.max
    if op == "eq":
        return value < lo or value > hi
    if op == "lt":
        return lo >= value
    if op == "le":
        return lo > value
    if op == "gt":
        return hi <= value
    if op == "ge":
        return hi < value
    return False
