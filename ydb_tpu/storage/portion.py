"""Immutable indexed portions with per-column statistics.

The unit of storage in a column shard — analog of the reference's portion
(`ydb/core/tx/columnshard/engines/portions/`): an immutable columnar chunk
with min/max stats per column used for scan pruning, stamped with the MVCC
write version that committed it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ydb_tpu.core.block import HostBlock
from ydb_tpu.storage.mvcc import WriteVersion


class _IdGen:
    """Monotonic portion ids; recovery advances past ids found on disk so
    new portions never collide with persisted files."""

    def __init__(self):
        self.n = 0

    def __next__(self) -> int:
        self.n += 1
        return self.n

    def ensure_above(self, m: int) -> None:
        self.n = max(self.n, m)


_portion_ids = _IdGen()


@dataclass
class ColumnStats:
    min: object = None
    max: object = None
    null_count: int = 0


@dataclass
class Portion:
    block: HostBlock
    version: WriteVersion
    stats: dict = field(default_factory=dict)   # col name -> ColumnStats
    id: int = field(default_factory=lambda: next(_portion_ids))

    @property
    def num_rows(self) -> int:
        return self.block.length

    @staticmethod
    def from_block(block: HostBlock, version: WriteVersion,
                   id: Optional[int] = None) -> "Portion":
        """`id`: recovery restores the persisted portion id (a fresh one
        would alias a different on-disk file)."""
        stats = {}
        for c in block.schema:
            cd = block.columns[c.name]
            st = ColumnStats()
            if cd.valid is not None:
                st.null_count = int((~cd.valid).sum())
                vals = cd.data[cd.valid]
            else:
                vals = cd.data
            if len(vals) and not c.dtype.is_string:
                st.min = vals.min()
                st.max = vals.max()
            stats[c.name] = st
        if id is not None:
            return Portion(block, version, stats, id)
        return Portion(block, version, stats)


def prune_by_range(portion: Portion, col: str, op: str, value) -> bool:
    """True if the portion can be skipped for `col <op> value` (no row matches).

    The pruning analog of the reference's early-filter index checks
    (`engines/reader/.../fetching.h` TApplyIndexStep / TPredicateFilter)."""
    st = portion.stats.get(col)
    if st is None or st.min is None:
        return False
    lo, hi = st.min, st.max
    if op == "eq":
        return value < lo or value > hi
    if op == "lt":
        return lo >= value
    if op == "le":
        return lo > value
    if op == "gt":
        return hi <= value
    if op == "ge":
        return hi < value
    return False
