"""Portion blob + WAL file formats (native C++ fast path, numpy fallback).

ONE on-disk format, two implementations. The native library
(`ydb_tpu/native/blobio.cpp`) owns the IO when a toolchain is present —
CRC-32 framing, fsync discipline, atomic renames — mirroring how the
reference's persistence floor is native (PDisk chunk/log framing,
`ydb/core/blobstorage/pdisk/`). The fallback here produces byte-identical
files with numpy + zlib.crc32 (same polynomial), so either side can read
the other's output; `tests/test_native_blobio.py` pins that equivalence.

Portion file (.ydbp):
    "YDBP" | u32 version=1 | u32 header_len | u32 header_crc
    | header JSON | zero-pad to 64 | per-column sections (64-aligned):
    data bytes, then validity bytes (u8/row) for nullable columns.
Header JSON: {"rows": N, "cols": [{"name", "dtype" (numpy str),
    "off", "len", "crc", ["voff", "vlen", "vcrc"]} ...]}

WAL file (wal.bin): records framed as u32 len | u32 crc | payload
(payload = UTF-8 JSON, opaque to the framing layer). Replay stops at the
first torn/corrupt frame — the PDisk log-tail rule.
"""

from __future__ import annotations

import ctypes
import json
import os
import zlib

import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.schema import Schema
from ydb_tpu.native import lib as _native_lib

_ALIGN = 64


def _pad(off: int) -> int:
    return (off + _ALIGN - 1) // _ALIGN * _ALIGN


def _layout(block: HostBlock):
    """Header dict + ordered section arrays (shared by both writers)."""
    cols = []
    sections = []
    off = 0  # relative to section base (end of padded header)
    for name, cd in block.columns.items():
        data = np.ascontiguousarray(cd.data)
        ent = {"name": name, "dtype": data.dtype.str,
               "off": off, "len": int(data.nbytes), "crc": None}
        sections.append(data)
        off = _pad(off + data.nbytes)
        if cd.valid is not None:
            v = np.ascontiguousarray(cd.valid.astype(np.uint8))
            ent["voff"], ent["vlen"] = off, int(v.nbytes)
            sections.append(v)
            off = _pad(off + v.nbytes)
        cols.append(ent)
    # CRCs in one pass (native when possible)
    si = 0
    for ent in cols:
        ent["crc"] = _crc(sections[si]); si += 1
        if "voff" in ent:
            ent["vcrc"] = _crc(sections[si]); si += 1
    header = {"rows": block.length, "cols": cols}
    return header, sections


def _crc(arr: np.ndarray) -> int:
    L = _native_lib()
    buf = arr.tobytes() if not arr.flags["C_CONTIGUOUS"] else arr
    if L is not None:
        p = buf if isinstance(buf, bytes) else buf.ctypes.data_as(
            ctypes.c_char_p)
        n = len(buf) if isinstance(buf, bytes) else buf.nbytes
        return int(L.ydbt_crc32(p, n))
    return zlib.crc32(buf if isinstance(buf, bytes) else buf.tobytes())


def write_portion(path: str, block: HostBlock) -> None:
    header, sections = _layout(block)
    hjson = json.dumps(header, separators=(",", ":")).encode()
    L = _native_lib()
    if L is not None:
        ptrs = (ctypes.c_void_p * len(sections))(
            *[s.ctypes.data_as(ctypes.c_void_p).value for s in sections])
        lens = (ctypes.c_uint64 * len(sections))(
            *[s.nbytes for s in sections])
        rc = L.ydbt_write_portion(path.encode(), hjson, len(hjson),
                                  len(sections), ptrs, lens)
        if rc != 0:
            raise OSError(-rc, f"native portion write failed: {path}")
        return
    # numpy fallback — byte-identical layout AND durability discipline
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        head = b"YDBP" + np.uint32(1).tobytes() \
            + np.uint32(len(hjson)).tobytes() \
            + np.uint32(zlib.crc32(hjson)).tobytes()
        f.write(head)
        f.write(hjson)
        off = 16 + len(hjson)
        if off % _ALIGN:
            f.write(b"\0" * (_ALIGN - off % _ALIGN))
        for s in sections:
            f.write(s.tobytes())
            n = s.nbytes
            if n % _ALIGN:
                f.write(b"\0" * (_ALIGN - n % _ALIGN))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def _fsync_dir(dirpath: str) -> None:
    """Make a rename durable (the native writer does the same)."""
    try:
        fd = os.open(dirpath, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def read_portion(path: str, schema: Schema, dicts: dict) -> HostBlock:
    """Read + CRC-verify a portion (single file read; CRC runs native
    when the library is loaded)."""
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:4] != b"YDBP":
        raise ValueError(f"{path}: bad magic")
    hlen = int(np.frombuffer(raw, np.uint32, 1, 8)[0])
    hcrc = int(np.frombuffer(raw, np.uint32, 1, 12)[0])
    hjson = raw[16:16 + hlen]
    if zlib.crc32(hjson) != hcrc:
        raise ValueError(f"{path}: header checksum mismatch")
    header = json.loads(hjson)
    base = _pad(16 + hlen)
    by_name = {}
    for ent in header["cols"]:
        d0 = base + ent["off"]
        data = np.frombuffer(raw, np.dtype(ent["dtype"]),
                             count=ent["len"] // np.dtype(ent["dtype"]).itemsize,
                             offset=d0)
        if _crc(data) != ent["crc"]:
            raise ValueError(f"{path}: column {ent['name']} corrupt")
        valid = None
        if "voff" in ent:
            v = np.frombuffer(raw, np.uint8, count=ent["vlen"],
                              offset=base + ent["voff"])
            if _crc(v) != ent["vcrc"]:
                raise ValueError(
                    f"{path}: column {ent['name']} validity corrupt")
            valid = v.astype(bool)
        by_name[ent["name"]] = (data, valid)
    cols = {}
    for c in schema:
        if c.name not in by_name:
            # the portion predates this column (ALTER TABLE ADD COLUMN):
            # synthesize nulls — per-portion schema versioning
            if not c.dtype.nullable:
                raise ValueError(
                    f"{path}: missing NOT NULL column {c.name}")
            fill = -1 if c.dtype.is_string else 0   # -1 = null string code
            cols[c.name] = ColumnData(
                np.full(header["rows"], fill, dtype=c.dtype.np),
                np.zeros(header["rows"], dtype=bool),
                dicts.get(c.name))
            continue
        data, valid = by_name[c.name]
        cols[c.name] = ColumnData(np.array(data), valid,
                                  dicts.get(c.name))
    return HostBlock(schema, cols, header["rows"])


# -- WAL -------------------------------------------------------------------


def wal_append(path: str, rec: dict, sync: bool = True) -> None:
    payload = json.dumps(rec, separators=(",", ":")).encode()
    L = _native_lib()
    if L is not None:
        rc = L.ydbt_wal_append(path.encode(), payload, len(payload),
                               1 if sync else 0)
        if rc != 0:
            raise OSError(-rc, f"native wal append failed: {path}")
        return
    frame = np.uint32(len(payload)).tobytes() \
        + np.uint32(zlib.crc32(payload)).tobytes() + payload
    with open(path, "ab") as f:
        f.write(frame)
        f.flush()
        if sync:
            os.fsync(f.fileno())


def wal_replay(path: str) -> list:
    """Valid records up to a torn tail (an incomplete LAST frame — the
    expected crash shape, dropped silently). A complete frame with a bad
    CRC means real corruption with possibly-acked records behind it:
    that fails loudly instead of silently truncating history."""
    if not os.path.exists(path):
        return []
    with open(path, "rb") as f:
        raw = f.read()
    L = _native_lib()
    if L is not None:
        good = ctypes.c_uint64()
        status = ctypes.c_int32()
        L.ydbt_wal_scan(raw, len(raw), ctypes.byref(good),
                        ctypes.byref(status))
        valid, st = good.value, status.value
    else:
        valid, st = _scan_frames(raw)
    if st == 2:
        raise ValueError(
            f"{path}: WAL corrupt at byte {valid} (complete frame with "
            "bad checksum) — refusing to silently drop records after it")
    recs = []
    off = 0
    while off < valid:
        ln = int(np.frombuffer(raw, np.uint32, 1, off)[0])
        recs.append(json.loads(raw[off + 8:off + 8 + ln]))
        off += 8 + ln
    return recs


def _scan_frames(raw: bytes):
    """(valid_prefix_bytes, status) — mirror of the native ydbt_wal_scan:
    status 0 = clean, 1 = torn tail, 2 = mid-log corruption."""
    off = 0
    n = len(raw)
    while True:
        if off == n:
            return off, 0
        if off + 8 > n:
            return off, 1
        ln = int(np.frombuffer(raw, np.uint32, 1, off)[0])
        crc = int(np.frombuffer(raw, np.uint32, 1, off + 4)[0])
        if off + 8 + ln > n:
            return off, 2 if (ln > (1 << 30)
                              and n - off > (1 << 20)) else 1
        if ln > (1 << 30) or zlib.crc32(raw[off + 8:off + 8 + ln]) != crc:
            return off, 2
        off += 8 + ln


def wal_rewrite(path: str, recs: list) -> None:
    """Atomically replace the WAL contents (post-indexation truncate)."""
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        os.unlink(tmp)
    for r in recs:
        wal_append(tmp, r, sync=False)
    if not os.path.exists(tmp):
        open(tmp, "wb").close()
    with open(tmp, "rb+") as f:
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
