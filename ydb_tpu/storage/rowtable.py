"""Row-store OLTP tables — the DataShard analog (embedded v0).

The reference's DataShard (`ydb/core/tx/datashard/datashard_impl.h:165`)
is a key-ordered row store with MVCC reads (`datashard__read_iterator.cpp`)
and per-key UPSERT/DELETE under the distributed-tx protocol. The TPU-first
analog keeps rows on the HOST — OLTP point ops are control-plane work; the
TPU earns its keep on scans — with:

  * a primary-key → version-chain map (each entry `(version, values|None)`,
    None = tombstone) giving MVCC point reads and snapshot scans;
  * UPSERT / INSERT (duplicate-checked) / REPLACE / DELETE by key;
  * columnar materialization of any snapshot (`snapshot_entries`), so the
    whole SQL/scan/device path runs unchanged over row tables (the scan
    executor consumes it through the same `scan_sources` protocol as
    ColumnShard insert buffers);
  * a mutation WAL through `storage/persist.Store` for durability.

Column tables remain the analytics home; a row table is the right home for
high-churn key-value state (the reference's default `STORE=ROW`).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.dictionary import Dictionary
from ydb_tpu.core.schema import Schema
from ydb_tpu.storage.mvcc import MAX_SNAPSHOT, Snapshot, WriteVersion
from ydb_tpu.storage.table import _table_uids


class _RowScanAdapter:
    """Presents a snapshot of the row store through the ColumnShard
    `scan_sources` protocol (as one committed insert-buffer entry), so the
    scan executor and device caches need no row-specific path."""

    def __init__(self, table: "RowTable"):
        self.table = table
        self.shard_id = 0
        self.portion_rows = 1 << 20
        self.portions: list = []       # row stores have no portions

    def scan_sources(self, snapshot: Snapshot = MAX_SNAPSHOT,
                     prune_predicates=None):
        # equality prune over an indexed column (or the pk) serves a
        # candidates-only block instead of the full table
        t = self.table
        eq = None
        for (col, op, val) in (prune_predicates or ()):
            if op == "eq" and (
                    col in t._index_data
                    or (len(t.key_columns) == 1 and col == t.key_columns[0]
                        and not t.schema.dtype(col).is_string)):
                eq = (col, val)
                break
        return [], t.snapshot_entries(snapshot, eq=eq)

    def scan(self, columns: list[str], snapshot: Snapshot = MAX_SNAPSHOT,
             prune_predicates=None,
             block_rows: Optional[int] = None) -> Iterator[HostBlock]:
        for e in self.table.snapshot_entries(snapshot):
            if e.block.length:
                yield e.block.select(columns)

    def indexate(self) -> int:
        return 0

    def compact(self) -> int:
        return 0

    @property
    def num_rows(self) -> int:
        return self.table.num_rows


class _SnapshotEntry:
    """Duck-typed InsertEntry: (block, write_id) for cache identity."""

    def __init__(self, block: HostBlock, write_id):
        self.block = block
        self.write_id = write_id
        self.committed_version = WriteVersion(0, 0)


class RowTable:
    def __init__(self, name: str, schema: Schema, key_columns: list[str],
                 shards: int = 1, portion_rows: int = 1 << 20,
                 partition_by: Optional[list[str]] = None):
        if not key_columns:
            raise ValueError("row tables need a primary key")
        self.name = name
        self.schema = schema
        self.key_columns = key_columns
        self.partition_by = partition_by or [key_columns[0]]
        self.store_kind = "row"
        # pk tuple -> [(WriteVersion | None, values tuple | None, tx)],
        # append-ordered; version None = uncommitted entry of open tx `tx`
        # (stamped at commit, removed at rollback)
        self.rows: dict[tuple, list] = {}
        self.dictionaries: dict[str, Dictionary] = {
            c.name: Dictionary() for c in schema if c.dtype.is_string}
        self.uid = next(_table_uids)
        self.data_version = 0
        self.store = None
        self.shards = [_RowScanAdapter(self)]
        self._snap_cache: dict = {}    # (data_version, snap) -> entries
        self._tx_touched: dict = {}    # open tx id -> set of touched pks
        # secondary indexes (schemeshard build-index analog, v0):
        # name -> column; per-column candidate map value -> {pk}. The map
        # over-approximates (no removal on delete/update) — reads verify
        # visibility + current value, so stale candidates are harmless.
        self.indexes: dict[str, str] = {}
        self._index_data: dict[str, dict] = {}
        # CDC sink (storage/topic.ChangefeedSink) — committed mutations
        # publish to a topic in commit order (change_exchange analog)
        self.changefeed = None
        # open-tx CDC events (old/new images captured at statement time;
        # emitted at stamp_tx, discarded at rollback_tx). Statement-time
        # images are commit-time-correct: optimistic point-conflict
        # validation aborts any tx whose touched keys were re-committed
        # under it, so a tx that reaches stamp_tx saw the images it
        # publishes.
        self._tx_events: dict = {}
        # WAL-replay event log: persist.load arms this ([]) before
        # replaying rowwal.bin, apply() appends (version, events) per
        # replayed commit, and the engine re-emits them through the
        # changefeed after topics load — producer seq dedup drops all but
        # a torn topic tail. None outside recovery.
        self._replay_log = None

    # -- write path -------------------------------------------------------

    def _pk_of(self, vals: dict) -> tuple:
        return tuple(vals[k] for k in self.key_columns)

    def _encode_value(self, col: str, v):
        dt = self.schema.dtype(col)
        if v is None:
            return None
        if dt.is_string:
            return int(self.dictionaries[col].encode([str(v)])[0])
        return dt.np(v).item() if not isinstance(v, (int, float, bool)) \
            else v

    def _decode_row(self, values) -> Optional[dict]:
        """Stored (encoded) value tuple -> {col: python value} with string
        codes decoded — the CDC row-image domain."""
        if values is None:
            return None
        out = {}
        for c, v in zip(self.schema.columns, values):
            if v is not None and c.dtype.is_string:
                v = self.dictionaries[c.name]._values[v]
            out[c.name] = v
        return out

    # -- schema evolution (ALTER TABLE) ------------------------------------

    def add_column(self, col) -> None:
        """ADD COLUMN (nullable only): stored value tuples are positional
        by schema order — every version chain gains a None slot."""
        self.schema = self.schema.extend([col])
        if col.dtype.is_string:
            self.dictionaries[col.name] = Dictionary()
        for pk, chain in self.rows.items():
            self.rows[pk] = [
                (v, (vals + (None,)) if vals is not None else None, etx)
                for (v, vals, etx) in chain]
        self.data_version += 1
        self._snap_cache.clear()

    def create_index(self, iname: str, col: str) -> None:
        if not self.schema.has(col):
            raise ValueError(f"unknown column {col!r}")
        if iname in self.indexes:
            raise ValueError(f"index {iname!r} already exists")
        self.indexes[iname] = col
        if col not in self._index_data:
            ix = self.schema.names.index(col)
            data: dict = {}
            for pk, chain in self.rows.items():
                for (_v, vals, _tx) in chain:
                    if vals is not None:
                        data.setdefault(vals[ix], set()).add(pk)
            self._index_data[col] = data

    def drop_index(self, iname: str) -> None:
        col = self.indexes.pop(iname, None)
        if col is None:
            raise ValueError(f"unknown index {iname!r}")
        if col not in self.indexes.values():
            self._index_data.pop(col, None)

    def drop_column(self, name: str) -> None:
        for iname, col in list(self.indexes.items()):
            if col == name:
                raise ValueError(
                    f"column {name!r} is indexed by {iname!r}; drop the "
                    "index first")
        ix = self.schema.names.index(name)
        self.schema = Schema([c for c in self.schema.columns
                              if c.name != name])
        self.dictionaries.pop(name, None)
        for pk, chain in self.rows.items():
            self.rows[pk] = [
                (v, (vals[:ix] + vals[ix + 1:]) if vals is not None
                 else None, etx)
                for (v, vals, etx) in chain]
        self.data_version += 1
        self._snap_cache.clear()
        if self.store is not None:
            # the mutation log still carries pre-DROP values: compact it
            # to the surviving state or a later re-ADD of the same name
            # would resurrect them at replay
            self.store.rewrite_row_wal(self)

    def apply(self, ops: list, version: Optional[WriteVersion],
              durable: bool = True, tx: Optional[int] = None,
              strict: bool = True) -> int:
        """Apply a batch of mutations.

        ops: [("upsert"|"insert"|"replace", {col: value}) | ("delete",
        {pk col: value})]. "insert" raises on a live duplicate key;
        "replace" nulls unspecified columns; "upsert" merges with the
        previous visible row. Returns rows affected.

        With `tx`, entries stay UNCOMMITTED (visible only through a
        snapshot carrying `tx_view == tx`) until `stamp_tx`/`rollback_tx`
        — the interactive-transaction write path (`ydb_tpu/tx`).

        The batch is ATOMIC: every op validates against the batch's own
        running state first; nothing mutates until all of them pass."""
        view = Snapshot(2 ** 62, 2 ** 62, tx_view=tx)
        appends: list[tuple[tuple, object]] = []   # (pk, values | None)
        overlay: dict[tuple, object] = {}          # batch-local live view
        events: list = []   # CDC: committed effects with old/new images
        for kind, vals in ops:
            # non-strict = WAL replay: mutations may predate a DROP COLUMN
            enc = {c: self._encode_value(c, v) for c, v in vals.items()
                   if strict or self.schema.has(c)}
            pk = self._pk_of(enc)
            if pk in overlay:
                live = overlay[pk]
            else:
                live = self._visible(self.rows.get(pk, ()), view)
            if kind == "delete":
                if live is None:
                    continue           # no-op delete: no effect, no event
                appends.append((pk, None))
                overlay[pk] = None
                events.append({"op": kind, "row": vals,
                               "old": self._decode_row(live), "new": None})
                continue
            if kind == "insert" and live is not None:
                raise ValueError(
                    f"duplicate primary key {pk} in {self.name}")
            row = {}
            if kind == "upsert" and live is not None:
                row.update(dict(zip(self.schema.names, live)))
            for c in self.schema.names:
                if c in enc:
                    row[c] = enc[c]
                elif c not in row:
                    if not self.schema.dtype(c).nullable:
                        raise ValueError(f"missing NOT NULL column {c}")
                    row[c] = None
            values = tuple(row[c] for c in self.schema.names)
            appends.append((pk, values))
            overlay[pk] = values
            events.append({"op": kind, "row": vals,
                           "old": self._decode_row(live),
                           "new": self._decode_row(values)})
        # validation passed — mutate
        idx_cols = [(col, self.schema.names.index(col), data)
                    for col, data in self._index_data.items()]
        for pk, values in appends:
            self.rows.setdefault(pk, []).append((version, values, tx))
            if values is not None:
                for _col, cix, data in idx_cols:
                    data.setdefault(values[cix], set()).add(pk)
        if tx is not None:
            self._tx_touched.setdefault(tx, set()).update(
                pk for pk, _v in appends)
            if events:
                self._tx_events.setdefault(tx, []).extend(events)
        self.data_version += 1
        self._snap_cache.clear()
        if durable and tx is None and self.store is not None:
            self.store.row_wal_append(self.name, ops, version)
            self.store.save_dictionaries(self)
            self.store.save_state(version.plan_step)
        if tx is None and version is not None \
                and self._replay_log is not None:
            self._replay_log.append((version, events))
        if self.changefeed is not None and tx is None \
                and version is not None and durable and events:
            self.changefeed.emit(events, version)
        return len(appends)

    def max_committed_step(self, pks) -> int:
        """Highest committed plan step across the given pk chains — the
        point-conflict probe for write-only optimistic validation."""
        hi = 0
        for pk in pks:
            for (ver, _vals, _tx) in self.rows.get(pk, ()):
                if ver is not None and ver.plan_step > hi:
                    hi = ver.plan_step
        return hi

    def pks_of_ops(self, ops: list) -> set:
        """Primary keys a mutation batch touches (encoded domain) —
        only KEY columns encode (apply() already paid the full pass)."""
        out = set()
        for (_kind, vals) in ops:
            try:
                enc = {k: self._encode_value(k, vals[k])
                       for k in self.key_columns if k in vals}
                out.add(self._pk_of(enc))
            except KeyError:
                pass                   # malformed op: apply() will raise
        return out

    def stamp_tx(self, tx: int, version: WriteVersion,
                 ops_for_wal: Optional[list] = None) -> None:
        """Commit an open transaction's entries at `version` — O(write
        set), not O(table)."""
        for pk in self._tx_touched.pop(tx, ()):
            chain = self.rows.get(pk)
            if not chain:
                continue
            for i, (ver, vals, etx) in enumerate(chain):
                if etx == tx and ver is None:
                    chain[i] = (version, vals, None)
        self.data_version += 1
        self._snap_cache.clear()
        if self.store is not None and ops_for_wal:
            self.store.row_wal_append(self.name, ops_for_wal, version)
            self.store.save_dictionaries(self)
            self.store.save_state(version.plan_step)
        events = self._tx_events.pop(tx, None)
        if self.changefeed is not None and events:
            self.changefeed.emit(events, version)

    def rollback_tx(self, tx: int) -> None:
        self._tx_events.pop(tx, None)
        for pk in self._tx_touched.pop(tx, ()):
            chain = [(v, vals, etx)
                     for (v, vals, etx) in self.rows.get(pk, [])
                     if not (etx == tx and v is None)]
            if chain:
                self.rows[pk] = chain
            else:
                self.rows.pop(pk, None)
        self.data_version += 1
        self._snap_cache.clear()

    # -- read path --------------------------------------------------------

    @staticmethod
    def _visible(chain: list, snapshot: Snapshot):
        vis = None
        for ver, vals, etx in chain:
            if ver is None:
                if snapshot.tx_view is not None and etx == snapshot.tx_view:
                    vis = vals            # own uncommitted write
            elif snapshot.includes(ver):
                vis = vals
        return vis

    def read_row(self, pk_vals: dict,
                 snapshot: Snapshot = MAX_SNAPSHOT) -> Optional[tuple]:
        """MVCC point read (the TEvRead iterator analog) — host-side, no
        device round trip."""
        enc = {c: self._encode_value(c, v) for c, v in pk_vals.items()}
        chain = self.rows.get(self._pk_of(enc))
        if not chain:
            return None
        return self._visible(chain, snapshot)

    def snapshot_entries(self, snapshot: Snapshot = MAX_SNAPSHOT,
                         eq=None) -> list:
        """Visible rows as one columnar block. `eq=(col, value)`: serve
        from a secondary index (or the pk map) — candidate pks only,
        verified against visibility + current value (the index-lookup
        read of `datashard__read_iterator`)."""
        key = (self.data_version, snapshot.plan_step, snapshot.tx_id,
               snapshot.tx_view, eq)
        hit = self._snap_cache.get(key)
        if hit is not None:
            return hit
        names = self.schema.names
        if eq is not None:
            col, want = eq
            ix = names.index(col)
            if col in self._index_data:
                cands = sorted(self._index_data[col].get(want, ()))
            else:                       # single-column pk point lookup
                cands = [(want,)] if (want,) in self.rows else []
            pks = (pk for pk in cands)
        else:
            pks = iter(sorted(self.rows))  # key-ordered, like DataShard
        cols: dict[str, list] = {c: [] for c in names}
        length = 0
        for pk in pks:
            chain = self.rows.get(pk)
            if chain is None:
                continue
            vals = self._visible(chain, snapshot)
            if vals is None:
                continue
            if eq is not None and vals[ix] != want:
                continue               # stale index candidate
            for c, v in zip(names, vals):
                cols[c].append(v)
            length += 1
        arrays, valids = {}, {}
        for c in self.schema:
            raw = cols[c.name]
            mask = np.array([v is not None for v in raw], dtype=bool)
            arrays[c.name] = np.array(
                [0 if v is None else v for v in raw], dtype=c.dtype.np)
            if not mask.all():
                valids[c.name] = mask
        block = HostBlock.from_arrays(self.schema, arrays, valids,
                                      dict(self.dictionaries))
        entries = [_SnapshotEntry(block, ("rowsnap", key))] if length else []
        self._snap_cache[key] = entries
        return entries

    @property
    def num_shards(self) -> int:
        return 1

    @property
    def num_rows(self) -> int:
        return sum(1 for chain in self.rows.values()
                   if self._visible(chain, MAX_SNAPSHOT) is not None)

    # -- compat shims (ColumnTable interface used by the engine) ----------

    def indexate(self) -> int:
        return 0

    def bulk_upsert(self, df, version: WriteVersion) -> int:
        ops = [("upsert", {c: (None if v != v else v) if isinstance(v, float)
                           else v for c, v in row.items()})
               for row in df.to_dict("records")]
        return self.apply(ops, version)

    def scan_shard(self, shard_id: int, columns: list[str],
                   snapshot: Snapshot = MAX_SNAPSHOT,
                   prune_predicates=None, block_rows=None):
        return self.shards[0].scan(columns, snapshot, prune_predicates,
                                   block_rows)
