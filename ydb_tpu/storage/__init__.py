from ydb_tpu.storage.mvcc import Snapshot, WriteVersion
from ydb_tpu.storage.table import ColumnTable

__all__ = ["ColumnTable", "Snapshot", "WriteVersion"]
