"""Sharded column tables.

A table is N ``ColumnShard``s; rows are routed by hash of the first
partitioning key column — the analog of the reference's hash-sharded OLAP
tables (`ydb/core/tx/data_events/shards_splitter.cpp` hash splitter, and
SchemeShard's partitioning metadata). String columns share one table-wide
dictionary per column so codes are comparable across shards and portions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np

from ydb_tpu.core.block import HostBlock
from ydb_tpu.core.dictionary import Dictionary
from ydb_tpu.core.schema import Schema
from ydb_tpu.storage.mvcc import MAX_SNAPSHOT, Snapshot, WriteVersion
from ydb_tpu.storage.shard import ColumnShard
from ydb_tpu.utils.hashing import splitmix64

_table_uids = iter(range(1, 2 ** 62))


class ColumnTable:
    def __init__(self, name: str, schema: Schema, key_columns: list[str],
                 shards: int = 1, portion_rows: int = 1 << 20,
                 partition_by: Optional[list[str]] = None):
        if not key_columns:
            raise ValueError("column tables need a primary key")
        for k in key_columns:
            if not schema.has(k):
                raise ValueError(f"unknown key column {k}")
        self.name = name
        self.schema = schema
        self.key_columns = key_columns
        self.partition_by = partition_by or [key_columns[0]]
        self.shards = [ColumnShard(schema, i, portion_rows) for i in range(shards)]
        self.dictionaries: dict[str, Dictionary] = {
            c.name: Dictionary() for c in schema if c.dtype.is_string}
        # data_version: bumped on every commit — cached plans snapshot
        # dictionary domains, so the plan cache keys on (uid, data_version)
        # per referenced table (the compile-cache schema-version key of
        # `kqp_compile_service.cpp:411`). uid distinguishes drop/recreate.
        self.uid = next(_table_uids)
        self.data_version = 0
        # durability hook (ydb_tpu/storage/persist.Store); None = volatile
        self.store = None
        # row TTL (ttl.cpp analog): (column, days) — expired rows evict
        # through the portion-rewrite delete path (engine.run_ttl)
        self.ttl = None

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    @property
    def num_rows(self) -> int:
        return sum(s.num_rows for s in self.shards)

    # -- write path -------------------------------------------------------

    def _route(self, block: HostBlock) -> np.ndarray:
        col = self.partition_by[0]
        cd = block.columns[col]
        h = splitmix64(np, cd.data)
        return (h % np.uint64(len(self.shards))).astype(np.int64)

    def write(self, block: HostBlock,
              tx: Optional[int] = None) -> list[tuple[int, int]]:
        """Stage rows into shards (WAL-logged when durable); returns
        [(shard_id, write_id)]. `tx`: owning open transaction (entries
        visible only through its tx_view until commit)."""
        staged: list[tuple[int, int, HostBlock]] = []
        if len(self.shards) == 1:
            staged.append((0, self.shards[0].write(block, tx), block))
        else:
            dest = self._route(block)
            for sid in range(len(self.shards)):
                idx = np.nonzero(dest == sid)[0]
                if len(idx):
                    blk = block.take(idx)
                    staged.append((sid, self.shards[sid].write(blk, tx),
                                   blk))
        if tx is not None:
            # staged writes grow shared dictionaries and change what the
            # owning tx's snapshot sees — cached plans must re-fingerprint
            self.data_version += 1
        if self.store is not None:
            for sid, wid, blk in staged:
                self.store.wal_write(self.name, sid, wid, blk, tx=tx)
        return [(sid, wid) for (sid, wid, _b) in staged]

    def rollback(self, writes: list[tuple[int, int]]) -> None:
        """Drop staged-but-uncommitted writes (interactive tx abort)."""
        by_shard: dict[int, list[int]] = {}
        for sid, wid in writes:
            by_shard.setdefault(sid, []).append(wid)
        for sid, wids in by_shard.items():
            self.shards[sid].rollback(wids)
            if self.store is not None:
                self.store.wal_abort(self.name, sid, wids)
        self.data_version += 1

    def commit(self, writes: list[tuple[int, int]],
               version: WriteVersion, deletes: Optional[list] = None) -> None:
        """Commit staged writes and/or MVCC delete marks ATOMICALLY:
        `deletes` = [(shard, portion, row indices)]. One intent-journal
        record covers both — a crash mid-commit heals to all-or-nothing
        (an UPDATE is delete marks + new rows; losing one half would be
        a data-losing pure delete or a duplicating pure insert)."""
        by_shard: dict[int, list[int]] = {}
        for sid, wid in writes:
            by_shard.setdefault(sid, []).append(wid)
        hits = deletes or []
        if self.store is not None and (by_shard or hits):
            # durable FIRST: the in-memory state below must never be
            # acknowledged unless it can be recovered
            self.store.commit_table(
                self.name, by_shard, version,
                deletes=[(s.shard_id, p.id, [int(r) for r in rows])
                         for (s, p, rows) in hits])
        for sid, wids in by_shard.items():
            self.shards[sid].commit(wids, version)
        for (_shard, portion, rows) in hits:
            portion.add_delete(rows, version=version)
        self.data_version += 1
        if self.store is not None:
            self.store.save_dictionaries(self)
            self.store.save_state(version.plan_step)

    # -- MVCC deletes (transactional column DML) ---------------------------

    def apply_deletes(self, hits: list, version: WriteVersion) -> int:
        """Commit delete marks: `hits` = [(shard, portion, row indices)].
        Historical snapshots keep seeing the rows (time travel preserved —
        the r3 portion-rewrite path destroyed it)."""
        hits = [h for h in hits if len(h[2])]
        if not hits:
            return 0                   # no-op: no bump, no WAL — a match-
        #                                nothing DELETE must not abort
        #                                concurrent optimistic txs
        self.commit([], version, deletes=hits)
        return sum(len(rows) for (_s, _p, rows) in hits)

    def stage_deletes(self, hits: list, tx: int) -> list:
        """Stage delete marks for an open tx (visible only through its
        tx_view); returns handles for commit/rollback."""
        handles = []
        for (shard, portion, rows) in hits:
            if not len(rows):
                continue
            handles.append((shard, portion,
                            portion.add_delete(rows, tx=tx)))
        if handles:
            self.data_version += 1   # own snapshot changes; re-fingerprint
        return handles

    def rollback_deletes(self, handles: list) -> None:
        if not handles:
            return
        for (_shard, portion, mark) in handles:
            portion.drop_delete(mark)
        self.data_version += 1

    def indexate(self, watermark: Optional[int] = None,
                 compact: bool = True) -> int:
        """Background indexation across shards (persists portion sets),
        followed by the compaction policy check — the background-controller
        analog (`columnshard_impl.h` background changes): steady small
        inserts must not accumulate unbounded small portions. `watermark`:
        see `ColumnShard.compact` (snapshot safety)."""
        made = 0
        for s in self.shards:
            n = s.indexate()
            merged = s.compact(watermark) if compact else 0
            made += n
            if self.store is not None and (n or merged):
                self.store.save_indexation(self, s)
        if self.store is not None and made:
            self.store.compact_intents(self)
        return made

    def compact(self, watermark: Optional[int] = None) -> int:
        """Compaction across shards (persists the rewritten portion sets)."""
        merged = 0
        for s in self.shards:
            n = s.compact(watermark)
            merged += n
            if self.store is not None and n:
                self.store.save_indexation(self, s)
        return merged

    # -- schema evolution (ALTER TABLE) ------------------------------------

    def add_column(self, col) -> None:
        """ADD COLUMN: existing portions/staged blocks gain an all-null
        column in memory; on-disk portion files stay untouched (the blob
        reader synthesizes nulls for columns a portion predates — the
        per-portion schema-versioning stance of the reference's
        ColumnShard)."""
        from ydb_tpu.core.block import ColumnData
        self.schema = self.schema.extend([col])
        if col.dtype.is_string:
            self.dictionaries[col.name] = Dictionary()

        def patch(block: HostBlock) -> HostBlock:
            # string nulls are code -1 (0 would index an empty dictionary)
            fill = -1 if col.dtype.is_string else 0
            data = np.full(block.length, fill, dtype=col.dtype.np)
            cd = ColumnData(data, np.zeros(block.length, bool),
                            self.dictionaries.get(col.name))
            return HostBlock(block.schema.extend([col]),
                             {**block.columns, col.name: cd}, block.length)

        for s in self.shards:
            s.schema = self.schema
            for p in s.portions:
                p.block = patch(p.block)
            for e in s.inserts:
                e.block = patch(e.block)
        self.data_version += 1

    def drop_column(self, name: str) -> None:
        """DROP COLUMN: stripped from memory AND from on-disk blobs (a
        later re-ADD of the same name must see nulls, not stale bytes)."""
        self.schema = Schema([c for c in self.schema.columns
                              if c.name != name])
        self.dictionaries.pop(name, None)

        def strip(block: HostBlock) -> HostBlock:
            if name not in block.columns:
                return block
            cols = {n: cd for n, cd in block.columns.items() if n != name}
            return HostBlock(
                Schema([c for c in block.schema.columns if c.name != name]),
                cols, block.length)

        for s in self.shards:
            s.schema = self.schema
            for p in s.portions:
                p.block = strip(p.block)
                p.stats.pop(name, None)
            for e in s.inserts:
                e.block = strip(e.block)
            if self.store is not None:
                self.store.rewrite_shard_blobs(self, s)
        self.data_version += 1

    def bulk_upsert(self, df, version: WriteVersion) -> int:
        """Ingest a pandas DataFrame (BulkUpsert analog): write+commit+indexate."""
        block = HostBlock.from_pandas(df, schema=self.schema,
                                      dictionaries=self.dictionaries)
        writes = self.write(block)
        self.commit(writes, version)
        self.indexate()
        return block.length

    # -- read path --------------------------------------------------------

    def scan_shard(self, shard_id: int, columns: list[str],
                   snapshot: Snapshot = MAX_SNAPSHOT,
                   prune_predicates: Optional[list[tuple]] = None,
                   block_rows: Optional[int] = None) -> Iterator[HostBlock]:
        return self.shards[shard_id].scan(columns, snapshot,
                                          prune_predicates, block_rows)
