"""Sharded column tables.

A table is N ``ColumnShard``s; rows are routed by hash of the first
partitioning key column — the analog of the reference's hash-sharded OLAP
tables (`ydb/core/tx/data_events/shards_splitter.cpp` hash splitter, and
SchemeShard's partitioning metadata). String columns share one table-wide
dictionary per column so codes are comparable across shards and portions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np

from ydb_tpu.core.block import HostBlock
from ydb_tpu.core.dictionary import Dictionary
from ydb_tpu.core.schema import Schema
from ydb_tpu.storage.mvcc import MAX_SNAPSHOT, Snapshot, WriteVersion
from ydb_tpu.storage.shard import ColumnShard
from ydb_tpu.utils.hashing import splitmix64

_table_uids = iter(range(1, 2 ** 62))

# virtual routing buckets per table: rows hash into a fixed bucket space
# and a bucket->shard map places them — splits reassign buckets instead
# of re-hashing the world (consistent-hashing-style, the splittable
# analog of the reference's key-range partitions)
VBUCKETS = 64


class ColumnTable:
    def __init__(self, name: str, schema: Schema, key_columns: list[str],
                 shards: int = 1, portion_rows: int = 1 << 20,
                 partition_by: Optional[list[str]] = None):
        if not key_columns:
            raise ValueError("column tables need a primary key")
        for k in key_columns:
            if not schema.has(k):
                raise ValueError(f"unknown key column {k}")
        self.name = name
        self.schema = schema
        self.key_columns = key_columns
        self.partition_by = partition_by or [key_columns[0]]
        self.shards = [ColumnShard(schema, i, portion_rows) for i in range(shards)]
        self.buckets = [i % shards for i in range(VBUCKETS)]
        self.dictionaries: dict[str, Dictionary] = {
            c.name: Dictionary() for c in schema if c.dtype.is_string}
        # data_version: bumped on every commit — cached plans snapshot
        # dictionary domains, so the plan cache keys on (uid, data_version)
        # per referenced table (the compile-cache schema-version key of
        # `kqp_compile_service.cpp:411`). uid distinguishes drop/recreate.
        self.uid = next(_table_uids)
        self.data_version = 0
        # durability hook (ydb_tpu/storage/persist.Store); None = volatile
        self.store = None
        # row TTL (ttl.cpp analog): (column, days) — expired rows evict
        # through the portion-rewrite delete path (engine.run_ttl)
        self.ttl = None

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    @property
    def num_rows(self) -> int:
        return sum(s.num_rows for s in self.shards)

    # -- write path -------------------------------------------------------

    def _route(self, block: HostBlock) -> np.ndarray:
        col = self.partition_by[0]
        cd = block.columns[col]
        b = self._bucket_of(cd.data)
        return np.asarray(self.buckets, np.int64)[b]

    @staticmethod
    def _bucket_of(data: np.ndarray) -> np.ndarray:
        h = splitmix64(np, data)
        return (h % np.uint64(VBUCKETS)).astype(np.int64)

    def write(self, block: HostBlock,
              tx: Optional[int] = None) -> list[tuple[int, int]]:
        """Stage rows into shards (WAL-logged when durable); returns
        [(shard_id, write_id)]. `tx`: owning open transaction (entries
        visible only through its tx_view until commit)."""
        staged: list[tuple[int, int, HostBlock]] = []
        if len(self.shards) == 1:
            staged.append((0, self.shards[0].write(block, tx), block))
        else:
            dest = self._route(block)
            for sid in range(len(self.shards)):
                idx = np.nonzero(dest == sid)[0]
                if len(idx):
                    blk = block.take(idx)
                    staged.append((sid, self.shards[sid].write(blk, tx),
                                   blk))
        if tx is not None:
            # staged writes grow shared dictionaries and change what the
            # owning tx's snapshot sees — cached plans must re-fingerprint
            self.data_version += 1
        if self.store is not None:
            for sid, wid, blk in staged:
                self.store.wal_write(self.name, sid, wid, blk, tx=tx)
        return [(sid, wid) for (sid, wid, _b) in staged]

    def rollback(self, writes: list[tuple[int, int]]) -> None:
        """Drop staged-but-uncommitted writes (interactive tx abort)."""
        by_shard: dict[int, list[int]] = {}
        for sid, wid in writes:
            by_shard.setdefault(sid, []).append(wid)
        for sid, wids in by_shard.items():
            self.shards[sid].rollback(wids)
            if self.store is not None:
                self.store.wal_abort(self.name, sid, wids)
        self.data_version += 1

    def commit(self, writes: list[tuple[int, int]],
               version: WriteVersion, deletes: Optional[list] = None) -> None:
        """Commit staged writes and/or MVCC delete marks ATOMICALLY:
        `deletes` = [(shard, portion, row indices)]. One intent-journal
        record covers both — a crash mid-commit heals to all-or-nothing
        (an UPDATE is delete marks + new rows; losing one half would be
        a data-losing pure delete or a duplicating pure insert)."""
        by_shard: dict[int, list[int]] = {}
        for sid, wid in writes:
            by_shard.setdefault(sid, []).append(wid)
        hits = deletes or []
        if self.store is not None and (by_shard or hits):
            # durable FIRST: the in-memory state below must never be
            # acknowledged unless it can be recovered
            self.store.commit_table(
                self.name, by_shard, version,
                deletes=[(s.shard_id, p.id, [int(r) for r in rows])
                         for (s, p, rows) in hits])
        for sid, wids in by_shard.items():
            self.shards[sid].commit(wids, version)
        for (_shard, portion, rows) in hits:
            portion.add_delete(rows, version=version)
        self.data_version += 1
        if self.store is not None:
            self.store.save_dictionaries(self)
            self.store.save_state(version.plan_step)

    # -- MVCC deletes (transactional column DML) ---------------------------

    def apply_deletes(self, hits: list, version: WriteVersion) -> int:
        """Commit delete marks: `hits` = [(shard, portion, row indices)].
        Historical snapshots keep seeing the rows (time travel preserved —
        the r3 portion-rewrite path destroyed it)."""
        hits = [h for h in hits if len(h[2])]
        if not hits:
            return 0                   # no-op: no bump, no WAL — a match-
        #                                nothing DELETE must not abort
        #                                concurrent optimistic txs
        self.commit([], version, deletes=hits)
        return sum(len(rows) for (_s, _p, rows) in hits)

    def stage_deletes(self, hits: list, tx: int) -> list:
        """Stage delete marks for an open tx (visible only through its
        tx_view); returns handles for commit/rollback."""
        handles = []
        for (shard, portion, rows) in hits:
            if not len(rows):
                continue
            handles.append((shard, portion,
                            portion.add_delete(rows, tx=tx)))
        if handles:
            self.data_version += 1   # own snapshot changes; re-fingerprint
        return handles

    def rollback_deletes(self, handles: list) -> None:
        if not handles:
            return
        for (_shard, portion, mark) in handles:
            portion.drop_delete(mark)
        self.data_version += 1

    def indexate(self, watermark: Optional[int] = None,
                 compact: bool = True) -> int:
        """Background indexation across shards (persists portion sets),
        followed by the compaction policy check — the background-controller
        analog (`columnshard_impl.h` background changes): steady small
        inserts must not accumulate unbounded small portions. `watermark`:
        see `ColumnShard.compact` (snapshot safety)."""
        made = 0
        for s in self.shards:
            n = s.indexate()
            merged = s.compact(watermark) if compact else 0
            made += n
            if self.store is not None and (n or merged):
                self.store.save_indexation(self, s)
        if self.store is not None and made:
            self.store.compact_intents(self)
        return made

    def compact(self, watermark: Optional[int] = None) -> int:
        """Compaction across shards (persists the rewritten portion sets)."""
        merged = 0
        for s in self.shards:
            n = s.compact(watermark)
            merged += n
            if self.store is not None and n:
                self.store.save_indexation(self, s)
        return merged

    # -- shard split / merge -----------------------------------------------

    def split_shard(self, sid: int) -> bool:
        """Split a hot/large shard: half its routing buckets move to a new
        shard and every portion's rows redistribute by bucket — the
        SchemeShard split trigger (`schemeshard__table_stats.cpp`)
        collapsed onto hash-bucket routing. Readers see the swap
        atomically (one shards-list rebind of copy-on-write shard
        objects); versions are preserved, so MVCC snapshots are unmoved.

        Returns False when the shard cannot split yet (single bucket,
        pending uncommitted inserts, or live delete marks — fold first)."""
        from ydb_tpu.storage.portion import Portion
        shard = self.shards[sid]
        mine = [b for b, s in enumerate(self.buckets) if s == sid]
        if len(mine) < 2 or any(e.committed_version is None
                                for e in shard.inserts):
            return False
        if any(p.deletes for p in shard.portions):
            return False               # marks hold row indices; fold first
        shard.indexate()               # committed inserts -> portions
        moving = set(mine[len(mine) // 2:])
        new_sid = len(self.shards)
        keep_shard = ColumnShard(self.schema, sid, shard.portion_rows)
        keep_shard._next_write_id = shard._next_write_id
        new_shard = ColumnShard(self.schema, new_sid, shard.portion_rows)
        col = self.partition_by[0]
        for p in shard.portions:
            b = self._bucket_of(p.block.columns[col].data)
            mv = np.isin(b, list(moving))
            if not mv.any():
                keep_shard.portions.append(p)      # untouched: same object
                continue
            stay = np.nonzero(~mv)[0]
            go = np.nonzero(mv)[0]
            if len(stay):
                keep_shard.portions.append(
                    Portion.from_block(p.block.take(stay), p.version))
            child = Portion.from_block(p.block.take(go), p.version)
            # crash-recovery marker: while the parent portion still exists
            # in the keep shard's manifest, these children are NOT yet
            # authoritative — load() drops them (split is all-or-nothing)
            child.split_src = p.id
            new_shard.portions.append(child)
        new_buckets = [new_sid if b in moving else s
                       for b, s in enumerate(self.buckets)]
        # ONE rebind each: lock-free readers see old or new state whole
        self.buckets = new_buckets
        self.shards = self.shards[:sid] + [keep_shard] \
            + self.shards[sid + 1:] + [new_shard]
        self.data_version += 1
        if self.store is not None:
            # durable ORDER is the crash-safety argument:
            # 1. the new shard's children land (additive; parents still
            #    authoritative → a crash here rolls the split back),
            # 2. the catalog learns the new shard count + bucket map,
            # 3. the keep shard's purge removes the parents — from here
            #    the children are authoritative.
            self.store.save_indexation(self, new_shard)
            if getattr(self, "catalog", None) is not None:
                self.store.save_catalog(self.catalog)
            self.store.save_indexation(self, keep_shard)
        return True

    def merge_last_shard(self) -> bool:
        """Merge the last shard into the one owning the fewest rows:
        whole portions move (reads scan every shard; routing only places
        new writes), its buckets reassign, and the shard list shrinks."""
        if len(self.shards) < 2:
            return False
        src = self.shards[-1]
        if any(e.committed_version is None for e in src.inserts):
            return False
        src.indexate()
        sid = src.shard_id
        target = min(range(len(self.shards) - 1),
                     key=lambda i: self.shards[i].num_rows)
        tgt = self.shards[target]
        merged = ColumnShard(self.schema, target, tgt.portion_rows)
        merged._next_write_id = max(tgt._next_write_id,
                                    src._next_write_id)
        merged.portions = tgt.portions + src.portions
        merged.inserts = list(tgt.inserts)
        self.buckets = [target if s == sid else s for s in self.buckets]
        self.shards = self.shards[:target] + [merged] \
            + self.shards[target + 1:-1]
        self.data_version += 1
        if self.store is not None:
            # moved portions keep their ids, so until the source dir is
            # dropped they exist in BOTH manifests — load() dedups by
            # portion id, making every crash window read-consistent
            self.store.save_indexation(self, merged)
            if getattr(self, "catalog", None) is not None:
                self.store.save_catalog(self.catalog)
            self.store.drop_shard_dir(self.name, sid)
        return True

    def maybe_split(self, threshold_rows: int) -> bool:
        """Auto-split check (called at commit points): split the biggest
        shard once it crosses the threshold."""
        if not threshold_rows:
            return False
        sid = max(range(len(self.shards)),
                  key=lambda i: self.shards[i].num_rows)
        if self.shards[sid].num_rows <= threshold_rows:
            return False
        return self.split_shard(sid)

    # -- schema evolution (ALTER TABLE) ------------------------------------

    def add_column(self, col) -> None:
        """ADD COLUMN: existing portions/staged blocks gain an all-null
        column in memory; on-disk portion files stay untouched (the blob
        reader synthesizes nulls for columns a portion predates — the
        per-portion schema-versioning stance of the reference's
        ColumnShard)."""
        from ydb_tpu.core.block import ColumnData
        self.schema = self.schema.extend([col])
        if col.dtype.is_string:
            self.dictionaries[col.name] = Dictionary()

        def patch(block: HostBlock) -> HostBlock:
            # string nulls are code -1 (0 would index an empty dictionary)
            fill = -1 if col.dtype.is_string else 0
            data = np.full(block.length, fill, dtype=col.dtype.np)
            cd = ColumnData(data, np.zeros(block.length, bool),
                            self.dictionaries.get(col.name))
            return HostBlock(block.schema.extend([col]),
                             {**block.columns, col.name: cd}, block.length)

        for s in self.shards:
            s.schema = self.schema
            for p in s.portions:
                p.block = patch(p.block)
            for e in s.inserts:
                e.block = patch(e.block)
        self.data_version += 1

    def drop_column(self, name: str) -> None:
        """DROP COLUMN: stripped from memory AND from on-disk blobs (a
        later re-ADD of the same name must see nulls, not stale bytes)."""
        self.schema = Schema([c for c in self.schema.columns
                              if c.name != name])
        self.dictionaries.pop(name, None)

        def strip(block: HostBlock) -> HostBlock:
            if name not in block.columns:
                return block
            cols = {n: cd for n, cd in block.columns.items() if n != name}
            return HostBlock(
                Schema([c for c in block.schema.columns if c.name != name]),
                cols, block.length)

        for s in self.shards:
            s.schema = self.schema
            for p in s.portions:
                p.block = strip(p.block)
                p.stats.pop(name, None)
            for e in s.inserts:
                e.block = strip(e.block)
            if self.store is not None:
                self.store.rewrite_shard_blobs(self, s)
        self.data_version += 1

    def bulk_upsert(self, df, version: WriteVersion) -> int:
        """Ingest a pandas DataFrame (BulkUpsert analog): write+commit+indexate."""
        block = HostBlock.from_pandas(df, schema=self.schema,
                                      dictionaries=self.dictionaries)
        writes = self.write(block)
        self.commit(writes, version)
        self.indexate()
        return block.length

    # -- read path --------------------------------------------------------

    def scan_shard(self, shard_id: int, columns: list[str],
                   snapshot: Snapshot = MAX_SNAPSHOT,
                   prune_predicates: Optional[list[tuple]] = None,
                   block_rows: Optional[int] = None) -> Iterator[HostBlock]:
        return self.shards[shard_id].scan(columns, snapshot,
                                          prune_predicates, block_rows)
