"""MVCC versions — the (plan step, tx id) pair.

Mirrors the reference's snapshot model (`ydb/core/tx/columnshard`: writes are
committed at a coordinator-assigned plan step; scans read "as of" a snapshot
`TSnapshot{PlanStep, TxId}`). The coordinator/mediator machinery lives in
ydb_tpu/tx (`tx/coordinator.py` plan-step allocation, `tx/session.py`
interactive transactions); storage only orders versions.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import total_ordering
from typing import Optional


@total_ordering
@dataclass(frozen=True)
class WriteVersion:
    plan_step: int
    tx_id: int

    def __lt__(self, other: "WriteVersion") -> bool:
        return (self.plan_step, self.tx_id) < (other.plan_step, other.tx_id)


@dataclass(frozen=True)
class Snapshot:
    plan_step: int
    tx_id: int
    # an open interactive transaction reading its OWN uncommitted writes:
    # storage makes entries tagged with this tx id visible in addition to
    # everything the (plan_step, tx_id) watermark includes (the DataShard
    # "immediate tx sees its accumulated effects" semantics)
    tx_view: Optional[int] = None

    def includes(self, v: WriteVersion) -> bool:
        return (v.plan_step, v.tx_id) <= (self.plan_step, self.tx_id)


MAX_SNAPSHOT = Snapshot(2**62, 2**62)
