"""Durable storage: on-disk portions + write-ahead insert log + recovery.

The reference persists every byte through the LocalDB redo log + snapshot
boot over BlobStorage (`ydb/core/tablet_flat/flat_executor.h:320`,
`flat_boot_*.h`); ColumnShard additionally owns an insert-table → portions
lifecycle (`ydb/core/tx/columnshard/engines/insert_table/`). The TPU build
keeps that shape but stores straight to the local filesystem (BlobStorage's
erasure/replication layer is a separate concern):

    <root>/
      catalog.json                   table metas (schema, pk, sharding)
      state.json                     last committed plan step
      <table>/
        dicts.json                   per-column string dictionaries
        shard_<i>/
          wal.bin                    insert log: CRC-framed write/commit
          wal_<wid>.ydbp             staged insert block (columnar)
          portion_<id>.ydbp          immutable indexed portion
          manifest.json              live portions + wal high-water mark

Crash consistency: json files go through write-tmp + atomic rename; the
WAL is append-only with per-record flush. Indexation order is (1) portion
files, (2) manifest rename (with ``wal_consumed_through`` = the highest
write id baked into portions), (3) WAL truncate — a crash between (2) and
(3) is healed at boot by skipping replay of consumed write ids.

Recovery (`Store.load`, the `flat_boot_misc.cpp` analog): read catalog +
dictionaries, load portion files, then replay the WAL — uncommitted writes
re-stage, committed-but-unindexed writes become visible inserts again.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from ydb_tpu.core.block import HostBlock
from ydb_tpu.core.dictionary import Dictionary
from ydb_tpu.core.dtypes import DType, Kind
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.storage.mvcc import WriteVersion


def _atomic_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str, default=None):
    if not os.path.exists(path):
        return default
    with open(path) as f:
        return json.load(f)


# blob + WAL IO: CRC-framed single format, native C++ fast path with a
# byte-identical numpy fallback (ydb_tpu/storage/blobfile.py,
# ydb_tpu/native/blobio.cpp)
from ydb_tpu.storage import blobfile as B


class Store:
    """Filesystem persistence for a catalog of column tables.

    `replica`: optional mirror sink (`cluster/replica.py`). Every durable
    TABLE-STORAGE mutation (catalog/state/dicts json, WAL appends and
    rewrites, portion blobs, drops) ships SYNCHRONOUSLY after the local
    write — an acknowledged commit exists on both sides, so a dead
    primary loses no table data (mirror-group v1,
    `blobstorage_grouptype.cpp` analog). Scope note: topics/changefeed
    state and the audit log are engine-level files that do NOT route
    through the Store yet — they are not mirrored."""

    def __init__(self, root: str, replica=None):
        self.root = root
        self.replica = replica
        os.makedirs(root, exist_ok=True)

    # -- replica shipping primitives ---------------------------------------

    def _ship(self, kind: str, path: str, data=None, **kw) -> None:
        if self.replica is None:
            return
        op = {"op": kind, "path": os.path.relpath(path, self.root), **kw}
        if data is not None:
            op["data"] = data
        self.replica.ship(op)

    def _json(self, path: str, obj) -> None:
        _atomic_json(path, obj)
        self._ship("json", path, obj)

    def _wal_app(self, path: str, rec: dict, sync: bool = True) -> None:
        B.wal_append(path, rec, sync=sync)
        self._ship("wal_append", path, rec, sync=sync)

    def _wal_rw(self, path: str, recs: list) -> None:
        B.wal_rewrite(path, recs)
        self._ship("wal_rewrite", path, recs)

    def _blob(self, path: str, block) -> None:
        B.write_portion(path, block)
        if self.replica is not None:
            import base64
            with open(path, "rb") as f:
                self._ship("put_b64", path,
                           base64.b64encode(f.read()).decode())

    def _unlink(self, path: str) -> None:
        os.unlink(path)
        self._ship("unlink", path)

    def _rmtree(self, path: str) -> None:
        import shutil
        shutil.rmtree(path)
        self._ship("rmtree", path)

    def sync_replica(self) -> int:
        """Full initial sync: ship EVERY existing file to the standby —
        required when a replica attaches to a store that already holds
        data (delta shipping alone would send manifests referencing
        portion blobs the standby never received). Skipped when the
        standby already holds a catalog (a routine primary restart must
        not re-ship the whole store). Returns files shipped."""
        if self.replica is None:
            return 0
        probe = getattr(self.replica, "has_catalog", None)
        if probe is not None and probe():
            return 0
        import base64
        n = 0
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                if fn.endswith(".tmp"):
                    continue
                path = os.path.join(dirpath, fn)
                with open(path, "rb") as f:
                    self._ship("put_b64", path,
                               base64.b64encode(f.read()).decode())
                n += 1
        return n

    # -- paths -------------------------------------------------------------

    def _tdir(self, table: str) -> str:
        return os.path.join(self.root, table)

    def _sdir(self, table: str, shard: int) -> str:
        return os.path.join(self.root, table, f"shard_{shard}")

    # -- catalog -----------------------------------------------------------

    def save_catalog(self, catalog) -> None:
        metas = {}
        # list() snapshot: lock-free SELECTs register/drop transient CTE
        # temps in the tables dict concurrently; transients never persist
        for name, t in list(catalog.tables.items()):
            if getattr(t, "transient", False):
                continue
            metas[name] = {
                "schema": [[c.name, c.dtype.kind.value, c.dtype.nullable]
                           for c in t.schema],
                "key_columns": t.key_columns,
                "partition_by": t.partition_by,
                "shards": len(t.shards),
                "buckets": list(getattr(t, "buckets", [])),
                "portion_rows": t.shards[0].portion_rows,
                "store_kind": getattr(t, "store_kind", "column"),
                "indexes": dict(getattr(t, "indexes", {})),
                "ttl": list(t.ttl) if getattr(t, "ttl", None) else None,
                "serial_next": dict(getattr(t, "serial_next", {}) or {}),
            }
        self._json(os.path.join(self.root, "catalog.json"),
                   {"tables": metas})

    def save_state(self, last_plan_step: int) -> None:
        self._json(os.path.join(self.root, "state.json"),
                   {"last_plan_step": last_plan_step})

    def load_state(self) -> int:
        return _read_json(os.path.join(self.root, "state.json"),
                          {"last_plan_step": 0})["last_plan_step"]

    def create_table(self, table) -> None:
        if getattr(table, "store_kind", "column") == "row":
            os.makedirs(self._tdir(table.name), exist_ok=True)
        else:
            for s in table.shards:
                os.makedirs(self._sdir(table.name, s.shard_id),
                            exist_ok=True)
        self.save_dictionaries(table)

    def drop_table(self, name: str) -> None:
        if os.path.isdir(self._tdir(name)):
            self._rmtree(self._tdir(name))

    def save_dictionaries(self, table) -> None:
        vals = {col: list(d.values_array())
                for col, d in table.dictionaries.items()}
        self._json(os.path.join(self._tdir(table.name), "dicts.json"), vals)

    # -- WAL ---------------------------------------------------------------

    def row_wal_append(self, table: str, ops: list,
                       version: WriteVersion) -> None:
        """Mutation log for row tables (the DataShard redo-log analog)."""
        def native(v):
            if hasattr(v, "item"):
                return v.item()
            return v

        rec = {"plan_step": version.plan_step, "tx_id": version.tx_id,
               "ops": [[kind, {c: native(v) for c, v in vals.items()}]
                       for (kind, vals) in ops]}
        self._wal_app(os.path.join(self._tdir(table), "rowwal.bin"), rec)

    def wal_write(self, table: str, shard: int, wid: int,
                  block: HostBlock, tx=None) -> None:
        sdir = self._sdir(table, shard)
        self._blob(os.path.join(sdir, f"wal_{wid}.ydbp"), block)
        rec = {"op": "write", "wid": wid}
        if tx is not None:
            rec["tx"] = tx     # boot discards writes of txs that died open
        self._wal_append(sdir, rec)

    def wal_commit(self, table: str, shard: int, wids: list,
                   version: WriteVersion) -> None:
        self._wal_append(self._sdir(table, shard),
                         {"op": "commit", "wids": wids,
                          "plan_step": version.plan_step,
                          "tx_id": version.tx_id})

    def commit_table(self, table: str, shard_wids: dict,
                     version: WriteVersion,
                     deletes: Optional[list] = None) -> None:
        """Atomic multi-part commit: an INTENT record covering every
        shard's write ids AND delete marks lands (fsynced) BEFORE the
        per-shard records, and a DONE record after. A crash between the
        records is healed at boot by re-applying intents without a
        matching DONE — the coordinator plan-step + readset-confirmation
        shape of the reference, collapsed to one durable journal
        (`ydb/core/tx/coordinator/coordinator__plan_step.cpp`).
        `deletes`: [(shard_id, portion_id, row index list)] — an UPDATE's
        marks and re-inserts must never be durable separately."""
        deletes = deletes or []
        need_intent = len(shard_wids) > 1 \
            or (bool(deletes) and bool(shard_wids)) or len(deletes) > 1
        if need_intent:
            self._intent_append(table, {
                "op": "intent", "plan_step": version.plan_step,
                "tx_id": version.tx_id,
                "shards": {str(sid): wids
                           for sid, wids in shard_wids.items()},
                "deletes": [[int(sid), int(pid), rows]
                            for (sid, pid, rows) in deletes]})
        for sid, wids in shard_wids.items():
            self.wal_commit(table, sid, wids, version)
        for (sid, pid, rows) in deletes:
            # always fsynced: compact_intents may drop a delete-bearing
            # intent before the next manifest persists the marks
            self.wal_delete(table, sid, pid, version, rows)
        if need_intent:
            # losing the DONE is harmless (healing re-applies the commit
            # idempotently) — skip the second fsync on the commit path
            self._intent_append(table, {
                "op": "done", "plan_step": version.plan_step,
                "tx_id": version.tx_id}, sync=False)

    def _intent_append(self, table: str, rec: dict,
                       sync: bool = True) -> None:
        self._wal_app(os.path.join(self._tdir(table), "commits.bin"), rec,
                      sync=sync)

    @staticmethod
    def _open_intents(path: str) -> dict:
        """commits.bin fold: {(plan_step, tx_id): intent rec} for every
        intent without a matching DONE (shared by recovery healing and
        compaction — they must never disagree on this)."""
        out: dict = {}
        for rec in B.wal_replay(path):
            key = (rec["plan_step"], rec["tx_id"])
            if rec["op"] == "intent":
                out[key] = rec
            else:
                out.pop(key, None)
        return out

    def compact_intents(self, table) -> None:
        """Drop intents whose write ids no longer exist anywhere (fully
        indexed) — called from indexation so commits.bin stays bounded."""
        path = os.path.join(self._tdir(table.name), "commits.bin")
        if not os.path.exists(path):
            return
        pending = {(s.shard_id, e.write_id)
                   for s in table.shards for e in s.inserts}
        keep = []
        for rec in self._open_intents(path).values():
            if any((int(sid), wid) in pending
                   for sid, wids in rec["shards"].items()
                   for wid in wids):
                keep.append(rec)
        self._wal_rw(path, keep)

    def wal_delete(self, table: str, shard: int, portion_id: int,
                   version: WriteVersion, rows, sync: bool = True) -> None:
        """Durable MVCC delete mark (fsynced before the statement acks,
        unless an intent record already covers the outcome)."""
        self._wal_app(os.path.join(self._sdir(table, shard), "wal.bin"),
                      {"op": "delete", "portion": portion_id,
                       "plan_step": version.plan_step,
                       "tx_id": version.tx_id,
                       "rows": [int(r) for r in rows]}, sync=sync)

    def wal_abort(self, table: str, shard: int, wids: list) -> None:
        self._wal_append(self._sdir(table, shard),
                         {"op": "abort", "wids": wids})

    def _wal_append(self, sdir: str, rec: dict) -> None:
        self._wal_app(os.path.join(sdir, "wal.bin"), rec)

    # -- portions ----------------------------------------------------------

    def save_indexation(self, table, shard) -> None:
        """Persist a shard's portion set after indexate()/compact() and
        truncate the consumed WAL prefix."""
        sdir = self._sdir(table.name, shard.shard_id)
        os.makedirs(sdir, exist_ok=True)   # split-born shards are new dirs
        live = []
        for p in shard.portions:
            path = os.path.join(sdir, f"portion_{p.id}.ydbp")
            if not os.path.exists(path):
                self._blob(path, p.block)
            entry = {"id": p.id, "rows": p.num_rows,
                     "plan_step": p.version.plan_step,
                     "tx_id": p.version.tx_id}
            if getattr(p, "split_src", None) is not None:
                # split child: authoritative only once the parent portion
                # is gone from its shard's manifest (crash-window marker)
                entry["split_src"] = p.split_src
            committed_marks = [m for m in p.deletes
                               if m.version is not None]
            if committed_marks:
                entry["deletes"] = [
                    {"plan_step": m.version.plan_step,
                     "tx_id": m.version.tx_id,
                     "rows": [int(r) for r in m.rows]}
                    for m in committed_marks]
            live.append(entry)
        # a write id is replayable iff still pending here, or newer than
        # anything this manifest knew about (a single high-water mark would
        # be wrong when an old uncommitted write outlives newer consumed
        # ones)
        self._json(os.path.join(sdir, "manifest.json"),
                   {"portions": live,
                      "pending_wids": [e.write_id for e in shard.inserts],
                      "max_wid": shard._next_write_id - 1})
        # drop orphaned portion files (compaction) and consumed wal blocks
        keep = {f"portion_{e['id']}.ydbp" for e in live}
        still = {f"wal_{e.write_id}.ydbp" for e in shard.inserts}
        for fn in os.listdir(sdir):
            if fn.startswith("portion_") and fn.endswith(".ydbp") \
                    and fn not in keep:
                self._unlink(os.path.join(sdir, fn))
            if fn.startswith("wal_") and fn.endswith(".ydbp") \
                    and fn not in still:
                self._unlink(os.path.join(sdir, fn))
        # rewrite the WAL with only still-pending entries
        recs = []
        for e in shard.inserts:
            recs.append({"op": "write", "wid": e.write_id})
            if e.committed_version is not None:
                recs.append({"op": "commit", "wids": [e.write_id],
                             "plan_step": e.committed_version.plan_step,
                             "tx_id": e.committed_version.tx_id})
        self._wal_rw(os.path.join(sdir, "wal.bin"), recs)

    def drop_shard_dir(self, table: str, shard_id: int) -> None:
        """Remove a merged-away shard's directory (portions already
        persisted under the target shard)."""
        sdir = os.path.join(self._tdir(table), f"shard_{shard_id}")
        if os.path.isdir(sdir):
            self._rmtree(sdir)

    def rewrite_row_wal(self, table) -> None:
        """Compact a row table's mutation log to its current committed
        state (DROP COLUMN: replay must not resurrect dropped values).
        One upsert record per live pk, original write versions kept."""
        recs = []
        names = table.schema.names
        for pk in sorted(table.rows):
            latest = None
            for (ver, vals, _tx) in table.rows[pk]:
                if ver is not None:
                    latest = (ver, vals)
            if latest is None or latest[1] is None:
                continue               # never committed, or deleted
            ver, vals = latest
            row = {}
            for c, v in zip(names, vals):
                if v is not None and table.schema.dtype(c).is_string:
                    v = str(table.dictionaries[c].values_array()[v])
                row[c] = v
            recs.append({"plan_step": ver.plan_step, "tx_id": ver.tx_id,
                         "ops": [["replace", row]]})
        self._wal_rw(os.path.join(self._tdir(table.name), "rowwal.bin"),
                     recs)

    def rewrite_shard_blobs(self, table, shard) -> None:
        """Force-rewrite every blob of a shard (DROP COLUMN: stale bytes
        must not resurface if the name is re-added). Atomic per file."""
        sdir = self._sdir(table.name, shard.shard_id)
        for p in shard.portions:
            self._blob(os.path.join(sdir, f"portion_{p.id}.ydbp"), p.block)
        for e in shard.inserts:
            self._blob(
                os.path.join(sdir, f"wal_{e.write_id}.ydbp"), e.block)

    # -- recovery ----------------------------------------------------------

    def load(self):
        """Rebuild a Catalog from disk (the flat_boot analog). Returns
        (catalog, last_plan_step)."""
        from ydb_tpu.scheme.catalog import Catalog
        from ydb_tpu.storage.portion import Portion, _portion_ids
        from ydb_tpu.storage.shard import InsertEntry

        catalog = Catalog(store=None)      # attach after load (no re-writes)
        # refuse stores written by the pre-binary-format layout: replaying
        # wal.bin over a tree that only has *.jsonl/*.npz would silently
        # come up empty (acked writes lost)
        for dirpath, _dirs, files in os.walk(self.root):
            legacy = [f for f in files
                      if f in ("wal.jsonl", "rowwal.jsonl")
                      or f.endswith(".npz")]
            if legacy:
                raise RuntimeError(
                    f"{dirpath} holds legacy-format files {legacy}; this "
                    "build reads the CRC-framed wal.bin/.ydbp layout only")
        # last_plan_step must cover every version replayed from disk:
        # state.json can lag a crash that landed between the fsynced
        # wal_commit and save_state (committed data would be invisible and
        # plan steps would be re-granted)
        seen_step = 0
        meta = _read_json(os.path.join(self.root, "catalog.json"),
                          {"tables": {}})
        for name, tm in meta["tables"].items():
            schema = Schema([Column(n, DType(Kind(k), nullable))
                             for (n, k, nullable) in tm["schema"]])
            t = catalog.create_table(
                name, schema, tm["key_columns"], shards=tm["shards"],
                portion_rows=tm["portion_rows"],
                partition_by=tm["partition_by"],
                store_kind=tm.get("store_kind", "column"))
            dvals = _read_json(os.path.join(self._tdir(name), "dicts.json"),
                               {})
            for col, vals in dvals.items():
                d = Dictionary()
                d.encode(list(vals))
                t.dictionaries[col] = d
            for c in schema:
                if c.dtype.is_string and c.name not in t.dictionaries:
                    t.dictionaries[c.name] = Dictionary()
            if tm.get("buckets"):
                t.buckets = [int(b) for b in tm["buckets"]]
            if tm.get("ttl"):
                t.ttl = (tm["ttl"][0], int(tm["ttl"][1]))
            if tm.get("serial_next"):
                t.serial_next = {c: int(n)
                                 for c, n in tm["serial_next"].items()}

            if tm.get("store_kind", "column") == "row":
                wal = os.path.join(self._tdir(name), "rowwal.bin")
                # arm the CDC replay log: the engine re-emits these
                # through the table's changefeed after topics load, so a
                # topic tail torn off by a crash between the row-WAL
                # fsync and the topic append heals (seq dedup drops the
                # already-published prefix)
                t._replay_log = []
                for rec in B.wal_replay(wal):
                    ver = WriteVersion(rec["plan_step"], rec["tx_id"])
                    ops = [(kind, vals) for (kind, vals) in rec["ops"]]
                    t.apply(ops, ver, durable=False, strict=False)
                    seen_step = max(seen_step, ver.plan_step)
                for iname, col in tm.get("indexes", {}).items():
                    t.create_index(iname, col)   # backfills from rows
                t.store = self
                continue

            # open intents first: a tx-tagged write whose own shard
            # lacks the commit record may still be covered by a torn
            # multi-shard commit — it must replay, not roll back
            open_intents = self._open_intents(
                os.path.join(self._tdir(name), "commits.bin"))
            intent_wids: dict = {}
            for rec in open_intents.values():
                for sid, wids in rec["shards"].items():
                    intent_wids.setdefault(int(sid), set()).update(wids)
            loaded_pids: set = set()     # merge crash window: a moved
            split_children: list = []    # portion can be in two manifests
            for shard in t.shards:
                sdir = self._sdir(name, shard.shard_id)
                man = _read_json(os.path.join(sdir, "manifest.json"),
                                 {"portions": [], "pending_wids": None,
                                  "max_wid": 0})
                for e in man["portions"]:
                    if e["id"] in loaded_pids:
                        continue         # duplicate from a torn merge
                    loaded_pids.add(e["id"])
                    block = B.read_portion(
                        os.path.join(sdir, f"portion_{e['id']}.ydbp"),
                        schema, t.dictionaries)
                    # restore the persisted id: a fresh one would alias a
                    # different portion_<id>.ydbp on the next indexation
                    p = Portion.from_block(
                        block, WriteVersion(e["plan_step"], e["tx_id"]),
                        id=e["id"])
                    for dm in e.get("deletes", []):
                        p.add_delete(np.array(dm["rows"], np.int64),
                                     version=WriteVersion(dm["plan_step"],
                                                          dm["tx_id"]))
                        seen_step = max(seen_step, dm["plan_step"])
                    if e.get("split_src") is not None:
                        p.split_src = e["split_src"]
                        split_children.append((shard, p))
                    shard.portions.append(p)
                    _portion_ids.ensure_above(e["id"])
                    seen_step = max(seen_step, e["plan_step"])
                # crash leftovers (portion written, manifest not) must not
                # be aliased by future ids either
                for fn in os.listdir(sdir):
                    if fn.startswith("portion_") and fn.endswith(".ydbp"):
                        _portion_ids.ensure_above(
                            int(fn[len("portion_"):-len(".ydbp")]))
                pending = man["pending_wids"]
                max_wid = man["max_wid"]

                def replayable(wid: int) -> bool:
                    if pending is None:      # no manifest yet: replay all
                        return True
                    return wid in pending or wid > max_wid

                staged: dict[int, InsertEntry] = {}
                recs = B.wal_replay(os.path.join(sdir, "wal.bin"))
                committed_wids = {wid for r in recs if r["op"] == "commit"
                                  for wid in r["wids"]}
                for rec in recs:
                    if rec["op"] == "write":
                        wid = rec["wid"]
                        if not replayable(wid):
                            continue       # baked into portions already
                        if rec.get("tx") is not None \
                                and wid not in committed_wids \
                                and wid not in intent_wids.get(
                                    shard.shard_id, ()):
                            # staged by a tx that died open: its commit
                            # can never arrive — implicit rollback at boot
                            continue
                        block = B.read_portion(
                            os.path.join(sdir, f"wal_{wid}.ydbp"),
                            schema, t.dictionaries)
                        staged[wid] = InsertEntry(block, wid)
                    elif rec["op"] == "commit":
                        ver = WriteVersion(rec["plan_step"], rec["tx_id"])
                        seen_step = max(seen_step, ver.plan_step)
                        for wid in rec["wids"]:
                            if wid in staged:
                                staged[wid].committed_version = ver
                    elif rec["op"] == "abort":
                        for wid in rec["wids"]:
                            staged.pop(wid, None)
                    elif rec["op"] == "delete":
                        # MVCC delete mark landed after the last manifest;
                        # duplicate application (manifest + WAL) is
                        # harmless — visibility unions row sets
                        ver = WriteVersion(rec["plan_step"], rec["tx_id"])
                        seen_step = max(seen_step, ver.plan_step)
                        for p in shard.portions:
                            if p.id == rec["portion"]:
                                p.add_delete(np.array(rec["rows"],
                                                      np.int64),
                                             version=ver)
                                break
                for wid in sorted(staged):
                    shard.inserts.append(staged[wid])
                    if staged[wid].committed_version:
                        shard.rows_written += staged[wid].block.length
                shard._next_write_id = max([max_wid] + list(staged)) + 1
            # split crash healing: a child portion whose PARENT still
            # exists (the keep-shard purge never landed) is not
            # authoritative — drop it; the split rolls back whole
            for (shard, child) in split_children:
                if child.split_src in loaded_pids:
                    shard.portions = [p for p in shard.portions
                                      if p is not child]
            # shard dirs beyond the catalog's count are crash leftovers of
            # a split that never reached its catalog save: children there
            # were just dropped (parents present); remove residue
            tdir = self._tdir(name)
            if os.path.isdir(tdir):
                for fn in os.listdir(tdir):
                    if fn.startswith("shard_"):
                        try:
                            k = int(fn[len("shard_"):])
                        except ValueError:
                            continue
                        if k >= len(t.shards):
                            import shutil
                            shutil.rmtree(os.path.join(tdir, fn),
                                          ignore_errors=True)
            # heal torn multi-shard commits: an INTENT without its DONE
            # means the crash hit between shard commit records — re-apply
            # the commit to every shard it covers (idempotent)
            for (ps, txid), rec in open_intents.items():
                ver = WriteVersion(ps, txid)
                seen_step = max(seen_step, ps)
                for sid, wids in rec["shards"].items():
                    sh = t.shards[int(sid)]
                    for e in sh.inserts:
                        if e.write_id in wids \
                                and e.committed_version is None:
                            e.committed_version = ver
                            e.tx = None
                            sh.rows_written += e.block.length
                # heal the commit's delete marks too (idempotent: the
                # mark union makes duplicate application harmless)
                for (sid, pid, rows) in rec.get("deletes", []):
                    sh = t.shards[int(sid)]
                    for p in sh.portions:
                        if p.id == pid:
                            p.add_delete(np.array(rows, np.int64),
                                         version=ver)
                            break
            # re-arm durability: post-recovery writes must persist too
            t.store = self
        # heal serial counters against data maxima: the catalog save can
        # lag a crash that landed after the row data was made durable
        for t in catalog.tables.values():
            serial = getattr(t, "serial_next", None)
            if not serial:
                continue
            for col in list(serial):
                if not t.schema.has(col):
                    serial.pop(col)   # column dropped after catalog save
                    continue
                mx = 0
                if getattr(t, "store_kind", "column") == "row":
                    ix = t.schema.names.index(col)
                    for chain in t.rows.values():
                        for (_v, vals, _tx) in chain:
                            if vals is not None and vals[ix] is not None:
                                mx = max(mx, int(vals[ix]))
                else:
                    for sh in t.shards:
                        for p in sh.portions:
                            st = p.stats.get(col)
                            if st is not None and st.max is not None:
                                mx = max(mx, int(st.max))
                        for e in sh.inserts:
                            d = e.block.columns[col].data
                            if len(d):
                                mx = max(mx, int(d.max()))
                serial[col] = max(serial[col], mx + 1)
        catalog.store = self
        return catalog, max(self.load_state(), seen_step)
