"""A single column shard: insert buffer → portions → compaction → scan.

Mirrors the reference ColumnShard's write/read lifecycle
(`ydb/core/tx/columnshard/columnshard_impl.h`):

  * writes land in an **insert table** of uncommitted blobs
    (`engines/insert_table/`), become visible at commit (plan step);
  * **indexation** turns committed inserts into immutable portions with
    stats (`engines/changes/indexation.cpp`);
  * **compaction** merges small portions (`general_compaction.cpp`);
  * **scan** iterates portions under an MVCC snapshot, prunes by stats,
    and hands blocks to the device program — the per-portion early-filter
    shape of `engines/reader/plain_reader/iterator/`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, Optional

import numpy as np

from ydb_tpu.core.block import HostBlock
from ydb_tpu.core.schema import Schema
from ydb_tpu.ops import ir
from ydb_tpu.storage.mvcc import MAX_SNAPSHOT, Snapshot, WriteVersion
from ydb_tpu.storage.portion import Portion, prune_by_range

DEFAULT_PORTION_ROWS = 1 << 20
COMPACT_MIN_PORTIONS = 8


@dataclass
class InsertEntry:
    block: HostBlock
    write_id: int
    committed_version: Optional[WriteVersion] = None
    tx: Optional[int] = None       # open interactive tx that staged this


class ColumnShard:
    def __init__(self, schema: Schema, shard_id: int = 0,
                 portion_rows: int = DEFAULT_PORTION_ROWS):
        self.schema = schema
        self.shard_id = shard_id
        self.portion_rows = portion_rows
        self.portions: list[Portion] = []
        self.inserts: list[InsertEntry] = []
        self._next_write_id = 1
        self.rows_written = 0

    # -- write path -------------------------------------------------------

    def write(self, block: HostBlock, tx: Optional[int] = None) -> int:
        """Stage an uncommitted insert; returns write id (InsertTable model)."""
        wid = self._next_write_id
        self._next_write_id += 1
        self.inserts.append(InsertEntry(block, wid, tx=tx))
        return wid

    def commit(self, write_ids: list[int], version: WriteVersion) -> None:
        for e in self.inserts:
            if e.write_id in write_ids:
                e.committed_version = version
                e.tx = None
                self.rows_written += e.block.length

    def rollback(self, write_ids: list[int]) -> None:
        self.inserts = [e for e in self.inserts
                        if e.write_id not in write_ids
                        or e.committed_version is not None]

    def indexate(self) -> int:
        """Background indexation: committed inserts → portions. Returns
        #portions.

        Concurrent-reader discipline: the portions list is extended in ONE
        rebind (atomic under the GIL) BEFORE the consumed inserts are
        removed in a second rebind; a reader between the two sees the rows
        in both places, and `scan_sources` dedups by the portions'
        `src_write_ids` — never zero copies, never two."""
        ready = [e for e in self.inserts if e.committed_version is not None]
        if not ready:
            return 0
        made = []
        # group by version so a portion has a single write version
        by_ver: dict[WriteVersion, list] = {}
        for e in ready:
            by_ver.setdefault(e.committed_version, []).append(e)
        for ver, entries in by_ver.items():
            wids = frozenset(e.write_id for e in entries)
            blocks = [e.block for e in entries]
            merged = HostBlock.concat(blocks) if len(blocks) > 1 else blocks[0]
            for start in range(0, merged.length, self.portion_rows):
                chunk = merged.slice(start, min(start + self.portion_rows,
                                                merged.length))
                p = Portion.from_block(chunk, ver)
                p.src_write_ids = wids
                made.append(p)
        consumed = {e.write_id for e in ready}
        self.portions = self.portions + made
        self.inserts = [e for e in self.inserts
                        if e.write_id not in consumed
                        or e.committed_version is None]
        return len(made)

    def compact(self, watermark: Optional[int] = None) -> int:
        """Merge small portions into full ones (`general_compaction.cpp`).

        The merged portion is stamped with the NEWEST version among its
        inputs, so only portions at or below `watermark` (the highest plan
        step no pinned snapshot is behind, `Coordinator.safe_watermark`)
        are eligible — every pinned reader stays at or past the merged
        version and sees identical data. Ad-hoc snapshots never registered
        with the coordinator keep only per-portion granularity (the
        reference tracks per-row versions inside portions — a later
        refinement here)."""
        folded = self._fold_deletes(watermark)
        small = [p for p in self.portions
                 if p.num_rows < self.portion_rows // 2
                 and not p.deletes      # freshly marked portions wait for
                 #                        their marks to pass the watermark
                 and (watermark is None
                      or p.version.plan_step <= watermark)]
        if len(small) < COMPACT_MIN_PORTIONS:
            return folded
        ids = {p.id for p in small}
        merged = HostBlock.concat([p.block for p in small])
        ver = max(p.version for p in small)
        new_portions = []
        src = frozenset().union(*(getattr(p, "src_write_ids", frozenset())
                                  for p in small))
        for start in range(0, merged.length, self.portion_rows):
            chunk = merged.slice(start,
                                 min(start + self.portion_rows,
                                     merged.length))
            p2 = Portion.from_block(chunk, ver)
            p2.src_write_ids = src
            new_portions.append(p2)
        # ONE rebind: a concurrent reader sees either the old set or the
        # new set — both contain the same rows for any snapshot at or
        # past the watermark (the eligibility gate above)
        self.portions = [p for p in self.portions
                         if p.id not in ids] + new_portions
        return len(small) + folded

    def _fold_deletes(self, watermark: Optional[int]) -> int:
        """Reclaim delete-marked rows: a portion whose every mark is
        committed at or below the watermark rewrites without the dead
        rows (new portion at the newest involved version) — TTL/DELETE
        must eventually free memory and disk, and every reader at or past
        the watermark sees identical data either way."""
        if watermark is None:
            return 0
        replaced, removed_ids = [], set()
        for p in self.portions:
            if not p.deletes or p.version.plan_step > watermark:
                continue
            if not all(m.version is not None
                       and m.version.plan_step <= watermark
                       for m in p.deletes):
                continue
            dead = np.unique(np.concatenate([m.rows for m in p.deletes]))
            ver = max([p.version] + [m.version for m in p.deletes])
            removed_ids.add(p.id)
            keep = np.setdiff1d(np.arange(p.num_rows, dtype=np.int64),
                                dead)
            if len(keep):
                p2 = Portion.from_block(p.block.take(keep), ver)
                p2.src_write_ids = getattr(p, "src_write_ids", frozenset())
                replaced.append(p2)
        if not removed_ids:
            return 0
        self.portions = [p for p in self.portions
                         if p.id not in removed_ids] + replaced
        return len(removed_ids)

    # -- read path --------------------------------------------------------

    @property
    def num_rows(self) -> int:
        return sum(p.num_rows for p in self.portions) + sum(
            e.block.length for e in self.inserts if e.committed_version)

    def scan_sources(self, snapshot: Snapshot = MAX_SNAPSHOT,
                     prune_predicates: Optional[list[tuple]] = None
                     ) -> tuple[list, list]:
        """(visible portions, visible committed-but-unindexed InsertEntry
        list) under the snapshot, after min/max pruning. Entries (not bare
        blocks) so callers can key device caches on stable write ids."""
        prune_predicates = prune_predicates or []
        # READ ORDER CONTRACT with indexate(): inserts FIRST, portions
        # second. Indexate appends portions before removing consumed
        # inserts, so a reader can see a row in both places (deduped by
        # covered write ids below) but never in neither. Reading portions
        # first would open exactly that missing-rows window.
        all_inserts = self.inserts           # one read: stable list object
        all_portions = self.portions
        portions = [
            p for p in all_portions
            if snapshot.includes(p.version)
            and not any(prune_by_range(p, c, op, v)
                        for (c, op, v) in prune_predicates)]
        # write ids already covered by a visible portion: during the
        # indexation window a reader can see an insert both places — the
        # portion wins (indexate's rebind-ordering contract)
        covered = set()
        for p in all_portions:
            if snapshot.includes(p.version):
                covered.update(getattr(p, "src_write_ids", ()))
        inserts = [e for e in all_inserts
                   if e.write_id not in covered
                   and ((e.committed_version
                         and snapshot.includes(e.committed_version))
                        or (e.committed_version is None and e.tx is not None
                            and e.tx == snapshot.tx_view))]
        return portions, inserts

    def scan(self, columns: list[str],
             snapshot: Snapshot = MAX_SNAPSHOT,
             prune_predicates: Optional[list[tuple]] = None,
             block_rows: Optional[int] = None) -> Iterator[HostBlock]:
        """Yield host blocks of ~block_rows under the snapshot.

        prune_predicates: [(col, op, value)] conjuncts for min/max pruning.
        """
        block_rows = block_rows or self.portion_rows
        pending: list[HostBlock] = []
        pending_rows = 0

        def flush():
            nonlocal pending, pending_rows
            if pending:
                out = HostBlock.concat(pending) if len(pending) > 1 else pending[0]
                pending, pending_rows = [], 0
                return out
            return None

        portions, insert_entries = self.scan_sources(snapshot,
                                                     prune_predicates)
        sources = [p.visible_block(snapshot) for p in portions] \
            + [e.block for e in insert_entries]

        for src in sources:
            blk = src.select(columns)
            pos = 0
            while pos < blk.length:
                take = min(block_rows - pending_rows, blk.length - pos)
                pending.append(blk.slice(pos, pos + take))
                pending_rows += take
                pos += take
                if pending_rows >= block_rows:
                    out = flush()
                    if out is not None:
                        yield out
        out = flush()
        if out is not None:
            yield out
