"""Topics: durable partitioned append logs + consumer offsets (+ CDC).

The reference's PersQueue is a partitioned persistent log tablet built on
the KeyValue tablet (`ydb/core/persqueue/pq_impl.h:32` TPersQueue :
NKeyValue::TKeyValueFlat, partition actors `partition.cpp`, consumer
read-offset state per partition) with exactly-once producer dedup by
(producer id, seq no). Change Data Capture emits DataShard row mutations
into such topics (`ydb/core/change_exchange/`).

This build keeps the same contracts on the storage substrate it already
has: a partition IS a CRC-framed WAL (`storage/blobfile.py` — the native
C++ framing layer), offsets are a JSON manifest, and CDC hooks the row
table's commit points so only COMMITTED mutations are published, in
commit order, tagged with their write version — the reference's
"changefeed sees the transaction's effects atomically" rule.

Messages are dicts (JSON-serializable); producers may pass `seq_no` for
exactly-once dedup per (producer, partition).
"""

from __future__ import annotations

import os
from typing import Optional

from ydb_tpu.storage import blobfile as B


class TopicPartition:
    def __init__(self, path: Optional[str]):
        import threading
        self.path = path               # None = volatile (no store)
        self.records: list = []        # [{offset, data, producer?, seq?}]
        self._producer_seq: dict = {}  # producer id -> last seq_no
        # producers append from concurrent session threads (and the
        # tracer sink): offset assignment + WAL append must be atomic
        self._mu = threading.Lock()
        if path is not None:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            for rec in B.wal_replay(path):
                self.records.append(rec)
                p, s = rec.get("producer"), rec.get("seq")
                if p is not None and s is not None:
                    self._producer_seq[p] = max(
                        self._producer_seq.get(p, -1), s)

    @property
    def end_offset(self) -> int:
        return len(self.records)

    def append(self, data, producer: Optional[str] = None,
               seq_no: Optional[int] = None) -> Optional[int]:
        """Returns the assigned offset, or None when deduplicated
        (exactly-once: seq_no at or below the producer's high-water)."""
        with self._mu:
            if producer is not None and seq_no is not None:
                if seq_no <= self._producer_seq.get(producer, -1):
                    return None
                self._producer_seq[producer] = seq_no
            rec = {"offset": len(self.records), "data": data}
            if producer is not None and seq_no is not None:
                rec["producer"] = producer
                rec["seq"] = seq_no
            self.records.append(rec)
            if self.path is not None:
                B.wal_append(self.path, rec)
            return rec["offset"]

    def read(self, offset: int, limit: int = 100) -> list:
        return self.records[offset:offset + limit]


class Topic:
    def __init__(self, name: str, partitions: int,
                 root: Optional[str] = None):
        self.name = name
        self.root = root
        self.partitions = [
            TopicPartition(None if root is None
                           else os.path.join(root, f"part_{i}", "log.bin"))
            for i in range(partitions)]
        # committed read offsets: consumer -> [offset per partition]
        self.offsets: dict[str, list] = {}
        self._offsets_path = None if root is None \
            else os.path.join(root, "offsets.json")
        if self._offsets_path and os.path.exists(self._offsets_path):
            import json
            with open(self._offsets_path) as f:
                self.offsets = {c: list(v)
                                for c, v in json.load(f).items()}

    def _route(self, key) -> int:
        if isinstance(key, int):
            return key % len(self.partitions)
        import zlib
        return zlib.crc32(str(key).encode()) % len(self.partitions)

    def write(self, data, partition: Optional[int] = None, key=None,
              producer: Optional[str] = None,
              seq_no: Optional[int] = None) -> tuple:
        """Append one message; returns (partition, offset | None-if-dedup)."""
        if partition is None:
            partition = self._route(key) if key is not None else 0
        off = self.partitions[partition].append(data, producer, seq_no)
        return partition, off

    def read(self, consumer: str, partition: int, limit: int = 100,
             offset: Optional[int] = None) -> list:
        """Read from the consumer's committed offset (or an explicit one)."""
        start = offset if offset is not None \
            else self.committed_offset(consumer, partition)
        return self.partitions[partition].read(start, limit)

    def committed_offset(self, consumer: str, partition: int) -> int:
        return self.offsets.get(consumer,
                                [0] * len(self.partitions))[partition]

    def commit_offset(self, consumer: str, partition: int,
                      offset: int) -> None:
        offs = self.offsets.setdefault(consumer,
                                       [0] * len(self.partitions))
        offs[partition] = offset
        if self._offsets_path is not None:
            from ydb_tpu.storage.persist import _atomic_json
            _atomic_json(self._offsets_path, self.offsets)


def _plain(v):
    return v.item() if hasattr(v, "item") else v


def _plain_row(d):
    return None if d is None else {c: _plain(v) for c, v in d.items()}


class ChangefeedSink:
    """CDC: publishes committed row-table mutations into a topic,
    partitioned by primary key (per-key ordering, like the reference's
    changefeed partitioning by key hash).

    Exactly-once: every message carries producer `cdc:<table>` with a
    DETERMINISTIC seq_no `(plan_step << 32) | index-in-commit`. Commits
    are the only emitters and each table sees one emit() per commit, so
    the sequence is globally monotone per table — and therefore monotone
    along the subsequence routed to any one partition, which is exactly
    what the per-(producer, partition) high-water dedup needs. A torn
    topic tail (crash between the row-WAL fsync and the topic append)
    heals at reopen: the engine re-emits row-WAL replay events through
    this same path and dedup drops everything already on disk."""

    def __init__(self, topic: Topic, table_name: str,
                 key_columns: list):
        self.topic = topic
        self.table_name = table_name
        self.key_columns = list(key_columns)
        self.producer = f"cdc:{table_name}"

    def emit(self, events: list, version) -> None:
        """events: [{"op", "row", "old", "new"}] — committed effects only
        (no-op deletes never reach here), in commit order, with decoded
        old/new row images (NEWIMAGE mode; consumers that maintain
        derived state need both sides of every mutation)."""
        base = version.plan_step << 32
        for i, ev in enumerate(events):
            row = _plain_row(ev["row"])
            key = tuple(row.get(k) for k in self.key_columns)
            self.topic.write(
                {"table": self.table_name, "op": ev["op"], "row": row,
                 "old": _plain_row(ev.get("old")),
                 "new": _plain_row(ev.get("new")),
                 "plan_step": version.plan_step, "tx_id": version.tx_id},
                key=str(key), producer=self.producer, seq_no=base | i)
