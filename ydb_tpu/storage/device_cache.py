"""Device-resident (HBM) column cache.

The TPU counterpart of the reference's shared page cache for tablet data
(`ydb/core/tablet_flat` shared cache / `columnshard` blob cache
`blobs_reader/`): immutable portion columns are uploaded to device memory
once and reused across queries, so repeated scans stream from HBM instead
of re-crossing the host↔device link every query. LRU-evicted under a byte
budget. Portions are immutable (compaction replaces them with new ids), so
entries never go stale — eviction of dropped portions happens lazily.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.block import HostBlock
from ydb_tpu.ops.device import DeviceBlock, bucket_capacity
from ydb_tpu.storage.portion import Portion

import os as _os

# bytes of HBM for cached columns (v5e: 16GB total; leave headroom for
# sort/groupby working sets)
DEFAULT_BUDGET = int(_os.environ.get("YDB_TPU_HBM_BUDGET", 10 << 30))


def enumerate_scan_sources(table, snapshot, prune):
    """Every visible scan source of a table: (HostBlocks, source ids).
    Source ids key superblock cache entries (write id, not list position:
    two snapshots seeing different insert subsets must not collide).
    Portions with MVCC delete marks visible at the snapshot contribute
    their filtered view under an id that carries the visible mark set."""
    sources, src_ids = [], []
    for shard in table.shards:
        portions, insert_entries = shard.scan_sources(snapshot, prune)
        for p in portions:
            sig = p.delete_sig(snapshot) if p.deletes else ()
            if sig:
                sources.append(p.visible_block(snapshot))
                src_ids.append(("pv", p.id, sig))
            else:
                sources.append(p.block)
                src_ids.append(("p", p.id))
        for e in insert_entries:
            sources.append(e.block)
            src_ids.append(("i", shard.shard_id, e.write_id))
    return sources, src_ids


def _device_source(b):
    """The still-on-device view of a stage-spine scan source (a landed
    `DeviceStageBlock` channel table), or None for plain host blocks.
    Reading it instead of `.columns` keeps the admission estimate and
    the superblock stack from forcing the block's host readback."""
    return getattr(b, "device", None)


def _source_cap(b) -> int:
    dev = _device_source(b)
    return dev.capacity if dev is not None \
        else bucket_capacity(max(b.length, 1))


def _source_has_valid(b, s: str) -> bool:
    dev = _device_source(b)
    return (s in dev.valids) if dev is not None \
        else (b.columns[s].valid is not None)


def estimate_scan_bytes(sources, storage_names: list,
                        pad_to: int = 0) -> int:
    """Superblock HBM footprint of a scan: K stacked sources at the max
    capacity bucket, per column data + validity — the fused-path
    admission estimate (no upload happens to find out it didn't fit).
    `pad_to`: the shape-bucketed row count (padded rows allocate real
    HBM, so the estimate must charge them). Device-resident sources
    answer from shape metadata — no readback."""
    if not sources:
        return 0
    K = max(len(sources), pad_to)
    CAP = max(_source_cap(b) for b in sources)
    total = 0
    for s in storage_names:
        b0 = sources[0]
        itemsize = int(np.dtype(b0.schema.dtype(s).np).itemsize) \
            if _device_source(b0) is not None \
            else b0.columns[s].data.itemsize
        total += K * CAP * itemsize
        if any(_source_has_valid(b, s) for b in sources):
            total += K * CAP
    return total


class DeviceColumnCache:
    def __init__(self, budget_bytes: int = DEFAULT_BUDGET):
        import threading
        self.budget = budget_bytes
        self._entries: OrderedDict = OrderedDict()  # (pid, col) -> (data, valid, nbytes)
        self.bytes = 0
        # device bytes held by OTHER long-lived caches sharing this HBM
        # budget (the cross-query BuildCache registers here): column
        # eviction makes room for them so the two pools never sum past
        # the device budget
        self.foreign_bytes = 0
        self.hits = 0
        self.misses = 0
        # concurrent readers share the cache; the lock covers the
        # LRU bookkeeping (uploads serialize on the device link anyway)
        self._mu = threading.RLock()

    def _evict(self):
        while self.bytes + self.foreign_bytes > self.budget \
                and self._entries:
            _key, (_d, _v, nbytes) = self._entries.popitem(last=False)
            self.bytes -= nbytes

    def acquire_foreign(self, nbytes: int) -> None:
        """Register device bytes owned by another long-lived cache
        against this budget, evicting columns to make room."""
        with self._mu:
            self.foreign_bytes += nbytes
            self._evict()

    def release_foreign(self, nbytes: int) -> None:
        with self._mu:
            self.foreign_bytes = max(0, self.foreign_bytes - nbytes)

    def reserve(self, nbytes: int) -> None:
        """Evict LRU entries until `nbytes` of HBM fits beside the cached
        set — for paths that allocate device memory the cache doesn't
        track (tiled scan stacks, spill partials)."""
        with self._mu:
            while self.bytes + self.foreign_bytes + nbytes > self.budget \
                    and self._entries:
                _key, (_d, _v, nb) = self._entries.popitem(last=False)
                self.bytes -= nb

    def _lookup(self, key):
        with self._mu:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
            return hit

    def _insert(self, key, data, valid, nbytes):
        """Insert a freshly built entry; a concurrent builder of the same
        key may have won the race — keep the existing entry (dropping the
        duplicate upload) so bytes accounting stays exact."""
        with self._mu:
            hit = self._entries.get(key)
            if hit is not None:
                return hit[0], hit[1]
            self._entries[key] = (data, valid, nbytes)
            self.bytes += nbytes
            self._evict()
            return data, valid

    def column(self, portion: Portion, col: str, device=None):
        """(device data, device valid | None), padded to the portion's
        capacity bucket; committed to `device` when given (mesh placement).

        The stack/upload work runs OUTSIDE the cache mutex — holding it
        across device transfers would serialize every concurrent SELECT's
        data prep on one lock."""
        import jax

        key = (portion.id, col, None if device is None else device.id)
        hit = self._lookup(key)
        if hit is not None:
            # the query's working set includes cache-resident columns —
            # the ledger accounts residency per query, not per upload
            from ydb_tpu.utils import memledger
            memledger.record_padded_buffers(
                "portion_column", "scan_columns", portion.num_rows,
                hit[0].shape[0], hit[0], hit[1])
            return hit[0], hit[1]
        put = (lambda x: jax.device_put(x, device)) if device is not None \
            else jnp.asarray
        cd = portion.block.columns[col]
        cap = bucket_capacity(max(portion.num_rows, 1))
        pad = cap - portion.num_rows
        data = put(np.pad(cd.data, (0, pad)) if pad else cd.data)
        valid = None
        nbytes = data.nbytes
        if cd.valid is not None:
            valid = put(np.pad(cd.valid, (0, pad)) if pad else cd.valid)
            nbytes += valid.nbytes
        from ydb_tpu.utils import memledger
        memledger.record_padded_buffers(
            "portion_column", "scan_columns", portion.num_rows, cap,
            data, valid)
        return self._insert(key, data, valid, nbytes)

    def superblock(self, table, storage_names: list, rename: dict,
                   snapshot, prune, sources=None, src_ids=None,
                   pad_to: int = 0):
        """Stacked (K, CAP) device arrays covering every visible scan source
        of `table` — the input of the whole-query fused program
        (`ydb_tpu/ops/fused.py`), one upload per column per data version.

        `sources`/`src_ids`: pass a pre-enumerated source list (the
        executor's admission estimate already walked the shards once).

        `pad_to`: quantize the row count up to a shape bucket
        (`progstore/buckets.bucket_sources`) — rows beyond the real K
        are zero-filled with length 0, which the fused kernels mask out
        exactly like a short real source, so a growing table reuses the
        bucket's compiled program instead of minting a shape per count.
        The EFFECTIVE row count rides the cache key (an exact-K stack
        and its padded sibling are different device arrays).

        Returns (arrays {internal: (K,CAP)}, valids {internal: (K,CAP)},
        lengths jnp (K,), K, CAP, dicts) or None when the table has no
        visible sources."""
        if sources is None:
            sources, src_ids = enumerate_scan_sources(table, snapshot, prune)
        if not sources:
            return None
        K = max(len(sources), pad_to)
        CAP = max(_source_cap(b) for b in sources)
        # no snapshot component: src_ids already reflect exactly which
        # sources the snapshot sees (portions are immutable), and
        # data_version covers commits — a snapshot in the key would make
        # every write to ANY table re-stack and re-upload this one
        src_key = (table.uid, table.data_version, tuple(src_ids), CAP, K)

        lengths_np = np.zeros(K, np.int32)
        lengths_np[:len(sources)] = [b.length for b in sources]
        arrays, valids, dicts = {}, {}, {}
        for s in storage_names:
            out = rename.get(s, s)
            key = ("sbc", src_key, s)
            hit = self._lookup(key)
            if hit is not None:
                arrays[out] = hit[0]
                if hit[1] is not None:
                    valids[out] = hit[1]
            elif all(_device_source(b) is not None for b in sources):
                # device-resident sources (stage-spine channel
                # landings): stack BY REFERENCE on device — no host
                # readback, no re-upload. Pad regions zero and validity
                # is length-clipped, so the stack is bit-identical to
                # what the host path would have built.
                iota = jnp.arange(CAP, dtype=jnp.int32)
                has_valid = any(_source_has_valid(b, s) for b in sources)
                rows_d, rows_v = [], []
                for b in sources:
                    dv = _device_source(b)
                    act = iota < jnp.int32(b.length)
                    a = dv.arrays[s]
                    if a.shape[0] > CAP:
                        a = a[:CAP]
                    elif a.shape[0] < CAP:
                        a = jnp.concatenate(
                            [a, jnp.zeros(CAP - a.shape[0], a.dtype)])
                    rows_d.append(jnp.where(act, a, 0))
                    if has_valid:
                        va = dv.valids.get(s)
                        if va is not None:
                            if va.shape[0] > CAP:
                                va = va[:CAP]
                            elif va.shape[0] < CAP:
                                va = jnp.concatenate(
                                    [va, jnp.zeros(CAP - va.shape[0],
                                                   jnp.bool_)])
                            va = va & act
                        else:
                            va = act
                        rows_v.append(va)
                for _ in range(K - len(sources)):
                    rows_d.append(jnp.zeros(CAP, rows_d[0].dtype))
                    if has_valid:
                        rows_v.append(jnp.zeros(CAP, jnp.bool_))
                d = jnp.stack(rows_d)
                v = jnp.stack(rows_v) if has_valid else None
                nbytes = d.nbytes + (v.nbytes if v is not None else 0)
                d, v = self._insert(key, d, v, nbytes)
                arrays[out] = d
                if v is not None:
                    valids[out] = v
            else:
                # stack + upload OUTSIDE the mutex (see column())
                dtype = sources[0].columns[s].data.dtype
                stack = np.zeros((K, CAP), dtype=dtype)
                has_valid = any(b.columns[s].valid is not None
                                for b in sources)
                vstack = np.zeros((K, CAP), np.bool_) if has_valid else None
                for k, b in enumerate(sources):
                    cd = b.columns[s]
                    stack[k, :b.length] = cd.data
                    if vstack is not None:
                        vstack[k, :b.length] = (cd.valid if cd.valid is not None
                                                else True)
                d = jnp.asarray(stack)
                v = jnp.asarray(vstack) if vstack is not None else None
                nbytes = d.nbytes + (v.nbytes if v is not None else 0)
                d, v = self._insert(key, d, v, nbytes)
                arrays[out] = d
                if v is not None:
                    valids[out] = v
            dv0 = _device_source(sources[0])
            dic = dv0.dictionaries.get(s) if dv0 is not None \
                else sources[0].columns[s].dictionary
            if dic is not None:
                dicts[out] = dic

        lkey = ("sbl", src_key)
        lhit = self._lookup(lkey)
        if lhit is None:
            lengths = jnp.asarray(lengths_np)
            lengths, _ = self._insert(lkey, lengths, None, lengths.nbytes)
        else:
            lengths = lhit[0]
        return arrays, valids, lengths, K, CAP, dicts

    def device_block(self, portion: Portion, columns: list,
                     rename: Optional[dict] = None,
                     device=None) -> DeviceBlock:
        """Assemble a DeviceBlock for a portion from cached columns."""
        import jax

        rename = rename or {}
        from ydb_tpu.core.schema import Column, Schema
        cap = bucket_capacity(max(portion.num_rows, 1))
        arrays, valids, dicts = {}, {}, {}
        cols = []
        for name in columns:
            out = rename.get(name, name)
            d, v = self.column(portion, name, device)
            arrays[out] = d
            if v is not None:
                valids[out] = v
            cd = portion.block.columns[name]
            if cd.dictionary is not None:
                dicts[out] = cd.dictionary
            cols.append(Column(out, portion.block.schema.dtype(name)))
        length = jax.device_put(np.int32(portion.num_rows), device) \
            if device is not None else jnp.int32(portion.num_rows)
        return DeviceBlock(Schema(cols), arrays, valids, length, cap, dicts)
