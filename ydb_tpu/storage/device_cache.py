"""Device-resident (HBM) column cache.

The TPU counterpart of the reference's shared page cache for tablet data
(`ydb/core/tablet_flat` shared cache / `columnshard` blob cache
`blobs_reader/`): immutable portion columns are uploaded to device memory
once and reused across queries, so repeated scans stream from HBM instead
of re-crossing the host↔device link every query. LRU-evicted under a byte
budget. Portions are immutable (compaction replaces them with new ids), so
entries never go stale — eviction of dropped portions happens lazily.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.block import HostBlock
from ydb_tpu.ops.device import DeviceBlock, bucket_capacity
from ydb_tpu.storage.portion import Portion

DEFAULT_BUDGET = 6 << 30          # bytes of HBM for cached columns


class DeviceColumnCache:
    def __init__(self, budget_bytes: int = DEFAULT_BUDGET):
        self.budget = budget_bytes
        self._entries: OrderedDict = OrderedDict()  # (pid, col) -> (data, valid, nbytes)
        self.bytes = 0
        self.hits = 0
        self.misses = 0

    def _evict(self):
        while self.bytes > self.budget and self._entries:
            _key, (_d, _v, nbytes) = self._entries.popitem(last=False)
            self.bytes -= nbytes

    def column(self, portion: Portion, col: str, device=None):
        """(device data, device valid | None), padded to the portion's
        capacity bucket; committed to `device` when given (mesh placement)."""
        import jax

        key = (portion.id, col, None if device is None else device.id)
        hit = self._entries.get(key)
        if hit is not None:
            self._entries.move_to_end(key)
            self.hits += 1
            return hit[0], hit[1]
        self.misses += 1
        put = (lambda x: jax.device_put(x, device)) if device is not None \
            else jnp.asarray
        cd = portion.block.columns[col]
        cap = bucket_capacity(max(portion.num_rows, 1))
        pad = cap - portion.num_rows
        data = put(np.pad(cd.data, (0, pad)) if pad else cd.data)
        valid = None
        nbytes = data.nbytes
        if cd.valid is not None:
            valid = put(np.pad(cd.valid, (0, pad)) if pad else cd.valid)
            nbytes += valid.nbytes
        self._entries[key] = (data, valid, nbytes)
        self.bytes += nbytes
        self._evict()
        return data, valid

    def device_block(self, portion: Portion, columns: list,
                     rename: Optional[dict] = None,
                     device=None) -> DeviceBlock:
        """Assemble a DeviceBlock for a portion from cached columns."""
        import jax

        rename = rename or {}
        from ydb_tpu.core.schema import Column, Schema
        cap = bucket_capacity(max(portion.num_rows, 1))
        arrays, valids, dicts = {}, {}, {}
        cols = []
        for name in columns:
            out = rename.get(name, name)
            d, v = self.column(portion, name, device)
            arrays[out] = d
            if v is not None:
                valids[out] = v
            cd = portion.block.columns[name]
            if cd.dictionary is not None:
                dicts[out] = cd.dictionary
            cols.append(Column(out, portion.block.schema.dtype(name)))
        length = jax.device_put(np.int32(portion.num_rows), device) \
            if device is not None else jnp.int32(portion.num_rows)
        return DeviceBlock(Schema(cols), arrays, valids, length, cap, dicts)
