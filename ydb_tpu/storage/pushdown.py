"""Extract min/max prune predicates from an SSA program.

The scan path runs the full program on-device per block; this module only
mines the program's *leading* Filter commands for `col <op> const` conjuncts
usable against portion statistics — the analog of the reference's
early-filter planning (`engines/reader/plain_reader/constructor/`,
`TPredicateFilter`).
"""

from __future__ import annotations

from ydb_tpu.ops import ir

_CMP = {"eq", "lt", "le", "gt", "ge"}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def _conjuncts(expr, out):
    if isinstance(expr, ir.Call) and expr.op == "and":
        for a in expr.args:
            _conjuncts(a, out)
    else:
        out.append(expr)


def extract_prune_predicates(program: ir.Program) -> list[tuple]:
    """[(col, op, value)] conjuncts implied by the program's filters."""
    preds: list[tuple] = []
    assigned: set[str] = set()
    for cmd in program.commands:
        if isinstance(cmd, ir.Assign):
            assigned.add(cmd.name)
        elif isinstance(cmd, ir.Filter):
            parts: list = []
            _conjuncts(cmd.pred, parts)
            for p in parts:
                if not (isinstance(p, ir.Call) and p.op in _CMP and len(p.args) == 2):
                    continue
                a, b = p.args
                if isinstance(a, ir.Col) and isinstance(b, ir.Const):
                    col, op, val = a.name, p.op, b.value
                elif isinstance(a, ir.Const) and isinstance(b, ir.Col):
                    col, op, val = b.name, _FLIP[p.op], a.value
                else:
                    continue
                if col not in assigned:  # only source columns have stats
                    preds.append((col, op, val))
        elif isinstance(cmd, (ir.GroupBy,)):
            break
    return preds
