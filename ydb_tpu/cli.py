"""Command-line interface — server, interactive SQL, benchmark workloads.

The `ydb` CLI analog (`ydb/public/lib/ydb_cli`): `server` plays `ydbd
server`, `sql` the query client, and `workload tpch init/run` the
benchmark runner (`commands/tpch.h:9-66`, shared runner
`benchmark_utils.cpp` — per-query times + geomean).

    python -m ydb_tpu.cli server --data-dir /path --port 2136
    python -m ydb_tpu.cli sql "select 1 as x" [--endpoint host:port]
    python -m ydb_tpu.cli workload tpch init --sf 0.1 [--data-dir /path]
    python -m ydb_tpu.cli workload tpch run [--queries q1,q6] [--repeat 3]
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time


def _embedded_engine(args):
    from ydb_tpu.query import QueryEngine
    return QueryEngine(data_dir=getattr(args, "data_dir", None))


def cmd_server(args) -> int:
    from ydb_tpu.server import serve
    eng = _embedded_engine(args)
    server, port = serve(eng, port=args.port)
    print(f"ydb_tpu server listening on 127.0.0.1:{port} "
          f"(data_dir={args.data_dir})", flush=True)
    fronts = []
    try:
        if args.pg_port is not None:
            from ydb_tpu.server.pgwire import serve_pg
            pg = serve_pg(eng, port=args.pg_port)
            fronts.append(pg)
            print(f"pgwire listening on 127.0.0.1:{pg.port}", flush=True)
        if args.http_port is not None:
            from ydb_tpu.server.http import serve_http
            h = serve_http(eng, port=args.http_port)
            fronts.append(h)
            print(f"http listening on 127.0.0.1:{h.port}", flush=True)
        if args.kafka_port is not None:
            from ydb_tpu.server.kafka import serve_kafka
            k = serve_kafka(eng, port=args.kafka_port, auto_create=True)
            fronts.append(k)
            print(f"kafka listening on 127.0.0.1:{k.port}", flush=True)
        server.wait_for_termination()
    except KeyboardInterrupt:
        pass
    finally:
        # a bind failure in a LATER front must not leave the gRPC server
        # (non-daemon threads) holding the process open with nothing
        # serving what was asked
        server.stop(grace=1)
        for fr in fronts:
            fr.stop()
    return 0


def cmd_sql(args) -> int:
    if args.endpoint:
        from ydb_tpu.server import Client
        client = Client(args.endpoint)
        df = client.query(args.query)
    else:
        df = _embedded_engine(args).query(args.query)
    print(df.to_string(index=False))
    return 0


def _ensure_repo_on_path() -> None:
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for p in (repo, "."):
        if p not in sys.path:
            sys.path.insert(0, p)


def _tpch_loader(catalog, sf):
    from ydb_tpu.bench.tpch_gen import load_tpch
    load_tpch(catalog, sf=sf)


def _clickbench_loader(catalog, sf):
    from ydb_tpu.bench.clickbench_gen import load_hits
    load_hits(catalog, n_rows=max(1000, int(sf * 1e6)))


def _tpcds_loader(catalog, sf):
    from ydb_tpu.bench.tpcds_gen import load_tpcds
    load_tpcds(catalog, sf=sf)


# workload name -> (fact table, loader, queries module)
WORKLOADS = {
    "tpch": ("lineitem", _tpch_loader, "tests.tpch_util"),
    "clickbench": ("hits", _clickbench_loader, "tests.clickbench_util"),
    "tpcds": ("store_sales", _tpcds_loader, "tests.tpcds_util"),
}


def _workload_queries(workload: str, names):
    import importlib
    _ensure_repo_on_path()
    qs = importlib.import_module(WORKLOADS[workload][2]).QUERIES
    if names:
        return {n: qs[n] for n in names}
    return dict(qs)


def _load_workload(eng, workload: str, args) -> None:
    fact, loader, _qm = WORKLOADS[workload]
    if not eng.catalog.has(fact):
        loader(eng.catalog, args.sf)


def cmd_workload_init(args) -> int:
    eng = _embedded_engine(args)
    t0 = time.perf_counter()
    _load_workload(eng, args.workload, args)
    fact = WORKLOADS[args.workload][0]
    rows = eng.catalog.table(fact).num_rows
    print(f"loaded {args.workload} sf={args.sf}: {rows} {fact} rows "
          f"in {time.perf_counter() - t0:.1f}s", flush=True)
    if args.data_dir:
        print(f"durable at {args.data_dir}")
    return 0


def cmd_workload_run(args) -> int:
    queries = _workload_queries(
        args.workload, args.queries.split(",") if args.queries else None)
    if args.endpoint:
        from ydb_tpu.server import Client
        runner = Client(args.endpoint).query
        eng = None
    else:
        eng = _embedded_engine(args)
        _load_workload(eng, args.workload, args)
        runner = eng.query

    times = {}
    for name, q in queries.items():
        try:
            runner(q)                       # warm-up (compile)
            best = math.inf
            for _ in range(args.repeat):
                t0 = time.perf_counter()
                runner(q)
                best = min(best, time.perf_counter() - t0)
            times[name] = best
            print(f"{name:>5}: {best * 1000:9.1f} ms", flush=True)
        except Exception as e:              # noqa: BLE001 — benchmark runner
            print(f"{name:>5}: FAILED {type(e).__name__}: {e}", flush=True)
    if times:
        geo = math.exp(sum(math.log(t) for t in times.values())
                       / len(times))
        print(f"geomean over {len(times)} queries: {geo * 1000:.1f} ms")
        print(json.dumps({"metric": f"{args.workload}_geomean_ms",
                          "value": round(geo * 1000, 1),
                          "queries": len(times)}))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ydb_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("server", help="run the gRPC query service")
    ps.add_argument("--port", type=int, default=2136)
    ps.add_argument("--pg-port", type=int, default=None,
                    help="also serve the PostgreSQL wire protocol")
    ps.add_argument("--http-port", type=int, default=None,
                    help="also serve the HTTP/JSON API")
    ps.add_argument("--kafka-port", type=int, default=None,
                    help="also serve the Kafka wire protocol (topics)")
    ps.add_argument("--data-dir", default=None)
    ps.set_defaults(fn=cmd_server)

    pq = sub.add_parser("sql", help="run one SQL statement")
    pq.add_argument("query")
    pq.add_argument("--endpoint", default=None,
                    help="host:port of a server (default: embedded engine)")
    pq.add_argument("--data-dir", default=None)
    pq.set_defaults(fn=cmd_sql)

    pw = sub.add_parser("workload", help="benchmark workloads")
    wsub = pw.add_subparsers(dest="workload", required=True)
    for wname in ("tpch", "clickbench", "tpcds"):
        pt = wsub.add_parser(wname)
        tsub = pt.add_subparsers(dest="action", required=True)
        ti = tsub.add_parser("init")
        ti.add_argument("--sf", type=float, default=0.1)
        ti.add_argument("--data-dir", default=None)
        ti.set_defaults(fn=cmd_workload_init)
        tr = tsub.add_parser("run")
        tr.add_argument("--queries", default=None,
                        help="comma list, e.g. q1,q6")
        tr.add_argument("--repeat", type=int, default=3)
        tr.add_argument("--sf", type=float, default=0.1)
        tr.add_argument("--endpoint", default=None)
        tr.add_argument("--data-dir", default=None)
        tr.set_defaults(fn=cmd_workload_run)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
