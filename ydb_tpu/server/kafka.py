"""Kafka wire-protocol front over the topic subsystem (v0 subset).

The reference serves the Kafka protocol next to its own fronts
(`ydb/core/kafka_proxy` — clients produce/consume YDB topics with stock
Kafka drivers). This front speaks the classic v0 protocol generation —
ApiVersions, Metadata, Produce, Fetch, and ListOffsets over CRC-framed
MessageSets — mapped onto `storage/topic.py`: a Kafka topic IS an
engine topic, a Kafka partition IS a topic partition, offsets are the
partition's record offsets. Message key/value bytes ride base64 inside
the topic's JSON-over-WAL records, so Kafka-produced data is durable
and replayable like any native producer's, and native consumers (CDC
readers, trace sinks) see Kafka-produced records and vice versa.

Scope v1: magic-0 messages (no compression, no record batches, no
consumer groups — clients manage offsets with ListOffsets/Fetch, the
simple-consumer pattern)."""

from __future__ import annotations

import base64
import socket
import socketserver
import struct
import threading
import zlib

API_PRODUCE, API_FETCH, API_LIST_OFFSETS, API_METADATA = 0, 1, 2, 3
API_VERSIONS = 18
ERR_NONE, ERR_UNKNOWN_TOPIC, ERR_OFFSET_OUT_OF_RANGE = 0, 3, 1


class _Reader:
    def __init__(self, data: bytes):
        self.d = data
        self.o = 0

    def i8(self):
        v = struct.unpack_from("!b", self.d, self.o)[0]
        self.o += 1
        return v

    def i16(self):
        v = struct.unpack_from("!h", self.d, self.o)[0]
        self.o += 2
        return v

    def i32(self):
        v = struct.unpack_from("!i", self.d, self.o)[0]
        self.o += 4
        return v

    def i64(self):
        v = struct.unpack_from("!q", self.d, self.o)[0]
        self.o += 8
        return v

    def string(self):
        n = self.i16()
        if n < 0:
            return None
        s = self.d[self.o:self.o + n].decode()
        self.o += n
        return s

    def bytes_(self):
        n = self.i32()
        if n < 0:
            return None
        b = self.d[self.o:self.o + n]
        self.o += n
        return b


def _s(v) -> bytes:
    if v is None:
        return struct.pack("!h", -1)
    b = v.encode()
    return struct.pack("!h", len(b)) + b


def _b(v) -> bytes:
    if v is None:
        return struct.pack("!i", -1)
    return struct.pack("!i", len(v)) + v


def _message(key, value) -> bytes:
    """One magic-0 message: crc | magic | attrs | key | value."""
    body = struct.pack("!bb", 0, 0) + _b(key) + _b(value)
    return struct.pack("!I", zlib.crc32(body)) + body


def _message_set(records: list) -> bytes:
    out = []
    for rec in records:
        data = rec.get("data")
        key, value = _rec_kv(data)
        msg = _message(key, value)
        out.append(struct.pack("!qi", rec["offset"], len(msg)) + msg)
    return b"".join(out)


def _rec_kv(data):
    """Topic record payload → (key bytes|None, value bytes). Kafka-
    produced records carry {"k": b64|None, "v": b64}; native records
    (CDC, traces, dict payloads) serialize as JSON values."""
    if isinstance(data, dict) and set(data) <= {"k", "v"} and "v" in data:
        key = base64.b64decode(data["k"]) if data.get("k") else None
        return key, base64.b64decode(data["v"])
    import json
    return None, json.dumps(data).encode()


def _parse_message_set(d: bytes) -> list:
    """MessageSet bytes → [(key, value)] (magic 0, uncompressed)."""
    out = []
    o = 0
    while o + 12 <= len(d):
        (_off, sz) = struct.unpack_from("!qi", d, o)
        o += 12
        if o + sz > len(d):
            break                         # partial trailing message
        r = _Reader(d[o:o + sz])
        o += sz
        r.i32()                           # crc (recomputed on emit)
        r.i8()                            # magic
        r.i8()                            # attributes
        key = r.bytes_()
        value = r.bytes_()
        out.append((key, value))
    return out


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        sock: socket.socket = self.request
        srv: "KafkaFront" = self.server.owner   # type: ignore[attr-defined]
        f = sock.makefile("rb")
        try:
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    return
                (size,) = struct.unpack("!i", hdr)
                body = f.read(size)
                if len(body) < size:
                    return
                r = _Reader(body)
                api, ver = r.i16(), r.i16()
                corr = r.i32()
                r.string()                 # client_id
                try:
                    payload = srv._dispatch(api, ver, r)
                except Exception as e:     # noqa: BLE001 — wire boundary
                    srv.errors.append(f"{type(e).__name__}: {e}")
                    return
                resp = struct.pack("!i", corr) + payload
                sock.sendall(struct.pack("!i", len(resp)) + resp)
        except (ConnectionError, BrokenPipeError):
            pass
        finally:
            sock.close()


class KafkaFront:
    """Kafka v0 listener over an engine's topics."""

    def __init__(self, engine, port: int = 0, host: str = "127.0.0.1",
                 auto_create: bool = False):
        self.engine = engine
        self.auto_create = auto_create
        self.errors: list = []
        self.host = host

        class _TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
        self._tcp = _TCP((host, port), _Handler)
        self._tcp.owner = self             # type: ignore[attr-defined]
        self.port = self._tcp.server_address[1]
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()

    # -- request handlers --------------------------------------------------

    def _topic(self, name: str):
        t = self.engine.topics.get(name)
        if t is None and self.auto_create:
            t = self.engine.create_topic(name, partitions=1)
        return t

    def _dispatch(self, api: int, ver: int, r: _Reader) -> bytes:
        if api == API_VERSIONS:
            keys = [(API_PRODUCE, 0, 0), (API_FETCH, 0, 0),
                    (API_LIST_OFFSETS, 0, 0), (API_METADATA, 0, 0),
                    (API_VERSIONS, 0, 0)]
            out = struct.pack("!hi", ERR_NONE, len(keys))
            for (k, lo, hi) in keys:
                out += struct.pack("!hhh", k, lo, hi)
            return out
        if api == API_METADATA:
            n = r.i32()
            names = [r.string() for _ in range(n)] if n > 0 \
                else sorted(self.engine.topics)
            out = struct.pack("!i", 1)                 # one broker
            out += struct.pack("!i", 0) + _s(self.host) \
                + struct.pack("!i", self.port)
            out += struct.pack("!i", len(names))
            for name in names:
                t = self._topic(name)
                if t is None:
                    out += struct.pack("!h", ERR_UNKNOWN_TOPIC) + _s(name)
                    out += struct.pack("!i", 0)
                    continue
                out += struct.pack("!h", ERR_NONE) + _s(name)
                out += struct.pack("!i", len(t.partitions))
                for pid in range(len(t.partitions)):
                    out += struct.pack("!hii", ERR_NONE, pid, 0)
                    out += struct.pack("!ii", 1, 0)    # replicas = [0]
                    out += struct.pack("!ii", 1, 0)    # isr = [0]
            return out
        if api == API_PRODUCE:
            r.i16()                                    # acks
            r.i32()                                    # timeout
            out_topics = []
            for _ in range(r.i32()):
                name = r.string()
                parts = []
                for _ in range(r.i32()):
                    pid = r.i32()
                    sz = r.i32()
                    mset = r.d[r.o:r.o + sz]
                    r.o += sz
                    t = self._topic(name)
                    if t is None:
                        parts.append((pid, ERR_UNKNOWN_TOPIC, -1))
                        continue
                    base = None
                    for (key, value) in _parse_message_set(mset):
                        rec = {"v": base64.b64encode(value or b"")
                               .decode()}
                        if key is not None:
                            rec["k"] = base64.b64encode(key).decode()
                        _p, off = t.write(rec, partition=pid)
                        if base is None:
                            base = off
                    parts.append((pid, ERR_NONE,
                                  -1 if base is None else base))
                out_topics.append((name, parts))
            out = struct.pack("!i", len(out_topics))
            for (name, parts) in out_topics:
                out += _s(name) + struct.pack("!i", len(parts))
                for (pid, err, off) in parts:
                    out += struct.pack("!ihq", pid, err, off)
            return out
        if api == API_LIST_OFFSETS:
            r.i32()                                    # replica_id
            out_topics = []
            for _ in range(r.i32()):
                name = r.string()
                parts = []
                for _ in range(r.i32()):
                    pid = r.i32()
                    ts = r.i64()                       # -1 latest, -2 first
                    r.i32()                            # max offsets
                    t = self._topic(name)
                    if t is None or pid >= len(t.partitions):
                        parts.append((pid, ERR_UNKNOWN_TOPIC, []))
                        continue
                    end = t.partitions[pid].end_offset
                    parts.append((pid, ERR_NONE,
                                  [0] if ts == -2 else [end]))
                out_topics.append((name, parts))
            out = struct.pack("!i", len(out_topics))
            for (name, parts) in out_topics:
                out += _s(name) + struct.pack("!i", len(parts))
                for (pid, err, offs) in parts:
                    out += struct.pack("!ihi", pid, err, len(offs))
                    for off in offs:
                        out += struct.pack("!q", off)
            return out
        if api == API_FETCH:
            r.i32()                                    # replica_id
            r.i32()                                    # max_wait
            r.i32()                                    # min_bytes
            out_topics = []
            for _ in range(r.i32()):
                name = r.string()
                parts = []
                for _ in range(r.i32()):
                    pid = r.i32()
                    fetch_off = r.i64()
                    max_bytes = r.i32()
                    t = self._topic(name)
                    if t is None or pid >= len(t.partitions):
                        parts.append((pid, ERR_UNKNOWN_TOPIC, 0, b""))
                        continue
                    part = t.partitions[pid]
                    if fetch_off > part.end_offset:
                        parts.append((pid, ERR_OFFSET_OUT_OF_RANGE,
                                      part.end_offset, b""))
                        continue
                    recs = part.read(fetch_off, limit=1000)
                    mset = _message_set(recs)[:max(max_bytes, 0)]
                    parts.append((pid, ERR_NONE, part.end_offset, mset))
                out_topics.append((name, parts))
            out = struct.pack("!i", len(out_topics))
            for (name, parts) in out_topics:
                out += _s(name) + struct.pack("!i", len(parts))
                for (pid, err, hw, mset) in parts:
                    out += struct.pack("!ihqi", pid, err, hw, len(mset))
                    out += mset
            return out
        raise ValueError(f"unsupported api key {api}")


def serve_kafka(engine, port: int = 0, auto_create: bool = False
                ) -> KafkaFront:
    return KafkaFront(engine, port=port, auto_create=auto_create)
