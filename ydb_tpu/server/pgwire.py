"""PostgreSQL wire-protocol front (v3, simple + extended query flow).

The reference serves the PG wire protocol next to gRPC
(`ydb/core/local_pgwire/`, `ydb/apps/pgwire` — startup/auth handshake,
simple `Q` queries, text-format result rows), so any psql-compatible
client can talk to it. Same here: a threaded TCP server translating the
v3 message flow onto the embedded engine.

Supported flow:
  * SSLRequest → 'N' (plaintext), StartupMessage → AuthenticationOk +
    ParameterStatus + BackendKeyData + ReadyForQuery
  * 'Q' (simple query) → RowDescription / DataRow* / CommandComplete /
    ReadyForQuery — text format, one statement per message
  * extended protocol: Parse ('P') with $n placeholders and optional
    param type oids, Bind ('B') with TEXT-format params (validated and
    inlined as typed literals — the proxy-style parameterization),
    Describe ('D'→ NoData; row descriptions ride Execute), Execute
    ('E'), Close ('C'), Sync ('S'), Flush ('H')
  * BEGIN/COMMIT/ROLLBACK ride the per-connection session, and the
    ReadyForQuery status byte tracks it ('I' idle / 'T' in tx)
  * 'X' terminate; errors → ErrorResponse (severity/code/message)
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading

_SSL_REQUEST = 80877103
_CANCEL_REQUEST = 80877102
_PROTO_V3 = 196608

# dtype kind -> (type oid, text encoder)
_PG_TEXT = "25"


def _date_str(days: int) -> str:
    import datetime
    return (datetime.date(1970, 1, 1)
            + datetime.timedelta(days=int(days))).isoformat()


def _oid_and_enc(kind: str):
    from ydb_tpu.core.dtypes import Kind
    k = Kind(kind)
    if k in (Kind.INT64, Kind.UINT64):
        return 20, str
    if k is Kind.INT32:
        return 23, str
    if k is Kind.FLOAT64:
        return 701, repr
    if k is Kind.BOOL:
        return 16, (lambda v: "t" if v else "f")
    if k is Kind.DATE32:
        return 1082, _date_str
    return 25, str                    # STRING and anything else: text


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


_INT_OIDS = (20, 21, 23, 26)
_FLOAT_OIDS = (700, 701, 1700)


def _substitute_params(sql: str, params: list, oids: list) -> str:
    """Inline TEXT-format parameters as validated typed literals (the
    proxy-style parameterization: the engine's own planner re-binds them
    as runtime params where it can). Numerics are parsed — a malformed
    value raises instead of splicing into the SQL text."""
    import re

    def lit(m):
        i = int(m.group(1)) - 1
        if i < 0 or i >= len(params):
            raise ValueError(f"parameter ${i + 1} not bound")
        v = params[i]
        if v is None:
            return "NULL"
        oid = oids[i] if i < len(oids) else 0
        if oid in _INT_OIDS:
            return str(int(v))
        if oid in _FLOAT_OIDS:
            return repr(float(v))
        if oid == 16:
            lv = v.lower()
            if lv in ("t", "true", "1", "on", "y", "yes"):
                return "TRUE"
            if lv in ("f", "false", "0", "off", "n", "no"):
                return "FALSE"
            raise ValueError(f"bad boolean parameter {v!r}")
        if oid == 1082:
            if not re.fullmatch(r"\d{4}-\d{2}-\d{2}", v):
                raise ValueError(f"bad date parameter {v!r}")
            return f"date '{v}'"
        # unspecified type (oid 0/705, what psycopg sends for all text
        # params): inline as a STRING and let the binder's PG-style
        # coercion re-type it against the compared column's domain
        # (ADVICE r4 — sniffing digits into numbers here silently broke
        # string-column comparisons like name = '123')
        s = v.replace("'", "''")
        return f"'{s}'"

    # quote-aware scan: $n inside a '...' literal is literal text, not a
    # placeholder (re.sub over the whole text would rewrite it)
    out = []
    i, n = 0, len(sql)
    in_str = False
    while i < n:
        ch = sql[i]
        if in_str:
            out.append(ch)
            if ch == "'":
                if i + 1 < n and sql[i + 1] == "'":
                    out.append("'")
                    i += 1
                else:
                    in_str = False
            i += 1
            continue
        if ch == "'":
            in_str = True
            out.append(ch)
            i += 1
            continue
        if ch == "$":
            m = re.match(r"\$(\d+)", sql[i:])
            if m:
                out.append(lit(m))
                i += m.end()
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _cstr(s: str) -> bytes:
    return s.encode() + b"\0"


def _error(message: str, code: str = "XX000") -> bytes:
    payload = b"S" + _cstr("ERROR") + b"C" + _cstr(code) \
        + b"M" + _cstr(message) + b"\0"
    return _msg(b"E", payload)


def _ready(status: bytes) -> bytes:
    return _msg(b"Z", status)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):  # noqa: C901 — one protocol loop
        sock: socket.socket = self.request
        srv: "PgServer" = self.server.owner   # type: ignore[attr-defined]
        f = sock.makefile("rb")

        def read_exact(n):
            data = f.read(n)
            if data is None or len(data) < n:
                raise ConnectionError
            return data

        try:
            # startup (possibly preceded by an SSLRequest)
            while True:
                (length,) = struct.unpack("!I", read_exact(4))
                body = read_exact(length - 4)
                (proto,) = struct.unpack("!I", body[:4])
                if proto == _SSL_REQUEST:
                    sock.sendall(b"N")
                    continue
                if proto == _CANCEL_REQUEST:
                    return
                if proto != _PROTO_V3:
                    sock.sendall(_error(f"unsupported protocol {proto}"))
                    return
                break
            out = _msg(b"R", struct.pack("!I", 0))          # AuthenticationOk
            for k, v in (("server_version", "15.0 (ydb-tpu)"),
                         ("server_encoding", "UTF8"),
                         ("client_encoding", "UTF8"),
                         ("integer_datetimes", "on")):
                out += _msg(b"S", _cstr(k) + _cstr(v))
            out += _msg(b"K", struct.pack("!II", 0, 0))     # BackendKeyData
            out += _ready(b"I")
            sock.sendall(out)

            session = srv.engine.session()
            self._aborted = False      # PG aborted-transaction state
            self._stmts: dict = {}     # name -> (sql, [oid])
            self._portals: dict = {}   # name -> bound sql
            pending = b""              # extended-flow replies batch to Sync
            skip = False               # error → ignore until Sync (v3 rule)

            def step(reply: bytes) -> bytes:
                nonlocal skip
                if reply[:1] == b"E":
                    skip = True
                return reply

            while True:
                tag = f.read(1)
                if not tag or tag == b"X":
                    return
                (length,) = struct.unpack("!I", read_exact(4))
                payload = read_exact(length - 4)
                if tag == b"Q":
                    sql = payload.rstrip(b"\0").decode()
                    sock.sendall(self._run(srv, session, sql))
                elif tag == b"S":                       # Sync
                    if session.tx is None:
                        # portals survive Sync inside a tx block (spec)
                        self._portals.clear()
                    skip = False
                    sock.sendall(pending
                                 + _ready(self._status(session)))
                    pending = b""
                elif skip and tag in (b"P", b"B", b"D", b"E", b"C",
                                      b"H"):
                    continue    # discard until Sync after an error
                elif tag == b"P":
                    pending += step(self._parse_msg(payload))
                elif tag == b"B":
                    pending += step(self._bind_msg(payload))
                elif tag == b"D":
                    pending += step(self._describe_msg(srv, session,
                                                       payload))
                elif tag == b"E":
                    pending += step(self._execute_msg(srv, session,
                                                      payload))
                elif tag == b"C":
                    kind, rest = payload[:1], payload[1:].rstrip(b"\0")
                    store = self._stmts if kind == b"S" else self._portals
                    store.pop(rest.decode(), None)
                    pending += _msg(b"3", b"")          # CloseComplete
                elif tag == b"H":                       # Flush
                    sock.sendall(pending)
                    pending = b""
                else:
                    sock.sendall(_error(
                        f"message {tag.decode(errors='replace')!r} not "
                        "supported") + _ready(self._status(session)))
        except (ConnectionError, BrokenPipeError, struct.error):
            pass
        finally:
            sock.close()

    def _parse_msg(self, payload: bytes) -> bytes:
        try:
            z1 = payload.index(b"\0")
            name = payload[:z1].decode()
            z2 = payload.index(b"\0", z1 + 1)
            sql = payload[z1 + 1:z2].decode()
            off = z2 + 1
            (noids,) = struct.unpack_from("!H", payload, off)
            off += 2
            oids = list(struct.unpack_from(f"!{noids}I", payload, off)) \
                if noids else []
            self._stmts[name] = (sql, oids)
            return _msg(b"1", b"")                      # ParseComplete
        except (ValueError, struct.error) as e:
            return _error(f"malformed Parse: {e}", code="08P01")

    def _bind_msg(self, payload: bytes) -> bytes:
        try:
            z1 = payload.index(b"\0")
            portal = payload[:z1].decode()
            z2 = payload.index(b"\0", z1 + 1)
            stmt_name = payload[z1 + 1:z2].decode()
            off = z2 + 1
            (nfmt,) = struct.unpack_from("!H", payload, off)
            off += 2
            fmts = list(struct.unpack_from(f"!{nfmt}H", payload, off))
            off += 2 * nfmt
            (nparams,) = struct.unpack_from("!H", payload, off)
            off += 2
            params = []
            for i in range(nparams):
                (plen,) = struct.unpack_from("!i", payload, off)
                off += 4
                if plen < 0:
                    params.append(None)
                else:
                    fmt = fmts[i] if i < len(fmts) \
                        else (fmts[0] if len(fmts) == 1 else 0)
                    if fmt != 0:
                        return _error("binary-format parameters are not "
                                      "supported (send text format)")
                    params.append(payload[off:off + plen].decode())
                    off += plen
            if stmt_name not in self._stmts:
                return _error(f"unknown prepared statement "
                              f"{stmt_name!r}", code="26000")
            sql, oids = self._stmts[stmt_name]
            self._portals[portal] = {
                "sql": _substitute_params(sql, params, oids)}
            return _msg(b"2", b"")                      # BindComplete
        except (ValueError, struct.error) as e:
            return _error(f"malformed Bind: {e}", code="08P01")

    _READ_KINDS = ("select", "setop", "explain")

    def _describe_msg(self, srv, session, payload: bytes) -> bytes:
        """Describe, per the v3 spec: the ROW DESCRIPTION belongs here,
        not on Execute (ADVICE r4 — JDBC/psycopg decode result sets off
        the Describe reply). Portal variant: read statements run NOW
        (execute-on-describe — output schemas need the bound plan) and
        the cached result rides the following Execute as DataRows only;
        non-reads answer NoData without executing (Describe must never
        mutate). Statement variant: ParameterDescription + NoData (the
        SQL still holds unbound $n placeholders)."""
        kind, rest = payload[:1], payload[1:].rstrip(b"\0")
        if kind == b"S":
            ent = self._stmts.get(rest.decode())
            if ent is None:
                return _error(f"unknown prepared statement "
                              f"{rest.decode()!r}", code="26000")
            _sql, oids = ent
            body = struct.pack("!H", len(oids))
            for o in oids:
                body += struct.pack("!I", o)
            return _msg(b"t", body) + _msg(b"n", b"")
        portal = self._portals.get(rest.decode())
        if portal is None:
            return _error(f"unknown portal {rest.decode()!r}", code="34000")
        first = portal["sql"].strip().split(None, 1)
        head = first[0].lower().rstrip(";") if first else ""
        if head not in ("select", "with", "values", "explain") \
                or self._aborted:
            return _msg(b"n", b"")
        try:
            # remember the commit epoch: a write landing between Describe
            # and Execute (same batch) invalidates this pre-computed
            # result — Execute re-runs instead of replaying stale rows
            portal["epoch"] = srv.engine.coordinator.last_plan_step
            block = srv.engine.execute(portal["sql"], session=session)
            kind2 = srv.engine.last_stats.kind
            if kind2 not in self._READ_KINDS:
                # executed but not row-producing: remember the completion
                # tag so the following Execute does NOT run it again
                n = getattr(srv.engine, "last_rows_affected", 0)
                portal["done_tag"] = {
                    "insert": f"INSERT 0 {n}", "update": f"UPDATE {n}",
                    "delete": f"DELETE {n}",
                    **self._DDL_TAGS}.get(kind2, kind2.upper())
                return _msg(b"n", b"")
            portal["result"] = block
            return self._row_desc(block)
        except Exception as e:           # noqa: BLE001 — wire boundary
            if session.tx is not None:
                self._aborted = True
            return _error(f"{type(e).__name__}: {e}")

    def _execute_msg(self, srv, session, payload: bytes) -> bytes:
        try:
            z1 = payload.index(b"\0")
            portal_name = payload[:z1].decode()
        except ValueError:
            return _error("malformed Execute", code="08P01")
        portal = self._portals.get(portal_name)
        if portal is None:
            return _error(f"unknown portal {portal_name!r}", code="34000")
        if self._aborted:
            # a statement failed inside the tx AFTER this portal was
            # described: its cached result must not leak past the
            # aborted-transaction barrier. Drop the caches and let _run
            # apply the 25P02 rule (which still honors ROLLBACK/COMMIT).
            portal.pop("result", None)
            portal.pop("done_tag", None)
        done = portal.pop("done_tag", None)
        if done is not None:
            portal["consumed"] = True
            return _msg(b"C", _cstr(done))
        block = portal.pop("result", None)
        if block is not None \
                and portal.get("epoch") != srv.engine.coordinator.last_plan_step:
            # a write landed since Describe: the client already holds the
            # RowDescription, so re-run and emit DataRows only (a second
            # 'T' inside Execute would desync v3 clients)
            try:
                block = srv.engine.execute(portal["sql"], session=session)
            except Exception as e:           # noqa: BLE001 — wire boundary
                if session.tx is not None:
                    self._aborted = True
                return _error(f"{type(e).__name__}: {e}")
        if block is not None:
            # described portal: the result was produced at Describe time;
            # Execute emits DataRows + CommandComplete only (spec shape)
            portal["consumed"] = True
            return self._data_rows(block) \
                + _msg(b"C", _cstr(f"SELECT {block.length}"))
        if portal.get("consumed"):
            # re-Execute of a completed portal: the stream is exhausted
            # (spec: portals run once) — no re-execution, no second 'T'
            return _msg(b"C", _cstr("SELECT 0"))
        # reuse the simple-query runner minus its trailing ReadyForQuery
        # (extended flow defers that to Sync)
        out = self._run(srv, session, portal["sql"])
        z = _ready(self._status(session))
        return out[:-len(z)] if out.endswith(z) else out

    def _status(self, session) -> bytes:
        if session.tx is None:
            return b"I"
        return b"E" if self._aborted else b"T"

    _DDL_TAGS = {"createtable": "CREATE TABLE", "droptable": "DROP TABLE",
                 "altertable": "ALTER TABLE", "createindex": "CREATE INDEX",
                 "dropindex": "DROP INDEX",
                 "creatematerializedview": "CREATE MATERIALIZED VIEW",
                 "dropmaterializedview": "DROP MATERIALIZED VIEW"}

    def _run(self, srv, session, sql: str) -> bytes:
        if not sql.strip():
            return _msg(b"I", b"") + _ready(self._status(session))
        # PG aborted-transaction rule: after an error inside an explicit
        # tx, everything except ROLLBACK is rejected, and COMMIT rolls
        # back (answering ROLLBACK) — partial data must not persist
        first = sql.strip().split(None, 1)[0].lower().rstrip(";")
        if self._aborted:
            if first in ("rollback", "commit"):
                try:
                    srv.engine.execute("rollback", session=session,
                                       _internal=True)
                except Exception:            # noqa: BLE001
                    pass
                self._aborted = False
                return _msg(b"C", _cstr("ROLLBACK")) \
                    + _ready(self._status(session))
            return _error("current transaction is aborted, commands "
                          "ignored until end of transaction block",
                          code="25P02") + _ready(self._status(session))
        # no front-side lock: the engine serializes its own write path
        # internally and SELECTs run concurrently over MVCC snapshots;
        # last_stats / last_rows_affected are thread-local to this handler
        try:
            block = srv.engine.execute(sql, session=session)
            kind = srv.engine.last_stats.kind
            if kind in ("select", "setop", "explain"):
                return self._rows(block) \
                    + _ready(self._status(session))
            n = getattr(srv.engine, "last_rows_affected", 0)
        except Exception as e:               # noqa: BLE001 — wire boundary
            if session.tx is not None:
                self._aborted = True
            return _error(f"{type(e).__name__}: {e}") \
                + _ready(self._status(session))
        tag = {"insert": f"INSERT 0 {n}",
               "update": f"UPDATE {n}",
               "delete": f"DELETE {n}",
               "begin": "BEGIN", "commit": "COMMIT",
               "rollback": "ROLLBACK",
               **self._DDL_TAGS}.get(kind, kind.upper())
        return _msg(b"C", _cstr(tag)) + _ready(self._status(session))

    @staticmethod
    def _row_desc(block) -> bytes:
        """RowDescription ('T') for a result block."""
        desc = struct.pack("!H", len(block.schema.columns))
        for c in block.schema.columns:
            oid, _enc = _oid_and_enc(c.dtype.kind.value)
            desc += _cstr(c.name) + struct.pack("!IHIhih", 0, 0, oid, -1,
                                                -1, 0)
        return _msg(b"T", desc)

    @staticmethod
    def _data_rows(block) -> bytes:
        """DataRow ('D') stream, serialized straight from the column
        arrays — no pandas on this thread (pyarrow-backed DataFrame
        construction is not safe off the main thread in this image)."""
        encs, series = [], []
        for c in block.schema.columns:
            _oid, enc = _oid_and_enc(c.dtype.kind.value)
            encs.append(enc)
            cd = block.columns[c.name]
            if c.dtype.is_string and cd.dictionary is not None:
                vals = cd.dictionary.decode(cd.data)
            else:
                vals = cd.data
            series.append((vals, cd.valid))
        chunks = []                      # list + join: linear, not O(n^2)
        ncols_hdr = struct.pack("!H", len(series))
        null_cell = struct.pack("!i", -1)
        for i in range(block.length):
            body = [ncols_hdr]
            for (vals, valid), enc in zip(series, encs):
                v = vals[i]
                if v is None or (valid is not None and not valid[i]) \
                        or (isinstance(v, float) and v != v):
                    body.append(null_cell)
                else:
                    if hasattr(v, "item"):
                        v = v.item()
                    text = enc(v).encode()
                    body.append(struct.pack("!I", len(text)) + text)
            chunks.append(_msg(b"D", b"".join(body)))
        return b"".join(chunks)

    @classmethod
    def _rows(cls, block) -> bytes:
        """Simple-query result: RowDescription + DataRows + tag."""
        return cls._row_desc(block) + cls._data_rows(block) \
            + _msg(b"C", _cstr(f"SELECT {block.length}"))


class PgServer:
    """Threaded pgwire listener over an embedded engine."""

    def __init__(self, engine, port: int = 0, host: str = "127.0.0.1"):
        self.engine = engine

        class _TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
        self._tcp = _TCP((host, port), _Handler)
        self._tcp.owner = self            # type: ignore[attr-defined]
        self.port = self._tcp.server_address[1]
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()


def serve_pg(engine, port: int = 0) -> PgServer:
    return PgServer(engine, port)
