"""HTTP/JSON front — the reference's HTTP proxy seat.

The reference serves HTTP next to gRPC (`ydb/core/http_proxy`, the
serverless YDB JSON API + monitoring endpoints). This front exposes the
same engine over plain HTTP so curl-class clients need no gRPC stack:

  POST /query      {"sql": "...", "session_id": "...?"}
                   → {"columns": [...], "rows": [[...]], "stats": {...}}
  GET  /health     → the same payload as the gRPC Health RPC
  GET  /counters   → {"counters": {...}} (monitoring scrape endpoint)
  GET  /metrics    → OpenMetrics text exposition (Prometheus scrape):
                   every counter with its COUNTER_REGISTRY # HELP doc,
                   histograms as cumulative buckets
  GET  /trace/<id> → Chrome trace-event JSON of the profiled query with
                   that trace_id (`.sys/query_profiles` is the index) —
                   load it straight into Perfetto / chrome://tracing.
                   404 when the id left the profile ring; 409 under
                   YDB_TPU_CRITPATH=0 (export disabled)
  GET  /ready      → 200 "ok" (liveness probe)

Bearer auth mirrors the gRPC front: `Authorization: Bearer <token>`
when the server was started with one. Statement semantics (sessions,
transactions, concurrency) are the engine's own — this is a thin
protocol adapter over exactly the code path the gRPC servicer uses."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class HttpFront:
    def __init__(self, engine, port: int = 0, host: str = "127.0.0.1",
                 token: str = ""):
        from ydb_tpu.server.service import QueryServicer
        servicer = QueryServicer(engine, token=token)
        front = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):      # noqa: N802 — stdlib name
                pass

            def _send(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _token(self) -> str:
                auth = self.headers.get("Authorization", "")
                return auth[7:] if auth.startswith("Bearer ") else ""

            def do_GET(self):               # noqa: N802 — stdlib name
                if self.path == "/ready":
                    self._send(200, {"ok": True})
                elif self.path == "/health":
                    self._send(200, servicer.health({}, None))
                elif self.path == "/counters":
                    resp = servicer.counters({"token": self._token()},
                                             None)
                    self._send(401 if "error" in resp else 200, resp)
                elif self.path == "/metrics":
                    # OpenMetrics exposition — same auth as /counters
                    # (Prometheus sends the token via bearer_token config)
                    resp = servicer.counters({"token": self._token()},
                                             None)
                    if "error" in resp:
                        self._send(401, resp)
                        return
                    from ydb_tpu.utils.metrics import render_openmetrics
                    body = render_openmetrics(
                        resp.get("counters", {})).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.startswith("/trace/"):
                    # timeline export (same auth as /counters): one
                    # profiled query as Perfetto-loadable Chrome trace
                    # events, keyed by trace_id
                    resp = servicer.counters({"token": self._token()},
                                             None)
                    if "error" in resp:
                        self._send(401, resp)
                        return
                    from ydb_tpu.utils import chrometrace, critpath
                    if not critpath.enabled():
                        self._send(409, {
                            "error": "trace export disabled "
                                     "(YDB_TPU_CRITPATH=0)"})
                        return
                    try:
                        qid = int(self.path[len("/trace/"):])
                    except ValueError:
                        self._send(400, {"error": "trace id must be the "
                                                  "integer trace_id"})
                        return
                    prof = next(
                        (p for p in reversed(list(engine.profiles))
                         if int(p.get("trace_id", 0)) == qid), None)
                    if prof is None:
                        self._send(404, {
                            "error": f"no profile for trace_id {qid} "
                                     "(ring holds the last "
                                     f"{engine.profiles.maxlen})"})
                        return
                    self._send(200, chrometrace.render(prof))
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):              # noqa: N802 — stdlib name
                if self.path != "/query":
                    self._send(404, {"error": f"no route {self.path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._send(400, {"error": f"bad request: {e}"})
                    return
                req["token"] = self._token()
                # ThreadingHTTPServer: one worker thread per request, so
                # concurrent POSTs drive the engine's dispatch→readout
                # pipeline in parallel (overlap shows up in the
                # pipeline/* counters at /counters)
                from ydb_tpu.utils.metrics import GLOBAL
                GLOBAL.inc("server/http_queries")
                resp = servicer.execute_query(req, None)
                if "error" in resp:
                    code = 401 if "Unauthenticated" in resp["error"] \
                        else 400
                    self._send(code, resp)
                else:
                    self._send(200, resp)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def serve_http(engine, port: int = 0, token: str = "") -> HttpFront:
    return HttpFront(engine, port=port, token=token)
