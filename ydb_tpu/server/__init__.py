from ydb_tpu.server.service import Client, serve  # noqa: F401
