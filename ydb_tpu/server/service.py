"""gRPC query service — the public API surface.

The reference exposes Ydb.* gRPC services (`ydb/public/api/grpc/
ydb_query_v1.proto` QueryService.ExecuteQuery, routed by
`grpc_services/grpc_request_proxy.cpp` into KQP). This server keeps the
same shape — a network QueryService speaking gRPC — with JSON message
bodies via custom (de)serializers instead of generated protobuf stubs
(grpc-python supports arbitrary serializers; the wire protocol is still
HTTP/2 gRPC framing).

Methods (service `ydb_tpu.QueryService`):
  ExecuteQuery  {sql, session_id?} → {columns, rows, stats} | {error}
                session_id scopes interactive transactions (BEGIN/COMMIT
                land on that session's state, the session-actor model)
  Counters      {} → {counters}
  Ping          {} → {ok: true}

Statement execution is serialized under one lock: the engine's caches and
the single TPU dispatch stream are not thread-safe, and the reference
likewise runs a session's statements sequentially.
"""

from __future__ import annotations

import json
import os
import threading
from concurrent import futures


def _ser(obj) -> bytes:
    return json.dumps(obj).encode()


def _deser(data: bytes):
    return json.loads(data.decode()) if data else {}


SERVICE = "ydb_tpu.QueryService"

# every shuffle temp the router materializes via ChannelOpen carries this
# prefix (`cluster/router.py` temp_of) — the channel RPCs refuse to touch
# tables outside the namespace, so a (even authed) caller can never drop
# or replace a real user table through the exchange plane
SHUFFLE_TMP_PREFIX = "__xj_"


def _frame_rows(df) -> list:
    """JSON-safe row lists (NaN/NaT → None, numpy scalars unboxed)."""
    rows = []
    for row in df.itertuples(index=False):
        out = []
        for v in row:
            if v is None or (isinstance(v, float) and v != v):
                out.append(None)
            elif hasattr(v, "item"):
                out.append(v.item())
            else:
                out.append(v)
        rows.append(out)
    return rows


def _result_payload(block, stats) -> dict:
    df = block.to_pandas()
    rows = _frame_rows(df)
    return {
        "columns": list(df.columns),
        "rows": rows,
        "stats": {
            "total_ms": stats.total_ms,
            "rows_out": stats.rows_out,
            "plan_cache_hit": stats.plan_cache_hit,
            "path": ("distributed" if stats.distributed
                     else "fused" if stats.fused else "portioned"),
        } if stats is not None else {},
    }


def health_snapshot(engine) -> dict:
    """The engine-level health payload, shared by the gRPC Health RPC
    and LocalWorker.health so the two surfaces cannot drift (graftlint
    rpc-surface discipline). Lock-free by design — a liveness probe
    must answer while a long query holds the execution lock. Callers
    layer their transport-specific fields (sessions, uptime) on top."""
    import jax
    tables = [n for n, t in list(engine.catalog.tables.items())
              if not getattr(t, "transient", False)]
    issues = []
    try:
        devs = jax.devices()
        platform = devs[0].platform if devs else "none"
    except Exception as e:                   # noqa: BLE001
        platform, issues = "unavailable", [f"device: {e}"]
    return {
        "status": "GOOD" if not issues else "DEGRADED",
        "issues": issues,
        "tables": len(tables),
        "topics": len(engine.topics),
        "durable": engine.catalog.store is not None,
        "platform": platform,
    }


MAX_SESSIONS = 256

import time as _time  # noqa: E402

_STARTED = _time.monotonic()


class QueryServicer:
    def __init__(self, engine, max_sessions: int = MAX_SESSIONS,
                 token: str = ""):
        import os
        import threading
        from collections import OrderedDict
        self.engine = engine
        # the engine locks its own write path internally now; SELECTs run
        # concurrently across the gRPC thread pool over MVCC snapshots.
        # This lock only guards the servicer's session table.
        self._lock = threading.Lock()
        self._sessions: "OrderedDict" = OrderedDict()   # guarded-by: _lock
        self._max_sessions = max_sessions
        # minimal bearer auth (ydb/core/security token check, radically
        # simplified): empty = open access; Ping/Health stay open (probes)
        self._token = token or os.environ.get("YDB_TPU_AUTH_TOKEN", "")
        # concurrent-RPC gauge: worker threads drive the engine's query
        # pipeline directly, so this also shows how many RPCs genuinely
        # overlap dispatch/readout (exported with engine.counters())
        self._rpc_mu = threading.Lock()
        self._rpc_inflight = 0           # guarded-by: _rpc_mu

    def _rpc_enter(self, gauge: str) -> None:
        from ydb_tpu.utils.metrics import GLOBAL
        with self._rpc_mu:
            self._rpc_inflight += 1
            # lint: allow-counters(gauge = server/rpc_in_flight, registered)
            GLOBAL.set(gauge, self._rpc_inflight)

    def _rpc_exit(self, gauge: str) -> None:
        from ydb_tpu.utils.metrics import GLOBAL
        with self._rpc_mu:
            self._rpc_inflight -= 1
            # lint: allow-counters(gauge = server/rpc_in_flight, registered)
            GLOBAL.set(gauge, self._rpc_inflight)

    def _authed(self, request) -> bool:
        import hmac
        return not self._token or hmac.compare_digest(
            str(request.get("token", "")), self._token)

    def _session_locked(self, session_id):
        """Resolve-or-create a session. `_locked`: the CALLER holds
        `_lock` — gRPC pool threads resolve sessions concurrently, and
        unlocked two requests with one fresh session_id both built an
        engine session (the loser leaked, staged tx and all) while the
        LRU popitem raced close_session's pop. The lock is taken at the
        call site (not here) so the resolve stays one acquisition on
        the per-RPC hot path — the convention graftlint's locks pass
        checks on both sides."""
        if not session_id:
            return None                      # default (autocommit) session
        s = self._sessions.get(session_id)
        if s is None:
            s = self.engine.session()
            self._sessions[session_id] = s
            # bounded session table: evict the least-recently-used
            # idle session (rolling back any open tx) — abandoned
            # clients must not pin staged writes forever
            while len(self._sessions) > self._max_sessions:
                _sid, old = self._sessions.popitem(last=False)
                if old.tx is not None:
                    old.rollback()
        else:
            self._sessions.move_to_end(session_id)
        return s

    def close_session(self, request, context):
        sid = request.get("session_id")
        with self._lock:
            s = self._sessions.pop(sid, None)
            if s is not None and s.tx is not None:
                s.rollback()
        return {"ok": True}

    def execute_query(self, request, context):
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        sql = request.get("sql", "")
        # each worker thread drives the engine's dispatch→readout
        # pipeline end to end; concurrent RPCs overlap inside the engine
        # (bounded by engine.pipeline_window + memory admission)
        self._rpc_enter("server/rpc_in_flight")
        try:
            with self._lock:
                session = self._session_locked(request.get("session_id"))
            block = self.engine.execute(sql, session=session)
            stats = getattr(self.engine, "last_stats", None)
            return _result_payload(block, stats)
        except Exception as e:               # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}
        finally:
            self._rpc_exit("server/rpc_in_flight")

    def counters(self, request, context):
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        return {"counters": self.engine.counters()}

    def prog_store_stats(self, request, context):
        """Persistent program-store snapshot (the zero-compile serving
        surface): store inventory + hit/miss/corrupt/refused counters +
        the admission backlog a compile-ahead fill overlaps with. The
        warm-start workflow polls this after restart to confirm every
        dispatched shape came from disk."""
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        try:
            from ydb_tpu.progstore import store as prog_store
            snap = prog_store.stats()
            snap["admission"] = self.engine.admission.backlog()
            return {"store": snap}
        except Exception as e:               # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}

    # -- worker<->worker exchange (DQ channel data plane) ------------------
    #
    # The DQ task runner (`ydb_tpu/dq/runner.py`) drives stage graphs:
    # DqRunTask runs one task — a stage SQL whose output routes over the
    # task's channels (hash-shuffled / broadcast to peers' ExchangePut as
    # binary frames, or collected in the response for router-bound
    # channels); ChannelOpen materializes a drained channel as a
    # transient table so the next stage is ordinary local SQL over
    # co-partitioned data; DqTasks lists task states (pending → running
    # → finished/failed) for observability.

    @property
    def exchange(self):
        from ydb_tpu.cluster.exchange import ExchangeBuffer
        buf = getattr(self, "_exchange", None)
        if buf is None:
            buf = self._exchange = ExchangeBuffer()
        return buf

    def exchange_put(self, request: bytes, context):
        import hmac

        from ydb_tpu.cluster.exchange import unpack_frame, unpack_header
        try:
            # auth BEFORE deserialization: the npz payload allows pickle
            # (trusted-cluster format) — only the JSON header may be
            # parsed pre-auth
            header = unpack_header(request)
            if self._token and not hmac.compare_digest(
                    str(header.get("token", "")), self._token):
                return {"error": "Unauthenticated: invalid or missing "
                                 "token"}
            header, df = unpack_frame(request)
            # (src, seq)-deduplicated: a retried put whose first attempt
            # landed (reply lost) is dropped — idempotent redelivery
            fresh = self.exchange.put(header["channel"], df, len(request),
                                      src=str(header.get("src", "")),
                                      seq=header.get("seq"))
            return {"ok": True, "rows": len(df), "dup": not fresh}
        except Exception as e:               # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}

    def dq_run_task(self, request, context):
        """Run one DQ task (stage program + output channel routing) —
        the task-control RPC of the stage/task/channel runtime
        (`ydb_tpu/dq/task.py` holds the shared execution core)."""
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        from collections import OrderedDict

        from ydb_tpu.dq import task as dq_task
        tid = str(request.get("task_id", ""))
        with self._lock:
            tasks = self.__dict__.setdefault("_dq_tasks", OrderedDict())
            rec = tasks.setdefault(tid, {"stage": request.get("stage", ""),
                                         "attempts": 0})
            rec["state"] = "running"
            rec["attempts"] += 1
            tasks.move_to_end(tid)
            while len(tasks) > 512:          # bounded task table
                tasks.popitem(last=False)
        try:
            if any(o.get("plane") == "ici"
                   for o in request.get("outputs") or []):
                # an ICI-plane edge only lowers between in-process mesh
                # workers; a gRPC worker has no shared mesh to ride and
                # no way to ship a by-reference frame — refuse loudly so
                # the runner's host-plane fallback takes over (state
                # stamped failed like every other error path: the task
                # table must never show a phantom running task)
                msg = ("IciPlaneError: ici-plane task sent to a gRPC "
                       "worker (no shared mesh)")
                with self._lock:
                    rec["state"] = "failed"
                    rec["error"] = msg
                return {"error": msg}

            def send(out, p, frame):
                ExchangeClient(out["peers"][p]).put(frame)

            resp = dq_task.run_task(
                self.engine, request["sql"], request.get("outputs") or [],
                str(request.get("src", "")), send, token=self._token,
                trace=request.get("trace"))
            if "collected_df" in resp:
                df = resp.pop("collected_df")
                resp["collected"] = {"columns": list(df.columns),
                                     "rows": _frame_rows(df)}
            with self._lock:
                rec["state"] = "finished"
            return resp
        except Exception as e:               # noqa: BLE001 — wire boundary
            with self._lock:
                rec["state"] = "failed"
                rec["error"] = f"{type(e).__name__}: {e}"
            return {"error": f"{type(e).__name__}: {e}"}

    def dq_tasks(self, request, context):
        """Task table snapshot (state machine observability)."""
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        with self._lock:
            # per-record copies: running task threads mutate the inner
            # dicts under the same lock, so the reply serializes a
            # consistent snapshot instead of racing json.dumps
            tasks = {k: dict(v)
                     for k, v in (self.__dict__.get("_dq_tasks")
                                  or {}).items()}
        return {"tasks": tasks}

    def channel_open(self, request, context):
        """Materialize a drained channel as a transient local table."""
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        from ydb_tpu.dq.task import materialize_channel
        try:
            name = request["table"]
            if not str(name).startswith(SHUFFLE_TMP_PREFIX):
                # drop the channel's queued frames too: a refused open
                # must not leave them parked in the exchange buffer
                # forever (repeated rejected opens would leak unbounded
                # server memory)
                self.exchange.drop(request.get("channel", ""))
                return {"error": f"ChannelOpen: table {name!r} is outside "
                                 f"the {SHUFFLE_TMP_PREFIX}* shuffle-temp "
                                 "namespace"}
            stats = materialize_channel(self.engine, self.exchange,
                                        request["channel"], name,
                                        request.get("columns"))
            return {"ok": True, **stats}
        except Exception as e:               # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}

    # -- distributed two-phase commit (cluster/dtx.py) ---------------------

    @property
    def _dtx_journal(self):
        from ydb_tpu.cluster.dtx import DtxJournal
        j = getattr(self, "_dtx_j", None)
        if j is None:
            store = self.engine.catalog.store
            root = store.root if store is not None else None
            if root is None:
                return None              # no durability: 2PC refuses
            j = self._dtx_j = DtxJournal(os.path.join(root, "dtx.jsonl"))
        return j

    def _maybe_crash(self, request, point: str) -> None:
        """Test-only fault injection (the nemesis hook the reference's
        test runtime provides via event interception): honored only when
        the worker opted in via YDB_TPU_TEST_FAULTS=1."""
        if os.environ.get("YDB_TPU_TEST_FAULTS") == "1" \
                and request.get("crash_point") == point:
            os._exit(137)

    def tx_prepare(self, request, context):
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        j = self._dtx_journal
        if j is None:
            return {"error": "2PC needs a durable worker (no data_dir)"}
        gtx = request["gtx"]
        sqls = request["sqls"]
        s = None
        try:
            s = self.engine.session()
            s.execute("begin")
            for sql in sqls:
                s.execute(sql)
            j.append({"op": "prepared", "gtx": gtx, "sqls": sqls})
            self._maybe_crash(request, "after_prepare")
            with self._lock:
                self.__dict__.setdefault("_dtx_live", {})[gtx] = s
            return {"ok": True}
        except Exception as e:               # noqa: BLE001 — wire boundary
            # roll the partial session back: a leaked open tx pins its
            # coordinator snapshot (blocking compaction) and holds
            # staged writes forever
            if s is not None and s.tx is not None:
                try:
                    s.rollback()
                except Exception:            # noqa: BLE001
                    pass
            return {"error": f"{type(e).__name__}: {e}"}

    def tx_decide(self, request, context):
        """Phase 2 on a LIVE worker: apply the decision to the held
        session, then mark done."""
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        j = self._dtx_journal
        gtx = request["gtx"]
        decision = request["decision"]
        try:
            with self._lock:
                s = self.__dict__.setdefault("_dtx_live", {}).pop(gtx, None)
            self._maybe_crash(request, "before_apply")
            if s is not None:
                if decision == "commit":
                    s.commit()
                else:
                    s.rollback()
            elif decision == "commit":
                # no live session (restarted since prepare): re-execute
                # from the journal — upsert idempotence
                return self.tx_resolve(request, context)
            self._maybe_crash(request, "after_apply")
            j.append({"op": "done", "gtx": gtx, "decision": decision})
            return {"ok": True}
        except Exception as e:               # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}

    def tx_resolve(self, request, context):
        """Recovery: the router re-delivers the durable decision for an
        in-doubt gtx. Commit re-executes the logged statements (UPSERT
        idempotence — safe whether or not the crashed apply landed);
        abort just closes the record (staged writes died with the
        process)."""
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        j = self._dtx_journal
        gtx = request["gtx"]
        decision = request["decision"]
        try:
            # a still-live prepared session (prepare succeeded but the
            # reply was lost) resolves like a decide — never leak it
            with self._lock:
                live = self.__dict__.setdefault("_dtx_live", {}).pop(
                    gtx, None)
            if live is not None:
                if decision == "commit":
                    live.commit()
                else:
                    live.rollback()
                j.append({"op": "done", "gtx": gtx, "decision": decision})
                return {"ok": True, "state": "resolved-live"}
            rec = j.in_doubt().get(gtx)
            if rec is None:
                return {"ok": True, "state": "already-done"}
            if decision == "commit":
                s = self.engine.session()
                s.execute("begin")
                try:
                    for sql in rec["sqls"]:
                        s.execute(sql)
                    s.commit()
                except Exception:
                    if s.tx is not None:
                        s.rollback()
                    raise
            j.append({"op": "done", "gtx": gtx, "decision": decision})
            return {"ok": True, "state": "resolved"}
        except Exception as e:               # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}

    def tx_in_doubt(self, request, context):
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        j = self._dtx_journal
        return {"gtx": sorted(j.in_doubt()) if j is not None else []}

    def channel_close(self, request, context):
        # auth like every other mutating RPC (the r5 version skipped the
        # check — an unauthenticated client could drop arbitrary tables)
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        try:
            tables = [str(n) for n in request.get("tables", [])]
            bad = [n for n in tables
                   if not n.startswith(SHUFFLE_TMP_PREFIX)]
            # same invariant as ChannelOpen: a durable table squatting in
            # the namespace is not ours to clobber either
            durable = [n for n in tables
                       if n not in bad and self.engine.catalog.has(n)
                       and not getattr(self.engine.catalog.table(n),
                                       "transient", False)]
            if bad or durable:
                # refuse ALL table drops (the exchange plane only ever
                # owns __xj_* transient temps) — but still free the
                # request's channel buffers: close is the cleanup RPC,
                # and a refusal must not leave frames parked forever
                for ch in request.get("channels", []):
                    self.exchange.drop(ch)
                return {"error": f"ChannelClose: refusing "
                                 f"{bad + durable} — outside the "
                                 f"{SHUFFLE_TMP_PREFIX}* shuffle-temp "
                                 "namespace or non-transient"}
            for name in tables:
                if self.engine.catalog.has(name):
                    self.engine.catalog.drop_table(name)
            for ch in request.get("channels", []):
                self.exchange.drop(ch)
            return {"ok": True}
        except Exception as e:               # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}

    # -- Hive control plane (ydb_tpu/hive/) --------------------------------
    #
    # The server hosting the Hive (engine.hive attached — typically a
    # router candidate) serves membership: workers push HiveRegister
    # once and HiveHeartbeat at lease/3 (`hive/agent.py`); HiveNodes is
    # the ops-facing snapshot (`.sys/cluster_nodes` serves the same rows
    # through SQL). HiveAdoptShard runs on WORKERS: the Hive's failover
    # tells a survivor to replay a dead peer's shard image into its own
    # tables (`hive/adopt.py`).

    def _hive(self):
        return getattr(self.engine, "hive", None)

    def hive_register(self, request, context):
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        hive = self._hive()
        if hive is None:
            return {"error": "no Hive hosted on this node"}
        try:
            return hive.register_worker(
                endpoint=str(request.get("endpoint", "")),
                node_id=str(request.get("node_id", "")),
                capacity=float(request.get("capacity", 1.0)),
                shards=list(request.get("shards") or []))
        except Exception as e:               # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}

    def hive_heartbeat(self, request, context):
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        hive = self._hive()
        if hive is None:
            return {"error": "no Hive hosted on this node"}
        try:
            load = request.get("load")
            return hive.heartbeat(str(request.get("node_id", "")),
                                  load=None if load is None
                                  else float(load))
        except Exception as e:               # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}

    def hive_nodes(self, request, context):
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        hive = self._hive()
        if hive is None:
            return {"error": "no Hive hosted on this node"}
        # membership-level sweep only, like `.sys/cluster_nodes`: a
        # monitoring poll must show expired leases as dead but must
        # never trigger re-placement data movement inline
        hive.membership.sweep()
        return {"nodes": hive.rows(), "epoch": hive.epoch}

    def hive_adopt_shard(self, request, context):
        """Replay a shard image (a dead peer's standby mirror root) into
        this worker's tables — the re-placement data plane."""
        if not self._authed(request):
            return {"error": "Unauthenticated: invalid or missing token"}
        from ydb_tpu.hive.adopt import adopt_shard
        try:
            root = str(request["root"])
            copied = adopt_shard(self.engine, root,
                                 request.get("tables"))
            return {"ok": True, "copied": copied}
        except Exception as e:               # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}

    def ping(self, request, context):
        return {"ok": True}

    def health(self, request, context):
        """Aggregated health (the health_check.cpp analog): engine
        liveness, storage mode, device platform, and basic capacity.
        Deliberately LOCK-FREE — a liveness probe must answer while a
        long query holds the execution lock, and reading approximate
        counts needs no consistency."""
        import time
        return {
            **health_snapshot(self.engine),
            "sessions": len(self._sessions),
            "uptime_s": round(time.monotonic() - _STARTED, 1),
        }


def serve(engine, port: int = 2136, max_workers: int = 8,
          token: str = ""):
    """Start the gRPC server; returns (server, bound_port). `token`
    (or $YDB_TPU_AUTH_TOKEN): require it on query/counters calls."""
    import grpc

    servicer = QueryServicer(engine, token=token)
    handlers = {
        "ExecuteQuery": grpc.unary_unary_rpc_method_handler(
            servicer.execute_query, request_deserializer=_deser,
            response_serializer=_ser),
        "Counters": grpc.unary_unary_rpc_method_handler(
            servicer.counters, request_deserializer=_deser,
            response_serializer=_ser),
        "ProgStoreStats": grpc.unary_unary_rpc_method_handler(
            servicer.prog_store_stats, request_deserializer=_deser,
            response_serializer=_ser),
        "Ping": grpc.unary_unary_rpc_method_handler(
            servicer.ping, request_deserializer=_deser,
            response_serializer=_ser),
        "CloseSession": grpc.unary_unary_rpc_method_handler(
            servicer.close_session, request_deserializer=_deser,
            response_serializer=_ser),
        "Health": grpc.unary_unary_rpc_method_handler(
            servicer.health, request_deserializer=_deser,
            response_serializer=_ser),
        # exchange data plane: binary request frames (npz), JSON replies
        "ExchangePut": grpc.unary_unary_rpc_method_handler(
            servicer.exchange_put, request_deserializer=lambda b: b,
            response_serializer=_ser),
        "DqRunTask": grpc.unary_unary_rpc_method_handler(
            servicer.dq_run_task, request_deserializer=_deser,
            response_serializer=_ser),
        "DqTasks": grpc.unary_unary_rpc_method_handler(
            servicer.dq_tasks, request_deserializer=_deser,
            response_serializer=_ser),
        "ChannelOpen": grpc.unary_unary_rpc_method_handler(
            servicer.channel_open, request_deserializer=_deser,
            response_serializer=_ser),
        "ChannelClose": grpc.unary_unary_rpc_method_handler(
            servicer.channel_close, request_deserializer=_deser,
            response_serializer=_ser),
        "TxPrepare": grpc.unary_unary_rpc_method_handler(
            servicer.tx_prepare, request_deserializer=_deser,
            response_serializer=_ser),
        "TxDecide": grpc.unary_unary_rpc_method_handler(
            servicer.tx_decide, request_deserializer=_deser,
            response_serializer=_ser),
        "TxResolve": grpc.unary_unary_rpc_method_handler(
            servicer.tx_resolve, request_deserializer=_deser,
            response_serializer=_ser),
        "TxInDoubt": grpc.unary_unary_rpc_method_handler(
            servicer.tx_in_doubt, request_deserializer=_deser,
            response_serializer=_ser),
        # Hive control plane: membership (on the Hive host) + shard
        # adoption (on workers)
        "HiveRegister": grpc.unary_unary_rpc_method_handler(
            servicer.hive_register, request_deserializer=_deser,
            response_serializer=_ser),
        "HiveHeartbeat": grpc.unary_unary_rpc_method_handler(
            servicer.hive_heartbeat, request_deserializer=_deser,
            response_serializer=_ser),
        "HiveNodes": grpc.unary_unary_rpc_method_handler(
            servicer.hive_nodes, request_deserializer=_deser,
            response_serializer=_ser),
        "HiveAdoptShard": grpc.unary_unary_rpc_method_handler(
            servicer.hive_adopt_shard, request_deserializer=_deser,
            response_serializer=_ser),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_send_message_length", 256 << 20),
                 ("grpc.max_receive_message_length", 256 << 20)])
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    server.start()
    return server, bound


class ExchangeClient:
    """Data-plane client: ships one binary channel frame to a peer."""

    _channels: dict = {}
    _mu = threading.Lock()

    def __init__(self, endpoint: str):
        import grpc
        # channel reuse: a shuffle sends many frames to few peers — a
        # fresh HTTP/2 connection per frame would dominate small shuffles
        with ExchangeClient._mu:
            ch = ExchangeClient._channels.get(endpoint)
            if ch is None:
                ch = grpc.insecure_channel(endpoint, options=[
                    ("grpc.max_send_message_length", 256 << 20),
                    ("grpc.max_receive_message_length", 256 << 20)])
                ExchangeClient._channels[endpoint] = ch
        self._put = ch.unary_unary(
            f"/{SERVICE}/ExchangePut",
            request_serializer=lambda b: b,
            response_deserializer=_deser)

    def put(self, frame: bytes) -> dict:
        resp = self._put(frame)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp


class Client:
    """Minimal SDK client (the ydb-sdk QueryClient analog)."""

    def __init__(self, endpoint: str, session_id: str = "",
                 token: str = ""):
        import grpc

        self.endpoint = endpoint
        self.token = token
        # same max-message override as the server: DqRunTask responses
        # carry router-bound collected frames that can exceed gRPC's
        # stock 4 MiB cap
        self._channel = grpc.insecure_channel(
            endpoint,
            options=[("grpc.max_send_message_length", 256 << 20),
                     ("grpc.max_receive_message_length", 256 << 20)])
        self._exec = self._channel.unary_unary(
            f"/{SERVICE}/ExecuteQuery", request_serializer=_ser,
            response_deserializer=_deser)
        self._counters = self._channel.unary_unary(
            f"/{SERVICE}/Counters", request_serializer=_ser,
            response_deserializer=_deser)
        self._prog_store_stats = self._channel.unary_unary(
            f"/{SERVICE}/ProgStoreStats", request_serializer=_ser,
            response_deserializer=_deser)
        self._ping = self._channel.unary_unary(
            f"/{SERVICE}/Ping", request_serializer=_ser,
            response_deserializer=_deser)
        self._health = self._channel.unary_unary(
            f"/{SERVICE}/Health", request_serializer=_ser,
            response_deserializer=_deser)
        self._dq_run = self._channel.unary_unary(
            f"/{SERVICE}/DqRunTask", request_serializer=_ser,
            response_deserializer=_deser)
        self._dq_tasks = self._channel.unary_unary(
            f"/{SERVICE}/DqTasks", request_serializer=_ser,
            response_deserializer=_deser)
        self._chopen = self._channel.unary_unary(
            f"/{SERVICE}/ChannelOpen", request_serializer=_ser,
            response_deserializer=_deser)
        self._chclose = self._channel.unary_unary(
            f"/{SERVICE}/ChannelClose", request_serializer=_ser,
            response_deserializer=_deser)
        self.session_id = session_id

    def execute(self, sql: str) -> dict:
        resp = self._exec({"sql": sql, "session_id": self.session_id,
                           "token": self.token})
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp

    def query(self, sql: str):
        """Execute and return a pandas DataFrame."""
        import pandas as pd

        resp = self.execute(sql)
        return pd.DataFrame(resp["rows"], columns=resp["columns"])

    def counters(self) -> dict:
        resp = self._counters({"token": self.token})
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp["counters"]

    def prog_store_stats(self) -> dict:
        resp = self._prog_store_stats({"token": self.token})
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp["store"]

    def dq_run_task(self, task_id: str, stage: str, sql: str,
                    outputs: list, src: str = "",
                    timeout: float = None, trace: dict = None) -> dict:
        """Run one DQ task (stage program + channel routing) on the
        worker; blocks until the task's frames are delivered. `trace`:
        the propagated {trace_id, parent_span_id, sampled} context —
        the worker records its spans against it and ships them back in
        `resp["profile"]`."""
        resp = self._dq_run({"task_id": task_id, "stage": stage,
                             "sql": sql, "outputs": list(outputs),
                             "src": src, "token": self.token,
                             "trace": trace},
                            timeout=timeout)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp

    def dq_tasks(self, timeout: float = None) -> dict:
        resp = self._dq_tasks({"token": self.token}, timeout=timeout)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp["tasks"]

    def channel_open(self, channel: str, table: str,
                     columns=None, timeout: float = None) -> dict:
        resp = self._chopen({"channel": channel, "table": table,
                             "columns": columns, "token": self.token},
                            timeout=timeout)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp

    def channel_close(self, tables=(), channels=(),
                      timeout: float = None) -> dict:
        return self._chclose({"tables": list(tables),
                              "channels": list(channels),
                              "token": self.token}, timeout=timeout)

    def _dtx_call(self, method: str, body: dict) -> dict:
        stubs = self.__dict__.setdefault("_dtx_stubs", {})
        call = stubs.get(method)
        if call is None:
            call = stubs[method] = self._channel.unary_unary(
                f"/{SERVICE}/{method}", request_serializer=_ser,
                response_deserializer=_deser)
        resp = call({**body, "token": self.token})
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp

    def tx_prepare(self, gtx: str, sqls: list, **extra) -> dict:
        return self._dtx_call("TxPrepare",
                              {"gtx": gtx, "sqls": sqls, **extra})

    def tx_decide(self, gtx: str, decision: str, **extra) -> dict:
        return self._dtx_call("TxDecide",
                              {"gtx": gtx, "decision": decision, **extra})

    def tx_resolve(self, gtx: str, decision: str) -> dict:
        return self._dtx_call("TxResolve",
                              {"gtx": gtx, "decision": decision})

    def tx_in_doubt(self) -> list:
        return self._dtx_call("TxInDoubt", {})["gtx"]

    # -- Hive control plane -------------------------------------------------

    def _hive_call(self, method: str, body: dict,
                   timeout: float = None) -> dict:
        stubs = self.__dict__.setdefault("_hive_stubs", {})
        call = stubs.get(method)
        if call is None:
            call = stubs[method] = self._channel.unary_unary(
                f"/{SERVICE}/{method}", request_serializer=_ser,
                response_deserializer=_deser)
        resp = call({**body, "token": self.token}, timeout=timeout)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp

    def hive_register(self, endpoint: str, node_id: str = "",
                      capacity: float = 1.0, shards=(),
                      timeout: float = None) -> dict:
        return self._hive_call("HiveRegister",
                               {"endpoint": endpoint, "node_id": node_id,
                                "capacity": capacity,
                                "shards": list(shards)}, timeout=timeout)

    def hive_heartbeat(self, node_id: str, load: float = None,
                       timeout: float = None) -> dict:
        return self._hive_call("HiveHeartbeat",
                               {"node_id": node_id, "load": load},
                               timeout=timeout)

    def hive_nodes(self, timeout: float = None) -> dict:
        return self._hive_call("HiveNodes", {}, timeout=timeout)

    def hive_adopt_shard(self, root: str, tables=None,
                         timeout: float = None) -> dict:
        return self._hive_call("HiveAdoptShard",
                               {"root": root, "tables": tables},
                               timeout=timeout)

    def ping(self, timeout: float = None) -> bool:
        return bool(self._ping({}, timeout=timeout).get("ok"))

    def health(self) -> dict:
        return self._health({})

    def close(self) -> None:
        if self.session_id:
            try:
                self._channel.unary_unary(
                    f"/{SERVICE}/CloseSession", request_serializer=_ser,
                    response_deserializer=_deser)(
                        {"session_id": self.session_id})
            except Exception:                # noqa: BLE001 — best effort
                pass
        self._channel.close()
