"""Native runtime loader.

Builds `blobio.cpp` into a shared library with the system toolchain on
first import (cached by source mtime) and exposes it through ctypes. The
reference's storage runtime is native C++ (PDisk/LocalDB); here the
native layer owns the blob/WAL IO floor while JAX/XLA owns the compute
plane. Everything degrades gracefully: if no compiler is present (or
``YDB_TPU_NATIVE=0``), callers fall back to the byte-identical numpy
implementation in `ydb_tpu/storage/blobfile.py`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "blobio.cpp")
_SO = os.path.join(_DIR, f"_blobio_py{sys.version_info[0]}{sys.version_info[1]}.so")

_lib = None
_tried = False


def _build() -> bool:
    try:
        if os.path.exists(_SO) and \
                os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return True
        tmp = f"{_SO}.{os.getpid()}.tmp.so"   # per-pid: concurrent builds
        subprocess.run(                        # must not interleave writes
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp,
             _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except Exception:
        return False


def lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("YDB_TPU_NATIVE", "1") == "0":
        return None
    if not _build():
        return None
    try:
        L = ctypes.CDLL(_SO)
        L.ydbt_abi_version.restype = ctypes.c_int
        if L.ydbt_abi_version() != 2:
            return None
        L.ydbt_crc32.restype = ctypes.c_uint32
        L.ydbt_crc32.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        L.ydbt_write_portion.restype = ctypes.c_int
        L.ydbt_write_portion.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64)]
        L.ydbt_wal_append.restype = ctypes.c_int
        L.ydbt_wal_append.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_int32]
        L.ydbt_wal_scan.restype = ctypes.c_int64
        L.ydbt_wal_scan.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                    ctypes.POINTER(ctypes.c_uint64),
                                    ctypes.POINTER(ctypes.c_int32)]
        _lib = L
    except OSError:
        _lib = None
    return _lib


def available() -> bool:
    return lib() is not None
