// Native storage engine: CRC-framed blob + WAL primitives.
//
// The reference's persistence stack is native C++ end to end — PDisk owns
// raw chunks with checksummed log framing (ydb/core/blobstorage/pdisk/
// blobstorage_pdisk_impl.h:46), and LocalDB replays a redo log at boot
// (ydb/core/tablet_flat/flat_boot_*.h). This library is the TPU build's
// equivalent runtime floor: portion blobs and the write-ahead log go
// through these routines when the toolchain is present; a byte-identical
// pure-numpy fallback lives in ydb_tpu/storage/blobfile.py.
//
// Format invariants shared with the Python fallback:
//   * CRC-32 (zlib polynomial 0xEDB88320) — matches python zlib.crc32, so
//     files written by either implementation verify under the other.
//   * Portion files:  "YDBP" | u32 version | u32 header_len | u32 header_crc
//                     | header JSON | zero-pad to 64 | sections (64-aligned)
//   * WAL records:    u32 payload_len | u32 payload_crc | payload
//     (replay stops at the first short/corrupt frame = torn tail).
//
// Durability: section writes go through one buffered file, fsync before
// the atomic rename (portions); WAL appends are O_APPEND + fdatasync.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <string>
#include <unistd.h>
#include <vector>

namespace {

// ---- CRC-32 (zlib polynomial), slice-by-8 ----------------------------

uint32_t crc_tab[8][256];
bool crc_init_done = false;

void crc_init() {
    if (crc_init_done) return;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_tab[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
        for (int t = 1; t < 8; t++)
            crc_tab[t][i] =
                (crc_tab[t - 1][i] >> 8) ^ crc_tab[0][crc_tab[t - 1][i] & 0xff];
    crc_init_done = true;
}

uint32_t crc32_update(uint32_t crc, const uint8_t* p, size_t n) {
    crc_init();
    crc = ~crc;
    while (n && (reinterpret_cast<uintptr_t>(p) & 7)) {
        crc = crc_tab[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
        n--;
    }
    while (n >= 8) {
        uint64_t w;
        memcpy(&w, p, 8);
        w ^= crc;                       // little-endian assumption (x86/ARM)
        crc = crc_tab[7][w & 0xff] ^ crc_tab[6][(w >> 8) & 0xff] ^
              crc_tab[5][(w >> 16) & 0xff] ^ crc_tab[4][(w >> 24) & 0xff] ^
              crc_tab[3][(w >> 32) & 0xff] ^ crc_tab[2][(w >> 40) & 0xff] ^
              crc_tab[1][(w >> 48) & 0xff] ^ crc_tab[0][(w >> 56) & 0xff];
        p += 8;
        n -= 8;
    }
    while (n--) crc = crc_tab[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
    return ~crc;
}

bool write_all(int fd, const uint8_t* p, size_t n) {
    while (n) {
        ssize_t w = ::write(fd, p, n);
        if (w < 0) {
            if (errno == EINTR) continue;
            return false;
        }
        p += w;
        n -= static_cast<size_t>(w);
    }
    return true;
}

const uint8_t ZEROS[64] = {0};

}  // namespace

extern "C" {

// Library self-description (the loader asserts the ABI version).
int ydbt_abi_version() { return 2; }

uint32_t ydbt_crc32(const uint8_t* data, uint64_t len) {
    return crc32_update(0, data, len);
}

// Write a portion blob atomically: header (already JSON-encoded by the
// caller, CRC'd here) + `nsec` sections, each zero-padded to a 64-byte
// boundary. tmp-file + fsync + rename, then fsync the directory so the
// rename itself is durable.
int ydbt_write_portion(const char* path, const uint8_t* header,
                       uint64_t header_len, int32_t nsec,
                       const uint8_t** sec_ptrs, const uint64_t* sec_lens) {
    std::string tmp = std::string(path) + ".tmp";
    int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return -errno;

    uint8_t head[16];
    memcpy(head, "YDBP", 4);
    uint32_t version = 1;
    uint32_t hlen = static_cast<uint32_t>(header_len);
    uint32_t hcrc = crc32_update(0, header, header_len);
    memcpy(head + 4, &version, 4);
    memcpy(head + 8, &hlen, 4);
    memcpy(head + 12, &hcrc, 4);

    bool ok = write_all(fd, head, 16) && write_all(fd, header, header_len);
    uint64_t off = 16 + header_len;
    if (ok && off % 64) {
        ok = write_all(fd, ZEROS, 64 - off % 64);
        off += 64 - off % 64;
    }
    for (int32_t i = 0; ok && i < nsec; i++) {
        ok = write_all(fd, sec_ptrs[i], sec_lens[i]);
        off += sec_lens[i];
        if (ok && off % 64) {
            ok = write_all(fd, ZEROS, 64 - off % 64);
            off += 64 - off % 64;
        }
    }
    if (ok) ok = ::fsync(fd) == 0;
    int saved = errno;
    ::close(fd);
    if (!ok) {
        ::unlink(tmp.c_str());
        return saved ? -saved : -EIO;
    }
    if (::rename(tmp.c_str(), path) != 0) {
        saved = errno;
        ::unlink(tmp.c_str());
        return -saved;
    }
    // make the rename durable: fsync the parent directory
    std::string dir(path);
    size_t slash = dir.rfind('/');
    dir = slash == std::string::npos ? "." : dir.substr(0, slash);
    int dfd = ::open(dir.c_str(), O_RDONLY | O_DIRECTORY);
    if (dfd >= 0) {
        ::fsync(dfd);
        ::close(dfd);
    }
    return 0;
}

// Append one CRC-framed record to the WAL and fdatasync it.
int ydbt_wal_append(const char* path, const uint8_t* payload, uint64_t len,
                    int32_t do_sync) {
    int fd = ::open(path, O_WRONLY | O_CREAT | O_APPEND, 0644);
    if (fd < 0) return -errno;
    uint32_t n = static_cast<uint32_t>(len);
    uint32_t crc = crc32_update(0, payload, len);
    std::vector<uint8_t> frame(8 + len);
    memcpy(frame.data(), &n, 4);
    memcpy(frame.data() + 4, &crc, 4);
    memcpy(frame.data() + 8, payload, len);
    bool ok = write_all(fd, frame.data(), frame.size());
    if (ok && do_sync) ok = ::fdatasync(fd) == 0;
    int saved = errno;
    ::close(fd);
    return ok ? 0 : (saved ? -saved : -EIO);
}

// Scan an already-read WAL buffer, validating frames in order.
// Returns the number of valid records; fills out_valid_bytes with the
// byte length of the valid prefix and out_status with how the scan ended:
//   0 = clean EOF
//   1 = torn tail (an incomplete last frame — the expected crash shape;
//       replay drops it silently, the PDisk log-tail rule)
//   2 = corruption (a COMPLETE frame whose CRC fails, or an implausible
//       length with its bytes present — acked records may follow, so the
//       caller must fail loudly instead of silently truncating history)
int64_t ydbt_wal_scan(const uint8_t* buf, uint64_t len,
                      uint64_t* out_valid_bytes, int32_t* out_status) {
    int64_t count = 0;
    uint64_t off = 0;
    *out_status = 0;
    for (;;) {
        if (off == len) break;                 // clean end
        if (off + 8 > len) { *out_status = 1; break; }
        uint32_t n, crc;
        memcpy(&n, buf + off, 4);
        memcpy(&crc, buf + off + 4, 4);
        if (off + 8 + n > len) {
            // payload extends past EOF: torn tail unless the length is
            // absurd AND most of the file remains (scrambled header)
            *out_status = (n > (1u << 30) && len - off > (1u << 20)) ? 2 : 1;
            break;
        }
        if (n > (1u << 30) ||
            crc32_update(0, buf + off + 8, n) != crc) {
            *out_status = 2;                   // complete frame, bad bytes
            break;
        }
        off += 8 + n;
        count++;
    }
    *out_valid_bytes = off;
    return count;
}

}  // extern "C"
