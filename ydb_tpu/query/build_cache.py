"""Cross-query join-build cache.

The round-4 profile (PERF.md) showed the slow half of TPC-H losing to a
single CPU core because every query RE-EXECUTED its join-build pipelines
and re-uploaded every probe LUT: q2/q5/q7/q9/q21-class plans spend
seconds per run rebuilding identical dimension tables. The reference
amortizes compiled patterns across queries through its computation
pattern cache (`mkql_computation_pattern_cache.h:56`) and reuses scan
state; the TPU-native equivalent is to cache the finished, device-
resident `BuildTable` (sorted keys + payload + direct-address LUT in
HBM) keyed by WHAT it was built from:

  * the structural fingerprint of the build plan (scans, programs,
    nested joins, sort/limit shape),
  * the VALUES of every runtime param the build references,
  * the exact visible data of every table the build scans at the read
    snapshot (the superblock cache's src-id discipline — portions are
    immutable, so the id set IS the data version),
  * the probe-side dictionary the build key was remapped into (held by
    reference: identity + length pin the remap),
  * the join-step shape (key/payload/kind/hash-keys/anti flags) and the
    executor knobs that steer the build (grace budget, mesh arity).

Entries are LRU-evicted under a byte budget of resident bytes (device
HBM for BuildTable, host DRAM for PartitionedBuild — the GraceJoin
partitions are cheap by comparison but still bounded).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

__all__ = ["BuildCache", "build_plan_fingerprint"]


def _hash_param_value(v) -> str:
    if isinstance(v, np.ndarray):
        h = hashlib.sha256()
        h.update(str(v.dtype).encode())
        h.update(str(v.shape).encode())
        h.update(np.ascontiguousarray(v).tobytes())
        return h.hexdigest()[:16]
    if isinstance(v, (np.generic,)):
        return f"{v.dtype}:{v!r}"
    return repr(v)


def _fp_pipeline(pipe, catalog, snapshot, parts: list, pnames: set) -> bool:
    """Fingerprint one Pipeline into `parts`, collecting referenced param
    names. False = uncacheable (row-table scans have no immutable source
    enumeration)."""
    from ydb_tpu.ops.ir import program_params
    from ydb_tpu.storage.device_cache import enumerate_scan_sources

    table = catalog.table(pipe.scan.table)
    try:
        _sources, src_ids = enumerate_scan_sources(
            table, snapshot, pipe.scan.prune or None)
    except AttributeError:
        return False
    parts.append(("scan", table.uid, tuple(pipe.scan.columns),
                  tuple((c, op, repr(v)) for (c, op, v) in
                        (pipe.scan.prune or [])),
                  tuple(src_ids)))

    def prog(p):
        if p is None:
            parts.append("-")
            return
        parts.append(p.fingerprint())
        for prm in program_params(p):
            pnames.add(prm.name)

    prog(pipe.pre_program)
    for kind, step in pipe.steps:
        if kind == "join":
            if not _fp_join_step(step, catalog, snapshot, parts, pnames):
                return False
        else:
            prog(step)
    prog(pipe.partial)
    parts.append(tuple(pipe.out_names))
    return True


def _fp_join_step(step, catalog, snapshot, parts: list, pnames: set) -> bool:
    parts.append(("join", step.build_key, step.probe_key, step.kind,
                  tuple(step.payload), step.mark_col, step.not_in,
                  step.anti_null_check, step.anti_null_col,
                  tuple(step.build_hash_keys)))
    return _fp_build(step.build, catalog, snapshot, parts, pnames)


def _fp_build(build, catalog, snapshot, parts: list, pnames: set) -> bool:
    """Fingerprint a JoinStep.build (Pipeline | QueryPlan), recursively."""
    from ydb_tpu.ops.ir import program_params
    from ydb_tpu.query.plan import QueryPlan

    if isinstance(build, QueryPlan):
        # a QueryPlan build executes with its OWN param set (plan.params),
        # so its referenced values hash locally instead of bubbling up
        local: set = set()
        parts.append(("plan", build.limit, build.offset,
                      tuple(build.output),
                      tuple((sk.name, sk.ascending, sk.nulls_first)
                            for sk in build.sort)))
        if build.final_program is not None:
            parts.append(build.final_program.fingerprint())
            for prm in program_params(build.final_program):
                local.add(prm.name)
        else:
            parts.append("-")
        for (pname, subplan) in build.init_subplans:
            parts.append(("init", pname))
            if not _fp_build(subplan, catalog, snapshot, parts, local):
                return False
        if not _fp_pipeline(build.pipeline, catalog, snapshot, parts,
                            local):
            return False
        parts.append(tuple((n, _hash_param_value(build.params[n]))
                           for n in sorted(local) if n in build.params))
        # names the plan does NOT carry resolve from the enclosing params
        for n in local:
            if n not in build.params:
                pnames.add(n)
        return True
    return _fp_pipeline(build, catalog, snapshot, parts, pnames)


def build_plan_fingerprint(step, params: dict, snapshot, catalog,
                           extra: tuple) -> Optional[tuple]:
    """Cache key for one join build, or None when uncacheable."""
    parts: list = []
    pnames: set = set()
    if not _fp_join_step(step, catalog, snapshot, parts, pnames):
        return None
    pvals = tuple((n, _hash_param_value(params[n]))
                  for n in sorted(pnames) if n in params)
    return (tuple(parts), pvals, extra)


def _entry_bytes(bt) -> int:
    from ydb_tpu.ops import join as J
    if isinstance(bt, J.PartitionedBuild):
        return sum(_entry_bytes(t) for t in bt.tables) or (1 << 10)
    total = int(bt.keys_sorted.nbytes)
    for a in bt.payload.values():
        total += int(a.nbytes)
    for a in bt.payload_valid.values():
        total += int(a.nbytes)
    if bt.lut is not None:
        total += int(bt.lut.nbytes)
    fdb = getattr(bt, "fd_block", None)
    if fdb is not None:
        # the retained host block for FD verification pins host RAM for
        # the cache lifetime — it must ride the budget like everything
        # else or a dimension-heavy workload grows RSS past it unseen
        total += sum(int(cd.data.nbytes) for cd in fdb.columns.values())
    return total


class BuildCache:
    def __init__(self, budget_bytes: int, device_cache=None):
        self.budget = budget_bytes
        # shared-HBM coordination: build bytes register as "foreign"
        # bytes in the DeviceColumnCache so the two pools never sum past
        # the device budget (columns evict to make room for builds)
        self.device_cache = device_cache
        self._entries: OrderedDict = OrderedDict()
        # each value: (build_table, nbytes, probe_dict_ref, probe_dict_len)
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self._mu = threading.RLock()

    def lookup(self, key, probe_dict):
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            bt, _nb, pd_ref, pd_len = ent
            # the build key was remapped INTO the probe dictionary: a
            # different dict object (table reloaded) or a grown one
            # (new values inserted) invalidates the remap
            if pd_ref is not probe_dict or \
                    (probe_dict is not None and len(probe_dict) != pd_len):
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return bt

    def insert(self, key, bt, probe_dict) -> None:
        nb = _entry_bytes(bt)
        with self._mu:
            old = self._entries.pop(key, None)
            if old is not None:
                # replace, don't keep: a stale entry here was invalidated
                # by a probe-dictionary change — keeping it would miss
                # forever while pinning the dead build's HBM
                self.bytes -= old[1]
                if self.device_cache is not None:
                    self.device_cache.release_foreign(old[1])
            if nb > self.budget:
                return                    # never cache something unevictable
            self._entries[key] = (bt, nb, probe_dict,
                                  len(probe_dict)
                                  if probe_dict is not None else 0)
            self.bytes += nb
            if self.device_cache is not None:
                self.device_cache.acquire_foreign(nb)
            while self.bytes > self.budget and self._entries:
                _k, (_bt, onb, _pd, _pl) = self._entries.popitem(last=False)
                self.bytes -= onb
                if self.device_cache is not None:
                    self.device_cache.release_foreign(onb)

    def clear(self) -> None:
        with self._mu:
            if self.device_cache is not None:
                self.device_cache.release_foreign(self.bytes)
            self._entries.clear()
            self.bytes = 0
