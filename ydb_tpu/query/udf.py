"""User-defined functions over dictionary-encoded strings.

The reference ships a 151k-LoC common UDF library (string/url/re2/
hyperscan/ip/json — `ydb/library/yql/udfs/common/`) behind a loadable
C ABI. The TPU-native seat for scalar string compute is the dictionary
LUT: a UDF evaluates ONCE per DISTINCT value on the host (vectorized
where possible), and the device gathers the result through an int32/
bool/typed LUT — URL-cardinality columns cost O(distinct), not O(rows),
and the hot path stays a single fused gather (`query/binder.py`'s
LIKE/startswith machinery generalized to arbitrary Python scalars).

Contract v1: `fn(str_or_None, *literal_args) -> result_or_None`; the
first argument is a string expression of one dictionary column, the
rest fold to literals at bind time. Returns: string (derived
dictionary), int64 / float64 (value LUT + validity LUT), bool
(predicate LUT). NULL in → NULL out unless the function handles None.

Registration: `engine.register_udf(name, fn, returns=...)`; the
standard library below installs at engine construction (regexp, case
folding, trim/pad, URL parts, JSON extraction, IP normalization — the
string/url/re2/json/ip udf seats)."""

from __future__ import annotations

import ipaddress
import json as _json
import re
from typing import Callable
from urllib.parse import urlsplit

RETURNS = ("string", "int64", "float64", "bool")


class Udf:
    __slots__ = ("name", "fn", "returns", "min_args", "max_args")

    def __init__(self, name: str, fn: Callable, returns: str,
                 min_args: int = 1, max_args: int = 8):
        if returns not in RETURNS:
            raise ValueError(f"udf returns must be one of {RETURNS}")
        self.name = name
        self.fn = fn
        self.returns = returns
        self.min_args = min_args
        self.max_args = max_args


class UdfRegistry:
    def __init__(self, with_builtins: bool = True):
        self._udfs: dict = {}
        if with_builtins:
            install_builtins(self)

    def register(self, name: str, fn: Callable, returns: str = "string",
                 min_args: int = 1, max_args: int = 8) -> None:
        self._udfs[name.lower()] = Udf(name.lower(), fn, returns,
                                       min_args, max_args)

    def get(self, name: str):
        return self._udfs.get(name.lower())

    def __contains__(self, name: str) -> bool:
        return name.lower() in self._udfs

    def names(self) -> list:
        return sorted(self._udfs)


# -- standard library -------------------------------------------------------


def _wrap_null(f):
    def g(s, *args):
        if s is None:
            return None
        return f(s, *args)
    return g


def _re_cache(pat: str):
    return re.compile(pat)


def install_builtins(reg: UdfRegistry) -> None:
    # re2/hyperscan seat
    reg.register("regexp_like",
                 _wrap_null(lambda s, p: _re_cache(p).search(s)
                            is not None), "bool", 2, 2)
    reg.register("regexp_extract", _wrap_null(
        lambda s, p, g=0: (lambda m: m.group(int(g)) if m else None)(
            _re_cache(p).search(s))), "string", 2, 3)
    reg.register("regexp_count", _wrap_null(
        lambda s, p: len(_re_cache(p).findall(s))), "int64", 2, 2)
    # string_udf seat (upper/lower/trim/ltrim/rtrim live in the binder's
    # _STR_UNARY table — the single source of truth for those five)
    reg.register("reverse", _wrap_null(lambda s: s[::-1]), "string", 1, 1)
    reg.register("lpad", _wrap_null(
        lambda s, n, c=" ": s.rjust(int(n), str(c)[:1] or " ")),
        "string", 2, 3)
    reg.register("rpad", _wrap_null(
        lambda s, n, c=" ": s.ljust(int(n), str(c)[:1] or " ")),
        "string", 2, 3)
    reg.register("split_part", _wrap_null(_split_part), "string", 3, 3)
    reg.register("find_position", _wrap_null(
        lambda s, sub: s.find(str(sub)) + 1), "int64", 2, 2)
    # url_udf seat
    reg.register("url_host", _wrap_null(
        lambda s: urlsplit(s).hostname), "string", 1, 1)
    reg.register("url_path", _wrap_null(
        lambda s: urlsplit(s).path or None), "string", 1, 1)
    reg.register("url_query", _wrap_null(
        lambda s: urlsplit(s).query or None), "string", 1, 1)
    reg.register("url_domain", _wrap_null(_cut_www), "string", 1, 1)
    # json_udf seat (json_extract('{"a":{"b":1}}', '$.a.b'))
    reg.register("json_extract", _wrap_null(_json_extract), "string", 2, 2)
    reg.register("json_extract_int", _wrap_null(
        lambda s, p: _as_int(_json_value(s, p))), "int64", 2, 2)
    reg.register("json_extract_double", _wrap_null(
        lambda s, p: _as_float(_json_value(s, p))), "float64", 2, 2)
    # ip_udf seat
    reg.register("ip_to_canonical", _wrap_null(_ip_canon), "string", 1, 1)
    reg.register("ip_is_private", _wrap_null(_ip_private), "bool", 1, 1)


def _split_part(s: str, sep, i):
    parts = s.split(str(sep))
    i = int(i)
    return parts[i - 1] if 1 <= i <= len(parts) else None


def _cut_www(s: str):
    h = urlsplit(s).hostname
    if h is None:
        return None
    return h[4:] if h.startswith("www.") else h


def _json_value(s: str, path: str):
    try:
        v = _json.loads(s)
    except (ValueError, TypeError):
        return None
    if not path.startswith("$"):
        return None
    for part in [p for p in re.split(r"\.|\[|\]", path[1:]) if p]:
        if isinstance(v, dict):
            v = v.get(part)
        elif isinstance(v, list):
            try:
                v = v[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
        if v is None:
            return None
    return v


def _json_extract(s: str, path: str):
    v = _json_value(s, path)
    if v is None:
        return None
    if isinstance(v, str):
        return v
    return _json.dumps(v)


def _as_int(v):
    try:
        return None if v is None else int(v)
    except (ValueError, TypeError):
        return None


def _as_float(v):
    try:
        return None if v is None else float(v)
    except (ValueError, TypeError):
        return None


def _ip_canon(s: str):
    try:
        ip = ipaddress.ip_address(s.strip())
    except ValueError:
        return None
    # IPv4-mapped addresses render dotted (`::ffff:10.0.0.1`) on every
    # Python only from 3.13 (cpython gh-87799); pin the dotted form
    v4 = getattr(ip, "ipv4_mapped", None)
    if v4 is not None:
        return f"::ffff:{v4}"
    return str(ip)


def _ip_private(s: str):
    try:
        return ipaddress.ip_address(s.strip()).is_private
    except ValueError:
        return None
