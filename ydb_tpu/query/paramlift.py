"""Parameter lifting: literals out of compiled programs.

N concurrent point-lookup clients whose SQL differs only in literals used
to compile N distinct device programs: every `ir.Const` value sits in the
program's structural fingerprint, so `where k = 5` and `where k = 7` were
different `fused_cache_key`s, different XLA compiles, and N entries of
exec-cache pressure (the executable-accumulation class behind the r5
full-suite SIGSEGV). The reference separates query TEXT from parameter
VALUES at the compile-service boundary (`kqp_compile_service.cpp` keys
its cache on text + schema version, with TParams bound at run time);
this pass recovers that split for plans whose SQL carries inline
literals — the wire shape of virtually every real client.

`lift_plan` runs at the tail of `Planner.plan_select`: every liftable
scalar `ir.Const` in the plan's programs (pushdown filters, join-build
fragments, partial/merge aggregation, HAVING, output expressions)
becomes a canonically named `ir.Param` (`__lit0`, `__lit1`, … in walk
order) whose value lands in `plan.params`. Programs then fingerprint on
*shape*: literal variants of one statement share one compiled program
(fused, tiled, finalize, and per-stage ProgramCache alike), and the
literal arrives as a device input at dispatch time — the inference
stance of arxiv 2603.09555 (pay compilation once, every subsequent step
constant-cost) applied to SQL.

Planning itself still sees concrete values: scan pruning
(`ScanSpec.prune`), CBO selectivity, and dictionary-code folding all run
BEFORE the lift, so plan *quality* is unchanged — only the compiled
artifact is value-free. LIMIT/OFFSET lift separately in the executor
(`__lim2` device input, program keyed on the limit's capacity bucket —
`ops/fused.py`).

Not lifted: `None` (NULL folds structurally at bind time), python
strings (dictionary codes are already ints by the time they reach IR; a
str-valued Const is host-only), array constants, and kernel `extra`
statics (they steer codegen shapes).

The lift also stamps the plan with the batch lane's grouping identity:
`lift_names` (the lifted slots) and `lift_sig` (the prune-stripped plan
shape the batched dispatch lane groups same-shape arrivals by —
`query/batch_lane.py`; build-affecting param VALUES are rederived per
member by `build_lift_values`, builds execute once per batch).
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from ydb_tpu.ops import ir
from ydb_tpu.query.plan import Pipeline, QueryPlan

LIFT_PREFIX = "__lit"
# the lifted LIMIT+OFFSET device input is named by `ops/fused.LIMIT_PARAM`
# ("__lim2") — the executor attaches it at dispatch time, not this pass


def lift_enabled() -> bool:
    """`YDB_TPU_PARAM_LIFT=0` restores literal-embedding plans (A/B
    lever; the batch lane requires lifting and disables with it)."""
    return os.environ.get("YDB_TPU_PARAM_LIFT", "1") not in ("0", "false")


def _liftable(c: ir.Const) -> bool:
    v = c.value
    if v is None or isinstance(v, str):
        return False
    if not isinstance(v, (bool, int, float, np.integer, np.floating,
                          np.bool_)):
        return False
    try:
        np.dtype(c.dtype.np)
    except TypeError:
        return False
    return True


class _Lifter:
    """One walk-ordered `__litN` namespace across the whole plan tree
    (nested build plans included), so merged param dicts never collide
    and literal variants of one statement name their slots identically."""

    def __init__(self):
        self.n = 0

    def _param(self, c: ir.Const, sink: dict) -> ir.Param:
        name = f"{LIFT_PREFIX}{self.n}"
        self.n += 1
        sink[name] = np.dtype(c.dtype.np).type(c.value)
        return ir.Param(name, c.dtype)

    def expr(self, e, sink: dict):
        if isinstance(e, ir.Const) and _liftable(e):
            return self._param(e, sink)
        if isinstance(e, ir.Call):
            return ir.Call(e.op,
                           tuple(self.expr(a, sink) for a in e.args),
                           e.extra)
        return e

    def program(self, p, sink: dict):
        if p is None:
            return None
        cmds = []
        for cmd in p.commands:
            if isinstance(cmd, ir.Assign):
                cmds.append(ir.Assign(cmd.name, self.expr(cmd.expr, sink)))
            elif isinstance(cmd, ir.Filter):
                cmds.append(ir.Filter(self.expr(cmd.pred, sink)))
            else:
                cmds.append(cmd)      # GroupBy / Projection carry no exprs
        return ir.Program(cmds)

    def pipeline(self, pipe: Pipeline, sink: dict) -> Pipeline:
        steps = []
        for kind, step in pipe.steps:
            if kind == "join":
                b = step.build
                if isinstance(b, QueryPlan):
                    # a QueryPlan build executes with its OWN params
                    # (`executor._prepare_join_uncached` → execute()):
                    # its lifted values live in ITS dict
                    b2 = self.queryplan(b)
                else:
                    b2 = self.pipeline(b, sink)
                steps.append((kind, dataclasses.replace(step, build=b2)))
            else:
                steps.append((kind, self.program(step, sink)))
        return dataclasses.replace(
            pipe,
            pre_program=self.program(pipe.pre_program, sink),
            steps=steps,
            partial=self.program(pipe.partial, sink))

    def queryplan(self, plan: QueryPlan, top: bool = False) -> QueryPlan:
        sink: dict = {}
        pipe2 = self.pipeline(plan.pipeline, sink)
        final2 = self.program(plan.final_program, sink)
        init2 = [(pname, self.queryplan(sub))
                 for (pname, sub) in plan.init_subplans]
        plan2 = dataclasses.replace(
            plan, pipeline=pipe2, final_program=final2,
            init_subplans=init2,
            params={**plan.params, **sink},
            lift_names=tuple(sink))
        if top:
            plan2 = dataclasses.replace(plan2,
                                        lift_sig=plan_shape_sig(plan2))
        return plan2


def lift_plan(plan: QueryPlan) -> QueryPlan:
    """Lift every literal in a freshly planned SELECT (no-op when
    disabled). Idempotent by construction: lifted plans contain no
    liftable Consts."""
    if not lift_enabled():
        return plan
    from ydb_tpu.utils.metrics import GLOBAL
    plan2 = _Lifter().queryplan(plan, top=True)
    if plan2.lift_names or any(
            getattr(sub, "lift_names", ())
            for (_p, sub) in plan2.init_subplans):
        GLOBAL.inc("batch/lift_hits")
    else:
        GLOBAL.inc("batch/lift_misses")
    return plan2


# -- plan shape identity (batch-lane grouping) ------------------------------


def plan_shape_sig(plan: QueryPlan) -> tuple:
    """Hashable identity of the plan's compiled SHAPE, literal-values
    excluded and scan pruning excluded (the batched lane executes the
    un-pruned superblock — pruning is a skip optimization whose outcome
    is literal-dependent, so it cannot partition a shared execution).
    Two statements with equal sigs lower to the same fused program
    modulo runtime inputs; the lane still keys separately on the visible
    DATA (src ids) and on build-affecting literal values."""
    from ydb_tpu.ops.device import bucket_capacity

    def prog_fp(p):
        return p.fingerprint() if p is not None else ""

    def pipe_sig(pipe: Pipeline) -> tuple:
        parts = [("scan", pipe.scan.table, tuple(pipe.scan.columns)),
                 ("pre", prog_fp(pipe.pre_program))]
        for kind, step in pipe.steps:
            if kind == "join":
                b = step.build
                bsig = ("plan", plan_shape_sig(b)) \
                    if isinstance(b, QueryPlan) else ("pipe", pipe_sig(b))
                parts.append(("join", step.probe_key, step.build_key,
                              step.kind, tuple(step.payload), step.mark_col,
                              step.not_in, tuple(step.build_hash_keys),
                              bsig))
            else:
                parts.append(("prog", prog_fp(step)))
        parts.append(("partial", prog_fp(pipe.partial)))
        return tuple(parts)

    lim2 = None if plan.limit is None else plan.limit + (plan.offset or 0)
    return ("shape-v1", pipe_sig(plan.pipeline),
            prog_fp(plan.final_program),
            tuple((sk.name, sk.ascending, sk.nulls_first)
                  for sk in plan.sort),
            plan.limit is None,
            None if lim2 is None else bucket_capacity(lim2, minimum=128),
            tuple(n for (n, _lbl) in plan.output),
            tuple(sorted(plan.params)),
            tuple(p for (p, _s) in plan.init_subplans))


def build_lift_values(plan: QueryPlan) -> tuple:
    """Every runtime param value a join-build fragment references —
    lifted literals AND pool params (IN-list LUT arrays, string-function
    LUTs: their VALUES are literal-derived too) — as a hashable
    (name, value-hash) tuple, the batch-lane group-key component. Build
    sides execute ONCE per batch with the leader's values, so members
    whose build-affecting values differ in ANY param must land in
    different groups."""
    from ydb_tpu.ops.ir import program_params
    from ydb_tpu.query.build_cache import _hash_param_value

    out: list = []

    def build_progs(pipe: Pipeline, progs: list) -> None:
        if pipe.pre_program is not None:
            progs.append(pipe.pre_program)
        for kind, step in pipe.steps:
            if kind == "join":
                b = step.build
                if isinstance(b, QueryPlan):
                    collect_plan(b, owner=b)
                else:
                    build_progs(b, progs)
            else:
                progs.append(step)
        if pipe.partial is not None:
            progs.append(pipe.partial)

    def collect(progs: list, owner: QueryPlan) -> None:
        for p in progs:
            for prm in program_params(p):
                v = owner.params.get(prm.name)
                if v is not None:
                    out.append((prm.name, _hash_param_value(v)))

    def collect_plan(p: QueryPlan, owner: QueryPlan) -> None:
        """A whole nested build plan is build-affecting: every param any
        of its programs reference pins the group."""
        progs: list = []
        build_progs(p.pipeline, progs)
        if p.final_program is not None:
            progs.append(p.final_program)
        collect(progs, owner)

    for kind, step in plan.pipeline.steps:
        if kind != "join":
            continue
        b = step.build
        if isinstance(b, QueryPlan):
            collect_plan(b, owner=b)
        else:
            progs: list = []
            build_progs(b, progs)
            collect(progs, plan)

    return tuple(sorted(set(out)))
