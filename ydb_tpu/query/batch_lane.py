"""Multi-query batched dispatch lane (continuous batching for SQL).

The north-star traffic shape is millions of concurrent point-lookup /
small-SELECT clients. PR 1's pipeline overlaps their readouts, and
parameter lifting (`query/paramlift.py`) already collapses their
compiles to one executable per plan SHAPE — but each client still pays
its own device dispatch and its own device→host readout, and on this
platform both carry a large fixed cost (PERF.md: ~15 ms per D2H round
trip through the tunnel). The inference-serving answer is to batch:
same-shape arrivals inside a small time window coalesce into ONE
stacked execution (`Executor.execute_fused_batched` — a vmap over the
members' lifted literals, DrJAX-style mapped composition, arxiv
2403.07128), each client's result resolving to its slice.

`YDB_TPU_BATCH_WINDOW` (milliseconds; 0 = off, the default) is the A/B
switch: off is byte-identical to the per-query pipeline path. A group
seals EARLY when it reaches `YDB_TPU_BATCH_MAX` members (default 64),
so a thundering herd pays no window latency; sparse traffic pays at
most one window per query.

Grouping is correctness-first. Two statements coalesce only when:

  * their `lift_sig`s match — same prune-stripped plan shape, so one
    compiled program serves both (the batched execution runs UN-pruned:
    pruning's outcome is literal-dependent and cannot partition a
    shared scan; the filter programs still apply every predicate);
  * every table either statement scans presents the IDENTICAL visible
    source set (src ids) at both snapshots — the superblock cache's
    data-identity discipline, so executing at the leader's snapshot is
    exact for every member (explicit-tx snapshots with older pins
    simply land in their own groups);
  * their build-affecting lifted literals agree — join builds execute
    once per batch, with the leader's values.

Admission discipline (the double-charge fix): members do NOT take
individual admission reservations or pipeline-window slots. The leader
takes ONE window slot and ONE byte reservation sized to the stacked
execution (`admission.batch_reservation_bytes`) spanning dispatch and
readout — N nominal slots for one physical execution could deadlock
the window under storm load.

Counters: batch/batches, batch/coalesced_queries, batch/max_size,
batch/singles, batch/fallbacks, batch/declined, batch/trace_errors,
plus paramlift's batch/lift_hits / batch/lift_misses; EXPLAIN ANALYZE
carries a `batching` block per statement (QueryStats.batching).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from ydb_tpu.ops import ir
from ydb_tpu.query.plan import QueryPlan


class _Group:
    __slots__ = ("members", "sealed", "full", "done", "results", "exc",
                 "batched")

    def __init__(self):
        self.members: list = []       # [(plan, params, snap, est)]
        self.sealed = False
        self.full = threading.Event()
        self.done = threading.Event()
        self.results: Optional[list] = None
        self.exc: Optional[BaseException] = None
        self.batched = False


def _has_groupby(plan: QueryPlan) -> bool:
    pipe = plan.pipeline
    progs = [pipe.partial, plan.final_program]
    return any(p is not None and any(isinstance(c, ir.GroupBy)
                                     for c in p.commands) for p in progs)


def _plan_tables(plan: QueryPlan, out: Optional[set] = None) -> set:
    """Every table any pipeline of the plan scans (builds included)."""
    if out is None:
        out = set()

    def walk_pipe(pipe):
        out.add(pipe.scan.table)
        for kind, step in pipe.steps:
            if kind != "join":
                continue
            b = step.build
            if isinstance(b, QueryPlan):
                _plan_tables(b, out)
            else:
                walk_pipe(b)

    walk_pipe(plan.pipeline)
    return out


class BatchLane:
    def __init__(self, engine, window_s: float, max_batch: int = 64):
        self.engine = engine
        self.window_s = window_s
        self.max_batch = max(1, int(max_batch))
        self._mu = threading.Lock()
        self._groups: dict = {}              # guarded-by: _mu
        # (table, uid, data_version, snap.plan_step) -> src-id sig memo:
        # between commits the coordinator publishes no new plan step, so
        # a storm's members all hit one entry; ANY commit advances the
        # step and naturally invalidates (compaction/indexation run at
        # commit points). Bounded: cleared when it outgrows the window.
        self._sig_memo: dict = {}            # guarded-by: _mu

    # -- eligibility / grouping --------------------------------------------

    def _group_key(self, plan: QueryPlan, snap, est: int):
        from ydb_tpu.query.paramlift import build_lift_values
        if getattr(plan, "lift_sig", None) is None:
            return None
        if plan.init_subplans:
            # precompute stages run their own sub-SELECTs; keep them on
            # the per-query path
            return None
        ex = self.engine.executor
        if not ex.enable_fused:
            return None
        if ex.mesh is not None and ex.mesh.devices.size > 1:
            return None
        # working-set gate: vmapped execution materializes B copies of
        # every cap-sized intermediate (masks, filtered columns) whatever
        # the OUTPUT shape — a LIMIT or GROUP BY bounds only the result.
        # Shapes whose stacked intermediates could approach the fused
        # scan budget stay on the per-query path (where admission queues
        # them one at a time); un-limited un-aggregated outputs keep the
        # tighter merge-budget bound, since B full result buffers also
        # cross to the host.
        if est * self.max_batch > ex.fused_scan_budget_bytes:
            return None
        if plan.limit is None and not _has_groupby(plan) \
                and est * self.max_batch > ex.merge_budget_bytes:
            return None
        try:
            data_sig = tuple(self._table_sig(t, snap)
                             for t in sorted(_plan_tables(plan)))
        except (AttributeError, KeyError):
            return None      # row-store scan / dropped table: no src ids
        return (plan.lift_sig, data_sig, build_lift_values(plan))

    def _table_sig(self, name: str, snap) -> tuple:
        from ydb_tpu.storage.device_cache import enumerate_scan_sources
        t = self.engine.catalog.table(name)
        memo_key = (name, t.uid, t.data_version, snap.plan_step)
        with self._mu:
            sig = self._sig_memo.get(memo_key)
        if sig is None:
            # enumerate outside the lock (it walks portions); publish
            # under it — storm threads raced clear()+setitem unguarded
            # here before the locks pass caught it
            _sources, ids = enumerate_scan_sources(t, snap, None)
            sig = (t.uid, t.data_version, tuple(ids))
            with self._mu:
                if len(self._sig_memo) > 256:
                    self._sig_memo.clear()
                self._sig_memo[memo_key] = sig
        return sig

    # -- entry -------------------------------------------------------------

    def try_run(self, plan: QueryPlan, snap, est: int, stats=None):
        """Coalesce this SELECT into a same-shape batch and return its
        HostBlock, or None when the statement isn't lane-eligible (the
        caller runs the normal per-query pipeline)."""
        from ydb_tpu.query.admission import AdmissionTimeout
        from ydb_tpu.utils.metrics import GLOBAL

        key = self._group_key(plan, snap, est)
        if key is None:
            GLOBAL.inc("batch/declined")
            return None
        with self._mu:
            g = self._groups.get(key)
            leader = g is None or g.sealed or len(g.members) >= self.max_batch
            if leader:
                g = _Group()
                self._groups[key] = g
            idx = len(g.members)
            g.members.append((plan, dict(plan.params), snap, est))
            if len(g.members) >= self.max_batch:
                g.full.set()             # herd: seal without window latency
        if leader:
            # the WHOLE leader section runs under one finally: a
            # BaseException during the window wait or the seal (not just
            # inside _execute) must still seal the group and release the
            # followers — an unsealed leaderless group would keep
            # collecting arrivals that block until their deadline
            try:
                # continuous-batching probe: a leader that is still
                # ALONE after a ~2 ms grace executes immediately —
                # sparse traffic must not pay the window as latency.
                # Only evidence of concurrency (a follower already
                # queued) buys the full window; a herd seals even
                # earlier via the full event.
                probe = min(0.002, self.window_s)
                if not g.full.wait(probe):
                    with self._mu:
                        alone = len(g.members) <= 1
                    if not alone:
                        g.full.wait(max(self.window_s - probe, 0.0))
                with self._mu:
                    g.sealed = True
                    if self._groups.get(key) is g:
                        del self._groups[key]
                    members = list(g.members)
                g.results, g.batched = self._execute(members)
            except Exception as e:       # noqa: BLE001 — fanned out below
                g.exc = e
            finally:
                with self._mu:
                    g.sealed = True
                    if self._groups.get(key) is g:
                        del self._groups[key]
                if g.results is None and g.exc is None:
                    # a BaseException (KeyboardInterrupt) tore the leader
                    # out mid-batch: followers must not hang on it
                    g.exc = RuntimeError("batch leader aborted")
                g.done.set()
        ok = g.done.wait(self.engine.admission.timeout_s
                         + self.window_s + 60.0)
        if not ok:
            GLOBAL.inc("batch/window_timeouts")
            raise AdmissionTimeout(
                "batched dispatch did not complete inside the admission "
                "deadline (leader stalled)")
        if g.exc is not None:
            raise g.exc
        if stats is not None:
            stats.batching = {"coalesced": len(g.results),
                              "leader": leader,
                              "batched": g.batched}
        if g.batched:
            self.engine.executor.last_path = "fused-batched"
        return g.results[idx]

    # -- leader ------------------------------------------------------------

    def _execute(self, members: list):
        """Run one sealed batch under ONE window slot + ONE admission
        reservation; returns ([HostBlock] in member order, batched?)."""
        from ydb_tpu.query.admission import (
            AdmissionTimeout, batch_reservation_bytes,
        )
        from ydb_tpu.utils.metrics import GLOBAL

        eng = self.engine
        B = len(members)
        if not eng._pipe_sem.acquire(timeout=eng.admission.timeout_s):
            GLOBAL.inc("pipeline/window_timeouts")
            raise AdmissionTimeout(
                f"pipeline window saturated: {eng.pipeline_window} "
                "queries dispatched-or-queued for longer than the "
                "admission deadline (batched dispatch)")
        try:
            est = batch_reservation_bytes(max(m[3] for m in members), B)
            with eng.admission.admit(est):
                GLOBAL.inc("batch/reservations")
                leader_plan, _p, snap, _e = members[0]
                if B == 1:
                    # nothing coalesced: the per-query executable (with
                    # pruning) already exists — don't compile a
                    # batch-of-1 variant for sparse traffic
                    GLOBAL.inc("batch/singles")
                    return [eng.executor.execute(leader_plan, snap)], False
                pipe = leader_plan.pipeline
                plan_b = dataclasses.replace(
                    leader_plan, pipeline=dataclasses.replace(
                        pipe, scan=dataclasses.replace(pipe.scan,
                                                       prune=[])))
                blocks = eng.executor.execute_fused_batched(
                    plan_b, [(m[0], m[1]) for m in members], snap)
                if blocks is None:
                    # shape declined at execution depth (expanding probe,
                    # tiled-class scan, vmap trace failure): serve every
                    # member individually under the held reservation
                    GLOBAL.inc("batch/fallbacks")
                    return [eng.executor.execute(m[0], m[2])
                            for m in members], False
                GLOBAL.inc("batch/batches")
                GLOBAL.inc("batch/coalesced_queries", B)
                GLOBAL.set_max("batch/max_size", B)
                return blocks, True
        finally:
            eng._pipe_sem.release()
