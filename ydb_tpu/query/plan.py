"""Physical query plans.

The analog of the reference's `TKqpPhyQuery` protobuf (`kqp_physical.proto`)
+ DQ task-graph stages (`dq/tasks/dq_tasks_graph.h`): a query is a tree of
streaming *pipelines*, each anchored on a table scan (its SSA pre-program
pushed down into the scan, `TKqpPhyOpReadOlapRanges` style), followed by
broadcast-join probe steps and an optional partial aggregation, and a final
stage that merges partials, applies HAVING, computes output expressions,
sorts and limits.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ydb_tpu.ops import ir


@dataclass
class ScanSpec:
    table: str
    columns: list                    # [(storage_name, internal_name)]
    prune: list = field(default_factory=list)   # [(storage_col, op, value)]
    # CBO estimate: post-local-predicate cardinality (query/stats.py);
    # -1 = not estimated
    est_rows: float = -1.0


@dataclass
class JoinStep:
    build: object                    # Pipeline | QueryPlan (subquery build)
    build_key: str                   # internal name in build output
    probe_key: str                   # internal name in probe pipeline
    kind: str                        # inner | left | left_semi | left_anti | mark
    payload: list = field(default_factory=list)  # build columns to attach
    mark_col: str = ""               # for kind=mark: bool match-flag column
    anti_null_check: bool = False    # NOT IN: reject NULLs in the build key
    anti_null_col: str = ""          # column to null-check (default build_key)
    # NOT IN semantics: a NULL probe key is excluded unless the build set is
    # empty (x NOT IN S is NULL when x is NULL and S != {}, TRUE when S = {})
    not_in: bool = False
    # composite keys: executor hashes these build columns host-side into
    # `build_key` before building (probe side hashes in its program)
    build_hash_keys: list = field(default_factory=list)
    # sizing metadata ONLY (bounds lattice / compact sizing): the
    # storage-backed build columns a synthesized `build_key` was derived
    # from, for in-program composite hashes where `build_hash_keys` must
    # stay empty (the hash is computed inside the build's partial, not
    # host-side). Lets PK-uniqueness survive the key synthesis — without
    # it a composite-PK probe (q9 lineitem x partsupp on the partkey/
    # suppkey pair) degrades the pipeline bound to a row product.
    build_key_cols: list = field(default_factory=list)


@dataclass
class Pipeline:
    """One streaming stage: scan → program → (join → program)* → partial."""
    scan: ScanSpec
    pre_program: Optional[ir.Program] = None      # pushdown filters/assigns
    steps: list = field(default_factory=list)     # [("join", JoinStep) | ("program", ir.Program)]
    partial: Optional[ir.Program] = None          # ends in partial GroupBy / projection
    out_names: list = field(default_factory=list)  # pipeline output columns
    # bounds lattice (query/bounds.py): proven-at-plan-time row upper
    # bound of this pipeline's output; 0 = unknown (capacity sizing).
    # Sizing-quality (admission, segment sizing, EXPLAIN) — the
    # correctness-bearing bounds live on ir.GroupBy.
    out_bound: int = 0
    # late materialization (query/latemat.py): columns the fused path
    # carries as row-ids — scan deferrals by name, join payloads as
    # "name(row-id)". Observability metadata (EXPLAIN `-- latemat:`);
    # the executor recomputes the sets against the actual fused shape.
    late_names: tuple = ()


@dataclass
class SortKey:
    name: str
    ascending: bool = True
    nulls_first: bool = False


@dataclass
class QueryPlan:
    pipeline: Pipeline
    final_program: Optional[ir.Program] = None    # merge agg + having + exprs
    sort: list = field(default_factory=list)      # [SortKey]
    limit: Optional[int] = None
    offset: Optional[int] = None
    output: list = field(default_factory=list)    # [(internal_name, label)]
    params: dict = field(default_factory=dict)    # param name -> value
    # uncorrelated scalar subqueries: executed first, their single value
    # becomes a runtime param (the KQP precompute-stage analog,
    # `KqpPhysicalTx` TxResultBinding)
    init_subplans: list = field(default_factory=list)  # [(param, QueryPlan)]
    # dictionaries for derived string columns (substring/concat results):
    # internal column name -> Dictionary
    result_dicts: dict = field(default_factory=dict)
    # schema-declaration order of every FROM relation's columns
    # ("alias.col" internal names) — SELECT * output order
    star_order: list = field(default_factory=list)
    # parameter lifting (query/paramlift.py): canonical `__litN` params
    # whose values were extracted from this plan's literals — programs
    # fingerprint on SHAPE, one compiled executable serves every literal
    # variant. `lift_sig`: prune-stripped shape identity the batched
    # dispatch lane groups same-shape arrivals by (build-affecting param
    # VALUES are rederived from the programs per member —
    # `paramlift.build_lift_values`); None = not lifted (lane
    # ineligible).
    lift_names: tuple = ()
    lift_sig: Optional[tuple] = None
    # bounds lattice (query/bounds.py): proven row upper bound of the
    # final result (post sort/limit); 0 = unknown
    out_bound: int = 0


def explain(plan: QueryPlan, indent: int = 0) -> str:
    """Human-readable plan (the `kqp_query_plan.cpp` analog)."""
    pad = "  " * indent
    lines = []

    def pipe(p: Pipeline, d: int):
        pp = "  " * d
        lines.append(f"{pp}Scan {p.scan.table} cols={[c[1] for c in p.scan.columns]}"
                     + (f" est_rows={p.scan.est_rows:g}"
                        if p.scan.est_rows >= 0 else "")
                     + (f" prune={p.scan.prune}" if p.scan.prune else ""))
        if p.out_bound:
            lines.append(f"{pp}  -- bounds: pipeline ≤ {p.out_bound} rows"
                         + _gb_bounds(p.partial))
        if p.late_names:
            lines.append(f"{pp}  -- latemat: {len(p.late_names)} deferred "
                         f"[{', '.join(p.late_names)}]")
        if p.pre_program:
            lines.append(f"{pp}  pre: {_prog(p.pre_program)}")
        for kind, step in p.steps:
            if kind == "join":
                lines.append(f"{pp}  {step.kind.upper()} JOIN probe={step.probe_key} "
                             f"build={step.build_key} payload={step.payload}")
                if isinstance(step.build, QueryPlan):
                    lines.append(f"{pp}    subplan:")
                    lines.append(explain(step.build, d + 3))
                else:
                    pipe(step.build, d + 2)
            else:
                lines.append(f"{pp}  program: {_prog(step)}")
        if p.partial:
            lines.append(f"{pp}  partial: {_prog(p.partial)}")

    pipe(plan.pipeline, indent)
    if plan.final_program:
        lines.append(f"{pad}final: {_prog(plan.final_program)}")
    if plan.out_bound:
        lines.append(f"{pad}-- bounds: result ≤ {plan.out_bound} rows")
    if plan.sort:
        lines.append(f"{pad}sort: {[(s.name, 'asc' if s.ascending else 'desc') for s in plan.sort]}")
    if plan.limit is not None:
        lines.append(f"{pad}limit: {plan.limit}")
    lines.append(f"{pad}output: {[lbl for _, lbl in plan.output]}")
    return "\n".join(lines)


def _gb_bounds(prog) -> str:
    """Group-by bound annotation for the `-- bounds:` line: each stage's
    proven bound next to what capacity sizing would have allocated."""
    if prog is None:
        return ""
    parts = []
    for cmd in prog.commands:
        if isinstance(cmd, ir.GroupBy) and cmd.out_bound:
            s = f"groupby ≤ {cmd.out_bound} groups"
            if cmd.carry_keys:
                s += f" ({len(cmd.carry_keys)} carried)"
            parts.append(s)
    return (" | " + ", ".join(parts)) if parts else ""


def _prog(p: ir.Program) -> str:
    parts = []
    for cmd in p.commands:
        if isinstance(cmd, ir.Assign):
            parts.append(f"assign {cmd.name}")
        elif isinstance(cmd, ir.Filter):
            parts.append("filter")
        elif isinstance(cmd, ir.GroupBy):
            parts.append(f"groupby[{','.join(cmd.keys)}]"
                         f"({','.join(a.func for a in cmd.aggs)})")
        elif isinstance(cmd, ir.Projection):
            parts.append(f"project[{len(cmd.names)}]")
    return " → ".join(parts)
