"""Query engine front door: SQL text in, result blocks out.

Plays the role of the KQP session actor + compile service
(`kqp_session_actor.cpp:455` CompileQuery → `ExecutePhyTx`): parses, plans
(with a fingerprint-keyed plan cache), executes, and applies DDL/DML against
the catalog. Single-session, single-node for now; the distributed planner
and the transactional write path slot in behind the same interface.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ydb_tpu.core.block import HostBlock
from ydb_tpu.query.binder import BindError, sql_type_to_dtype, parse_date_literal
from ydb_tpu.query.executor import Executor
from ydb_tpu.query.plan import QueryPlan, explain
from ydb_tpu.query.planner import PlanError, Planner
from ydb_tpu.scheme.catalog import Catalog
from ydb_tpu.sql import ast, parse
from ydb_tpu.storage.mvcc import Snapshot, WriteVersion
from ydb_tpu.core.schema import Column, Schema


class QueryError(Exception):
    pass


class QueryEngine:
    def __init__(self, catalog: Optional[Catalog] = None,
                 block_rows: int = 1 << 20, mesh=None,
                 data_dir: Optional[str] = None):
        """`mesh`: a jax.sharding.Mesh for distributed execution — scans are
        row-partitioned across its devices and aggregation boundaries become
        ICI hash shuffles (`ydb_tpu.parallel.make_mesh(n)` builds one).

        `data_dir`: durable root. An existing catalog there is recovered
        (portions + WAL replay, `storage/persist.py`); otherwise a fresh
        durable catalog is created. MVCC plan steps resume past the last
        committed step so recovered versions stay ordered."""
        restored_step = 0
        if data_dir is not None and catalog is None:
            import os

            from ydb_tpu.storage.persist import Store
            store = Store(data_dir)
            if os.path.exists(os.path.join(data_dir, "catalog.json")):
                catalog, restored_step = store.load()
            else:
                catalog = Catalog(store=store)
                store.save_catalog(catalog)
        self.catalog = catalog or Catalog()
        self.planner = Planner(self.catalog)
        self.executor = Executor(self.catalog, block_rows, mesh=mesh)
        self._plan_step = max(1, restored_step)
        self._tx_id = 1
        # plan cache (compile-service LRU analog, `kqp_compile_service.cpp:411`):
        # keyed by SQL text, validated against the (uid, data_version) of
        # every table the statement references — plans snapshot dictionary
        # domains at plan time, so any commit to a referenced table
        # invalidates only that statement's entry, not the whole cache
        self._plan_cache: dict = {}
        self.plan_cache_hits = 0
        self._tmp_n = 0

    # -- versions (standing in for coordinator/mediator time) -------------

    def _next_version(self) -> WriteVersion:
        self._plan_step += 1
        return WriteVersion(self._plan_step, self._tx_id)

    def snapshot(self) -> Snapshot:
        return Snapshot(self._plan_step, 2 ** 62)

    # -- entry -------------------------------------------------------------

    def execute(self, sql: str) -> HostBlock:
        stmt = parse(sql)
        try:
            if isinstance(stmt, ast.Select):
                if self._needs_materialize(stmt):
                    return self._execute_materialized(stmt)
                fp = self._table_fingerprint(stmt)
                cached = self._plan_cache.get(sql)
                if cached is not None and cached[0] == fp:
                    plan = cached[1]
                    self.plan_cache_hits += 1
                else:
                    plan = self.planner.plan_select(stmt)
                    self._plan_cache[sql] = (fp, plan)
                return self.executor.execute(plan, self.snapshot())
            if isinstance(stmt, ast.CreateTable):
                return self._create_table(stmt)
            if isinstance(stmt, ast.DropTable):
                if stmt.if_exists and not self.catalog.has(stmt.name):
                    return _unit_block()
                self.catalog.drop_table(stmt.name)
                return _unit_block()
            if isinstance(stmt, ast.Insert):
                return self._insert(stmt)
            raise QueryError(f"unsupported statement {type(stmt).__name__}")
        except (BindError, PlanError) as e:
            raise QueryError(str(e)) from e

    def explain(self, sql: str) -> str:
        stmt = parse(sql)
        if not isinstance(stmt, ast.Select):
            raise QueryError("EXPLAIN supports SELECT only")
        return explain(self.planner.plan_select(stmt))

    def query(self, sql: str):
        """Execute and return a pandas DataFrame (tests / CLI)."""
        return self.execute(sql).to_pandas()

    def _table_fingerprint(self, sel: ast.Select):
        """(name, uid, data_version) of every table the statement touches —
        the plan-cache validity key (reference keys its compile cache on
        query text + schema version, `kqp_compile_service.cpp:411`)."""
        names: set = set()

        def walk_sel(s: ast.Select):
            for (_n, body) in s.ctes:
                walk_sel(body)
            if s.relation is not None:
                walk_rel(s.relation)
            for e in ([i.expr for i in s.items] + [s.where, s.having]
                      + list(s.group_by) + [o.expr for o in s.order_by]):
                walk_expr(e)

        def walk_rel(r):
            if isinstance(r, ast.TableRef):
                names.add(r.name)
            elif isinstance(r, ast.Join):
                walk_rel(r.left)
                walk_rel(r.right)
                walk_expr(r.on)
            elif isinstance(r, ast.SubqueryRef):
                walk_sel(r.query)

        def walk_expr(e):
            if e is None or not hasattr(e, "__dataclass_fields__"):
                return
            if isinstance(e, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
                walk_sel(e.query)
                if isinstance(e, ast.InSubquery):
                    walk_expr(e.arg)
                return
            def walk_val(v):
                if isinstance(v, tuple):
                    for x in v:
                        walk_val(x)
                else:
                    walk_expr(v)

            for f in e.__dataclass_fields__:
                walk_val(getattr(e, f))

        walk_sel(sel)
        out = []
        for n in sorted(names):
            if self.catalog.has(n):
                t = self.catalog.table(n)
                out.append((n, t.uid, t.data_version))
        return tuple(out)

    # -- CTE / derived-table materialization -------------------------------
    #
    # WITH bodies and FROM subqueries materialize into transient column
    # tables before the outer statement plans — the stage-materialization
    # strategy of DQ precompute stages (`dq_opt_phy_finalizing.cpp`
    # DqBuildStages: a stage result becomes the next stage's source).

    def _needs_materialize(self, sel: ast.Select) -> bool:
        if sel.ctes:
            return True

        def rel_has(r):
            if isinstance(r, ast.SubqueryRef):
                return True
            if isinstance(r, ast.Join):
                return rel_has(r.left) or rel_has(r.right)
            return False

        def expr_has(e):
            if e is None or not hasattr(e, "__dataclass_fields__"):
                return False
            if isinstance(e, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
                sub = self._needs_materialize(e.query)
                if isinstance(e, ast.InSubquery):
                    return sub or expr_has(e.arg)
                return sub

            def any_in(v):
                if isinstance(v, tuple):
                    return any(any_in(x) for x in v)
                return expr_has(v)

            return any(any_in(getattr(e, f))
                       for f in e.__dataclass_fields__)

        if sel.relation is not None and rel_has(sel.relation):
            return True
        for e in ([i.expr for i in sel.items] + [sel.where, sel.having]
                  + list(sel.group_by) + [o.expr for o in sel.order_by]):
            if expr_has(e):
                return True
        return False

    def _execute_materialized(self, sel: ast.Select) -> HostBlock:
        temps: list = []
        try:
            sel2 = self._rewrite_sel(sel, {}, temps)
            plan = self.planner.plan_select(sel2)
            return self.executor.execute(plan, self.snapshot())
        finally:
            for t in temps:
                if self.catalog.has(t):
                    self.catalog.drop_table(t)

    def _rewrite_sel(self, sel: ast.Select, cte_map: dict,
                     temps: list) -> ast.Select:
        cte_map = dict(cte_map)
        for (name, body) in sel.ctes:
            cte_map[name] = self._materialize(
                self._rewrite_sel(body, cte_map, temps), temps)

        def rewrite_rel(r):
            if isinstance(r, ast.TableRef):
                t = cte_map.get(r.name)
                if t is not None:
                    return ast.TableRef(t, r.alias or r.name)
                return r
            if isinstance(r, ast.Join):
                return ast.Join(r.kind, rewrite_rel(r.left),
                                rewrite_rel(r.right),
                                rewrite_expr(r.on))
            if isinstance(r, ast.SubqueryRef):
                t = self._materialize(
                    self._rewrite_sel(r.query, cte_map, temps), temps)
                return ast.TableRef(t, r.alias)
            return r

        def rewrite_expr(e):
            import dataclasses
            if e is None or not hasattr(e, "__dataclass_fields__"):
                return e
            if isinstance(e, (ast.Exists, ast.InSubquery,
                              ast.ScalarSubquery)):
                kw = {"query": self._rewrite_sel(e.query, cte_map, temps)}
                if isinstance(e, ast.InSubquery):
                    kw["arg"] = rewrite_expr(e.arg)
                return dataclasses.replace(e, **kw)

            def rw(v):
                if isinstance(v, tuple):
                    return tuple(rw(x) for x in v)
                return rewrite_expr(v)

            kw = {f: rw(getattr(e, f)) for f in e.__dataclass_fields__}
            return dataclasses.replace(e, **kw)

        out = ast.Select(**{**sel.__dict__})
        out.ctes = []
        if out.relation is not None:
            out.relation = rewrite_rel(out.relation)
        out.where = rewrite_expr(out.where)
        out.having = rewrite_expr(out.having)
        out.items = [ast.SelectItem(rewrite_expr(i.expr), i.alias)
                     for i in out.items]
        out.group_by = [rewrite_expr(g) for g in out.group_by]
        out.order_by = [ast.OrderItem(rewrite_expr(o.expr), o.ascending,
                                      o.nulls_first) for o in out.order_by]
        return out

    def _materialize(self, sel: ast.Select, temps: list) -> str:
        block = self.executor.execute(self.planner.plan_select(sel),
                                      self.snapshot())
        tname = f"__tmp{self._tmp_n}"
        self._tmp_n += 1
        t = self.catalog.create_table(tname, block.schema,
                                      [block.schema.names[0]], shards=1,
                                      transient=True)
        t.dictionaries = {n: cd.dictionary
                          for n, cd in block.columns.items()
                          if cd.dictionary is not None}
        if block.length:
            t.commit(t.write(block), self._next_version())
            t.indexate()
        temps.append(tname)
        return tname

    # -- DDL / DML ---------------------------------------------------------

    def _create_table(self, stmt: ast.CreateTable) -> HostBlock:
        if self.catalog.has(stmt.name):
            if stmt.if_not_exists:
                return _unit_block()
            raise QueryError(f"table {stmt.name!r} already exists")
        cols = [Column(name, sql_type_to_dtype(ty, not_null))
                for (name, ty, not_null) in stmt.columns]
        pk = stmt.primary_key or [cols[0].name]
        self.catalog.create_table(stmt.name, Schema(cols), pk,
                                  shards=max(1, stmt.partition_count))
        return _unit_block()

    def _insert(self, stmt: ast.Insert) -> HostBlock:
        table = self.catalog.table(stmt.table)
        if stmt.query is not None:
            raise QueryError("INSERT ... SELECT not supported yet")
        names = stmt.columns or table.schema.names
        data: dict[str, list] = {n: [] for n in names}
        from ydb_tpu.query.binder import _try_fold
        for row in stmt.rows:
            if len(row) != len(names):
                raise QueryError("VALUES arity mismatch")
            for n, lit in zip(names, row):
                if isinstance(lit, ast.Literal) and lit.value is None:
                    data[n].append(None)
                    continue
                folded = _try_fold(lit)   # literals, -x, DATE '...', CAST
                if folded is None:
                    raise QueryError("VALUES must be constant expressions")
                data[n].append(folded.value)

        arrays, valids = {}, {}
        n_rows = len(stmt.rows)
        for c in table.schema:
            if c.name in data:
                vals = data[c.name]
                mask = np.array([v is not None for v in vals])
                if c.dtype.is_string:
                    codes = table.dictionaries[c.name].encode(
                        [None if v is None else str(v) for v in vals])
                    arrays[c.name] = codes
                else:
                    arrays[c.name] = np.array(
                        [0 if v is None else v for v in vals], dtype=c.dtype.np)
                if not mask.all():
                    if not c.dtype.nullable:
                        raise QueryError(f"NULL in NOT NULL column {c.name}")
                    valids[c.name] = mask
            else:
                if not c.dtype.nullable:
                    raise QueryError(f"missing NOT NULL column {c.name}")
                arrays[c.name] = np.zeros(n_rows, dtype=c.dtype.np)
                valids[c.name] = np.zeros(n_rows, dtype=bool)
        block = HostBlock.from_arrays(table.schema, arrays, valids,
                                      dict(table.dictionaries))
        writes = table.write(block)
        table.commit(writes, self._next_version())
        table.indexate()
        return _unit_block()


def _unit_block() -> HostBlock:
    return HostBlock(Schema([]), {}, 0)
