"""Query engine front door: SQL text in, result blocks out.

Plays the role of the KQP session actor + compile service
(`kqp_session_actor.cpp:455` CompileQuery → `ExecutePhyTx`): parses, plans
(with a fingerprint-keyed plan cache), executes, and applies DDL/DML against
the catalog. Interactive transactions (BEGIN/COMMIT/ROLLBACK with
optimistic locks) live in `ydb_tpu/tx`; `engine.session()` opens
concurrent sessions over the shared engine.
"""

from __future__ import annotations

import os
from contextlib import contextmanager as _contextmanager
from typing import Optional

import numpy as np

from ydb_tpu.core.block import HostBlock
from ydb_tpu.query.binder import BindError, sql_type_to_dtype, parse_date_literal
from ydb_tpu.query.executor import Executor
from ydb_tpu.query.plan import QueryPlan, explain
from ydb_tpu.query.planner import PlanError, Planner
from ydb_tpu.scheme.catalog import Catalog
from ydb_tpu.sql import ast, parse
from ydb_tpu.storage.mvcc import Snapshot, WriteVersion
from ydb_tpu.core.schema import Column, Schema


class QueryError(Exception):
    pass


class QueryEngine:
    def __init__(self, catalog: Optional[Catalog] = None,
                 block_rows: Optional[int] = None, mesh=None,
                 data_dir: Optional[str] = None, config=None,
                 replica=None):
        """`mesh`: a jax.sharding.Mesh for distributed execution — scans are
        row-partitioned across its devices and aggregation boundaries become
        ICI hash shuffles (`ydb_tpu.parallel.make_mesh(n)` builds one).

        `data_dir`: durable root. An existing catalog there is recovered
        (portions + WAL replay, `storage/persist.py`); otherwise a fresh
        durable catalog is created. MVCC plan steps resume past the last
        committed step so recovered versions stay ordered.

        `config`: a `ydb_tpu.utils.config.Config` (YAML-loadable, with
        selector overrides + feature flags); explicit arguments win over
        it."""
        import threading
        from ydb_tpu.utils.config import Config
        self.config = config or Config.load()
        # WRITE lock: mutations (DML, DDL, tx control, topic ops) from any
        # front serialize here; SELECTs run lock-free over MVCC snapshots
        # (the r3 design held this around EVERY statement — concurrency
        # item of VERDICT r3). RLock: DML bodies re-enter execute() for
        # their SELECT subflows. Network fronts must NOT wrap execute()
        # in this themselves anymore — the engine takes it internally.
        self.lock = threading.RLock()
        block_rows = block_rows if block_rows is not None \
            else self.config.block_rows
        data_dir = data_dir if data_dir is not None \
            else self.config.data_dir
        restored_step = 0
        if data_dir is not None and catalog is None:
            from ydb_tpu.storage.persist import Store
            sink = None
            if replica is not None:
                # synchronous standby mirror (cluster/replica.py):
                # every durable mutation ships before acknowledgement
                from ydb_tpu.cluster.replica import make_sink
                sink = make_sink(replica)
            store = Store(data_dir, replica=sink)
            if os.path.exists(os.path.join(data_dir, "catalog.json")):
                catalog, restored_step = store.load()
                # pre-existing data + fresh standby: full initial sync
                # (delta shipping alone would reference blobs the
                # standby never saw)
                store.sync_replica()
            else:
                catalog = Catalog(store=store)
                store.save_catalog(catalog)
        self.catalog = catalog or Catalog()
        self.planner = Planner(self.catalog)
        self.executor = Executor(self.catalog, block_rows, mesh=mesh)
        self.executor.enable_fused = self.config.flag("enable_fused")
        # budget priority: explicit env var > config (file or object) >
        # built-in default (the executor ctor already consumed the env)
        if "YDB_TPU_GRACE_BUDGET" not in os.environ:
            self.executor.grace_budget_bytes = \
                self.config.grace_budget_bytes
        from ydb_tpu.tx import Coordinator, Session
        self.coordinator = Coordinator(start_step=max(1, restored_step))
        # the engine's own statements run through a default session
        # (autocommit unless BEGIN is issued on it); `session()` opens
        # additional concurrent sessions
        self._default_session = Session(self)
        # plan cache (compile-service LRU analog, `kqp_compile_service.cpp:411`):
        # keyed by SQL text, validated against the (uid, data_version) of
        # every table the statement references — plans snapshot dictionary
        # domains at plan time, so any commit to a referenced table
        # invalidates only that statement's entry, not the whole cache
        self._plan_cache: dict = {}
        self.plan_cache_hits = 0
        import itertools as _it
        self._tmp_ids = _it.count()      # thread-safe temp-name allocator
        # device-memory admission (kqp_rm_service.h:68 analog): SELECTs
        # reserve their scan+build estimate before dispatch
        from ydb_tpu.query.admission import MemoryAdmission
        from ydb_tpu.storage.device_cache import DEFAULT_BUDGET
        self.admission = MemoryAdmission(
            int(os.environ.get("YDB_TPU_ADMISSION_BUDGET", DEFAULT_BUDGET)),
            timeout_s=float(os.environ.get("YDB_TPU_ADMISSION_TIMEOUT",
                                           60.0)))
        # per-statement stats ring — the `.sys/query_metrics` /
        # top-queries source (query_metrics_one_minute analog)
        from collections import deque
        self.query_history = deque(maxlen=256)
        # topics + changefeeds (PersQueue / change_exchange analogs,
        # ydb_tpu/storage/topic.py); durable under <root>/__topics
        self.topics: dict = {}
        self._changefeeds: dict = {}    # table -> topic name
        self._cdc_since: dict = {}      # table -> plan_step at enable
        if self.catalog.store is not None:
            self._load_topics()
        self._reconcile_changefeeds()
        # materialized views (ydb_tpu/views/): continuous queries over
        # the changefeeds above; loaded AFTER topics + healing so the
        # consumers resume against a consistent topic tail
        from ydb_tpu.views import ViewManager
        self.views = ViewManager(self)
        self.views.load()
        self._view_tls = threading.local()   # per-read serving notes
        # tracing (Wilson analog, utils/tracing.py): span tree per
        # statement, rendered by EXPLAIN ANALYZE; `trace_to_topic()`
        # wires the OTLP-uploader seat
        from ydb_tpu.utils.tracing import Tracer
        self.tracer = Tracer()
        self.executor.tracer = self.tracer
        # cluster control plane (ydb_tpu/hive/): a router candidate that
        # hosts the Hive attaches it here — the server's HiveRegister/
        # HiveHeartbeat RPCs and the `.sys/cluster_nodes` sysview both
        # read it; None on ordinary workers
        self.hive = None
        # admission-time trace sampling (jaeger_tracing sampler analog):
        # YDB_TPU_TRACE_SAMPLE in [0, 1] — 1 (default) traces every
        # statement, 0 records zero spans (results byte-identical),
        # fractions sample deterministically 1-in-1/rate. Statements
        # whose text previously blew the slow-query threshold are
        # FORCED-sampled regardless of rate, so the profile of a known
        # offender is always captured on its next run.
        self.trace_sample = min(1.0, max(0.0, float(
            os.environ.get("YDB_TPU_TRACE_SAMPLE", "1") or 0)))
        self.slow_query_ms = float(
            os.environ.get("YDB_TPU_SLOW_QUERY_MS", "1000"))
        self._slow_sqls: dict = {}       # guarded-by: _trace_mu
        self._trace_mu = threading.Lock()
        self._trace_acc = 0.0            # guarded-by: _trace_mu
        # assembled query profiles, last-N ring (`.sys/query_profiles`):
        # one record per SAMPLED outermost statement — sql, wall,
        # phase breakdown, and the full cross-worker span tree
        from collections import deque as _deque
        self.profiles = _deque(maxlen=int(
            os.environ.get("YDB_TPU_PROFILE_RING", "64")))
        # per-(stage, worker) DQ execution stats ring
        # (`.sys/dq_stage_stats`) — the TDqTaskRunnerStatsView seat;
        # filled by DqTaskRunner when this engine drives a stage graph
        self.dq_stage_stats = _deque(maxlen=int(
            os.environ.get("YDB_TPU_DQ_STATS_RING", "256")))
        # per-statement resource-ledger rollups, last-N ring
        # (`.sys/query_memory`): peak device bytes, padding account,
        # host transfers, admission calibration — one row per closed
        # ledger (utils/memledger.py; empty under YDB_TPU_MEMLEDGER=0)
        self.memory_stats = _deque(maxlen=int(
            os.environ.get("YDB_TPU_MEMORY_RING", "256")))
        # per-statement critical-path rollups, last-N ring
        # (`.sys/query_critical_path`): one row per extracted path —
        # per-class milliseconds, coverage, the dominant span
        # (utils/critpath.py; empty under YDB_TPU_CRITPATH=0)
        self.critpath_stats = _deque(maxlen=int(
            os.environ.get("YDB_TPU_CRITPATH_RING", "256")))
        # per-statement result metadata is THREAD-LOCAL: concurrent
        # sessions must each see their own stats/trace/rows-affected
        self._tls = threading.local()
        # in-flight lock-free reads register their snapshot plan step so
        # auto-compaction's watermark never restamps portions a running
        # SELECT still needs (autocommit snapshots are not coordinator-
        # pinned; explicit txs pin theirs)
        from collections import Counter as _Counter
        self._active_reads = _Counter()  # guarded-by: _reads_mu
        self._reads_mu = threading.Lock()
        # admission rate limiting (Kesus/quoter analog): meter the
        # "queries" resource via engine.quoter.set_quota(...)
        from ydb_tpu.utils.quota import Quoter
        self.quoter = Quoter()
        # concurrent-query pipeline (the continuous-batching discipline):
        # SELECT dispatch (plan → compile-cache → device enqueue) and
        # readout (the one pytree device_get) are separate phases, so
        # query N+1 dispatches while query N drains D2H instead of both
        # paying the full post-readout dispatch cliff serially (PERF.md).
        # The window bounds dispatched-but-undrained queries: each holds
        # its result buffers (plus admission reservation) in device
        # memory until drained.
        self.pipeline_window = max(1, int(os.environ.get(
            "YDB_TPU_PIPELINE_WINDOW", self.config.pipeline_window)))
        self._pipe_sem = threading.BoundedSemaphore(self.pipeline_window)
        self._pipe_mu = threading.Lock()
        self._pipe_inflight = 0          # guarded-by: _pipe_mu
        # multi-query batched dispatch lane (query/batch_lane.py): with
        # YDB_TPU_BATCH_WINDOW=<ms> > 0, same-shape SELECTs arriving
        # inside the window coalesce into ONE stacked fused execution
        # (one dispatch + one readout + one admission reservation for B
        # clients). 0 = off, byte-identical to the per-query path.
        self.batch_window_ms = float(
            os.environ.get("YDB_TPU_BATCH_WINDOW", "0") or 0)
        self._batch_lane = None
        if self.batch_window_ms > 0:
            from ydb_tpu.query.batch_lane import BatchLane
            self._batch_lane = BatchLane(
                self, self.batch_window_ms / 1000.0,
                max_batch=int(os.environ.get("YDB_TPU_BATCH_MAX", "64")))

    # -- per-thread statement metadata -------------------------------------

    @property
    def last_stats(self):
        return getattr(self._tls, "last_stats", None)

    @last_stats.setter
    def last_stats(self, v):
        self._tls.last_stats = v

    @property
    def last_rows_affected(self) -> int:
        return getattr(self._tls, "last_rows_affected", 0)

    @last_rows_affected.setter
    def last_rows_affected(self, v: int):
        self._tls.last_rows_affected = v

    @property
    def last_trace(self):
        return getattr(self._tls, "last_trace", [])

    @last_trace.setter
    def last_trace(self, v):
        self._tls.last_trace = v

    # -- in-flight read registry (compaction safety floor) -----------------

    def _enter_read(self, plan_step: int) -> None:
        with self._reads_mu:
            self._active_reads[plan_step] += 1

    def _register_read(self):
        """Atomically take an autocommit read snapshot AND register it in
        the active-read floor. Taking the snapshot first and registering
        after (the r4 shape) left a gap where a commit + auto-compaction
        could restamp portions the snapshot still needed (ADVICE r4):
        under `_reads_mu`, any maintenance watermark computed before this
        registration was bounded by an older published step, so portions
        this snapshot sees are never restamped past it."""
        with self._reads_mu:
            snap = self.coordinator.read_snapshot()
            self._active_reads[snap.plan_step] += 1
        return snap

    def _exit_read(self, plan_step: int) -> None:
        with self._reads_mu:
            self._active_reads[plan_step] -= 1
            if self._active_reads[plan_step] <= 0:
                del self._active_reads[plan_step]

    def _maintenance_watermark(self) -> int:
        """Highest plan step background compaction may restamp up to:
        bounded by pinned tx snapshots (coordinator) AND every in-flight
        lock-free read."""
        w = self.coordinator.safe_watermark()
        with self._reads_mu:
            if self._active_reads:
                w = min(w, min(self._active_reads))
        return w

    # -- versions (coordinator time, ydb_tpu/tx/coordinator.py) ------------

    @property
    def _plan_step(self) -> int:
        return self.coordinator.last_plan_step

    def _next_version(self) -> WriteVersion:
        """A plan step published immediately — for callers that commit to
        storage directly (tests, loaders) with no reader able to observe
        the mid-apply state they create. Statement paths use
        `_commit_step` so the watermark trails the apply."""
        version = self.coordinator.propose(0)
        self.coordinator.publish(version.plan_step)
        return version

    @_contextmanager
    def _commit_step(self, tx_id: int = 0):
        """Propose→apply→publish envelope. The coordinator grants the plan
        step on entry; the read watermark advances only when the body's
        in-memory apply (stamps + delete marks) has finished, so lock-free
        SELECTs snapshotting mid-commit never observe a torn multi-shard
        apply. Publish runs in `finally` — a failed apply must not wedge
        the watermark (storage-level intent journals own partial-failure
        atomicity)."""
        version = self.coordinator.propose(tx_id)
        try:
            yield version
        finally:
            self.coordinator.publish(version.plan_step)

    def snapshot(self) -> Snapshot:
        return self.coordinator.read_snapshot()

    def session(self):
        """Open an interactive session (BEGIN/COMMIT/ROLLBACK scope)."""
        from ydb_tpu.tx import Session
        return Session(self)

    def register_udf(self, name: str, fn, returns: str = "string",
                     min_args: int = 1, max_args: int = 8) -> None:
        """Register a scalar UDF (`query/udf.py`): `fn(str_or_None,
        *literal_args)` evaluated once per DISTINCT dictionary value,
        gathered on device through a LUT. `returns`: string | int64 |
        float64 | bool."""
        self.catalog.udfs.register(name, fn, returns, min_args, max_args)

    # -- topics / changefeeds (PersQueue + change_exchange analogs) --------

    def create_topic(self, name: str, partitions: int = 1):
        import re as _re
        from ydb_tpu.storage.topic import Topic
        if not _re.fullmatch(r"[A-Za-z0-9_][A-Za-z0-9_.-]*", name):
            # the name becomes a directory under <root>/__topics — '/'
            # or '..' would escape it
            raise QueryError(f"invalid topic name {name!r}")
        if partitions < 1:
            raise QueryError("a topic needs at least one partition")
        with self.lock:
            if name in self.topics:
                raise QueryError(f"topic {name!r} already exists")
            self.topics[name] = Topic(name, partitions,
                                      self._topic_root(name))
            self._save_topics()
            return self.topics[name]

    def topic(self, name: str):
        t = self.topics.get(name)
        if t is None:
            raise QueryError(f"unknown topic {name!r}")
        return t

    def drop_topic(self, name: str) -> None:
        with self.lock:
            self.topic(name)
            if name in self._changefeeds.values():
                raise QueryError(f"topic {name!r} feeds a changefeed")
            del self.topics[name]
            root = self._topic_root(name)
            if root is not None and os.path.isdir(root):
                import shutil
                shutil.rmtree(root)
            self._save_topics()

    def enable_changefeed(self, table_name: str, topic_name: str) -> None:
        """Publish the row table's committed mutations into the topic
        (CDC; per-pk partition ordering)."""
        from ydb_tpu.storage.topic import ChangefeedSink
        with self.lock:
            if not self.catalog.has(table_name):
                raise QueryError(f"unknown table {table_name!r}")
            t = self._table(table_name)
            if getattr(t, "store_kind", "column") != "row":
                raise QueryError("changefeeds are row-store only for now")
            t.changefeed = ChangefeedSink(self.topic(topic_name),
                                          table_name, t.key_columns)
            self._changefeeds[table_name] = topic_name
            # publication floor: commits at or below this step predate
            # the changefeed and must not be re-emitted by replay healing
            self._cdc_since[table_name] = self.coordinator.last_plan_step
            self._save_topics()

    def _topic_root(self, name: str):
        if self.catalog.store is None:
            return None
        return os.path.join(self.catalog.store.root, "__topics", name)

    def _save_topics(self) -> None:
        if self.catalog.store is None:
            return
        from ydb_tpu.storage.persist import _atomic_json
        _atomic_json(
            os.path.join(self.catalog.store.root, "topics.json"),
            {"topics": {n: len(t.partitions)
                        for n, t in self.topics.items()},
             "changefeeds": {t: {"topic": n,
                                 "since": self._cdc_since.get(t, 0)}
                             for t, n in self._changefeeds.items()}})

    def _load_topics(self) -> None:
        import json as _json
        from ydb_tpu.storage.topic import ChangefeedSink, Topic
        path = os.path.join(self.catalog.store.root, "topics.json")
        if not os.path.exists(path):
            return
        with open(path) as f:
            meta = _json.load(f)
        for n, parts in meta.get("topics", {}).items():
            self.topics[n] = Topic(n, parts, self._topic_root(n))
        for table_name, cf in meta.get("changefeeds", {}).items():
            # legacy format stored a bare topic name; treat its floor as
            # "now" so replay healing never republishes history
            topic_name = cf["topic"] if isinstance(cf, dict) else cf
            since = cf.get("since", 0) if isinstance(cf, dict) \
                else self.coordinator.last_plan_step
            if self.catalog.has(table_name) and topic_name in self.topics:
                t = self.catalog.table(table_name)
                t.changefeed = ChangefeedSink(
                    self.topics[topic_name], table_name, t.key_columns)
                self._changefeeds[table_name] = topic_name
                self._cdc_since[table_name] = int(since)

    def _reconcile_changefeeds(self) -> None:
        """Heal torn topic tails after recovery: re-emit the row-WAL
        replay events through each wired changefeed. The deterministic
        producer seq_no dedups everything already published, so only a
        tail lost to a crash between the row-WAL fsync and the topic
        append lands again — exactly once, in commit order."""
        for table_name in self._changefeeds:
            t = self.catalog.table(table_name)
            log = getattr(t, "_replay_log", None)
            since = self._cdc_since.get(table_name, 0)
            if t.changefeed is None or not log:
                continue
            for version, events in log:
                if events and version.plan_step > since:
                    t.changefeed.emit(events, version)
        for t in self.catalog.tables.values():
            if getattr(t, "_replay_log", None) is not None:
                t._replay_log = None

    # -- entry -------------------------------------------------------------

    _AUDITED_KINDS = frozenset((
        "createtable", "droptable", "altertable", "createindex",
        "dropindex", "insert", "update", "delete", "begin", "commit",
        "rollback", "creatematerializedview", "dropmaterializedview"))

    def execute(self, sql: str, session=None,
                _internal: bool = False) -> HostBlock:
        """`_internal`: a re-entrant call from inside another statement
        (EXPLAIN ANALYZE, forced rollback) — already admitted and audited
        by its enclosing statement, so the quoter and audit skip it."""
        if not _internal and not self.quoter.acquire("queries"):
            from ydb_tpu.utils.metrics import GLOBAL
            GLOBAL.inc("engine/throttled")
            raise QueryError("query rate limit exceeded (quoter: the "
                             "'queries' resource bucket is empty)")
        from contextlib import nullcontext
        session = session or self._default_session
        # per-session statement serialization (SESSION_BUSY analog —
        # concurrency comes from many sessions, not one)
        ctx = session._mu if session is not self._default_session \
            else nullcontext()
        outermost = self.tracer._state().depth == 0
        # the sampling decision (and its accumulator/forced-slow side
        # effects) applies to OUTERMOST statements only — a nested
        # begin_trace inherits the open trace's decision anyway
        self.tracer.begin_trace(
            sampled=self._sample_decision(sql) if outermost else True)
        kind_box: list = []
        ok = False
        # resource ledger (utils/memledger.py): one per OUTERMOST
        # statement on this thread — a nested execute (EXPLAIN ANALYZE,
        # DQ router merge) contributes to the enclosing ledger
        from ydb_tpu.utils import memledger, progstats
        led = memledger.open_statement()
        # program-execution accumulator (utils/progstats.py): same
        # outermost-statement discipline — feeds QueryStats.programs and
        # the EXPLAIN ANALYZE `-- programs:` block
        pst = progstats.open_statement()
        try:
            with ctx, self.tracer.span("statement", sql=sql[:60]):
                block = self._execute_traced(sql, session, kind_box)
            ok = True
            return block
        finally:
            if pst is not None:
                progstats.close_statement(pst)
            if led is not None:
                memledger.close_statement(led)
                self._record_memory(sql, kind_box[0] if kind_box else "",
                                    led)
            self.last_trace = self.tracer.end_trace()
            # profiles record USER statements: a DQ stage program run
            # through a legacy (context-free) caller is still internal
            if outermost and self.last_trace \
                    and not self.executor.dq_stage_depth:
                self._record_profile(sql, self.last_trace,
                                     memory=led.summary()
                                     if led is not None else None)
            if not _internal:
                self._audit(sql, ok, kind_box[0] if kind_box else "")

    def _sample_decision(self, sql: str) -> bool:
        """Admission-time trace sampling: rate-based, with forced-on for
        EXPLAIN (the user asked for the profile) and for statements whose
        text previously exceeded the slow-query threshold. Nested
        (internal) statements inherit the enclosing decision — this is
        only consulted for the thread's OUTERMOST begin_trace."""
        if self.trace_sample >= 1.0:
            return True
        if sql.lstrip()[:7].lower() == "explain":
            return True
        if sql in self._slow_sqls:
            from ydb_tpu.utils.metrics import GLOBAL
            GLOBAL.inc("trace/forced_slow")
            return True
        if self.trace_sample <= 0.0:
            return False
        with self._trace_mu:
            self._trace_acc += self.trace_sample
            if self._trace_acc >= 1.0:
                self._trace_acc -= 1.0
                return True
        return False

    def _record_memory(self, sql: str, kind: str, led) -> None:
        """Append one closed ledger to the `.sys/query_memory` ring.
        Statements that never touched the device (DDL, constant
        SELECTs) are skipped — a ring of zero rows would bury the
        queries this view exists to rank."""
        s = led.summary()
        if not (s["peak_bytes"] or s["transfers"] or s["padded_bytes"]):
            return
        self.memory_stats.append({
            "sql": sql, "kind": kind,
            "peak_bytes": s["peak_bytes"],
            "alloc_bytes": s["alloc_bytes"],
            "live_bytes": s["live_bytes"],
            "padded_bytes": s["padded_bytes"],
            "waste_bytes": s["waste_bytes"],
            "pad_efficiency": s["pad_efficiency"],
            "transfers": s["transfers"],
            "transfer_bytes": s["transfer_bytes"],
            "to_pandas_in_plan": s["to_pandas_in_plan"],
            "admission_est_bytes": s["admission_est_bytes"],
            "est_error_pct": s["est_error_pct"],
        })

    def _record_profile(self, sql: str, spans: list,
                        stage_stats: list = None, total_ms: float = None,
                        rows_out: int = None, kind: str = None,
                        memory: dict = None) -> None:
        """Append one assembled profile to the last-N ring
        (`.sys/query_profiles`): the span tree plus its device-timeline
        rollup. `stage_stats`: the DQ runner's per-(stage, worker) rows
        for distributed queries. total_ms/rows_out/kind overrides: the
        router passes the DQ wall explicitly — for a distributed query
        `last_stats` holds only the router-MERGE statement's numbers
        (or a previous statement's, when the final stage had no merge
        SQL), not the graph's."""
        from ydb_tpu.utils.tracing import phase_breakdown
        st = self.last_stats
        # last_stats is only trustworthy when it belongs to THIS
        # statement and finished: a statement that raised before (or
        # inside) stats assembly leaves the PREVIOUS statement's record
        # in the thread-local — attributing its wall/kind/rows to this
        # profile row would fabricate exactly the numbers this view
        # exists to make reliable
        mine = st is not None and getattr(st, "sql", None) == sql
        finished = mine and getattr(st, "total_ms", 0.0) > 0.0
        rec = {
            "trace_id": spans[0].trace_id,
            "sql": sql,
            "kind": kind if kind is not None
            else (st.kind if mine else "error"),
            "total_ms": total_ms if total_ms is not None
            else (st.total_ms if finished
                  else round(spans[0].dur_ms, 3)),
            "rows_out": rows_out if rows_out is not None
            else (int(st.rows_out) if mine else 0),
            "phases": phase_breakdown(spans),
            "n_spans": len(spans),
            "spans": [s.to_dict() for s in spans],
            "stages": list(stage_stats or []),
        }
        # critical-path extraction (utils/critpath.py): which chain of
        # segments actually bounded this query's wall — classified,
        # counted (`crit/*`), ringed (`.sys/query_critical_path`), and
        # stored on the profile for the `/trace/<id>` timeline export.
        # Lever-gated: YDB_TPU_CRITPATH=0 freezes all of it.
        from ydb_tpu.utils import critpath
        if critpath.enabled():
            try:
                cp = critpath.extract(spans, memory=memory)
                rec["critical_path"] = cp
                critpath.record_counters(cp)
                self.critpath_stats.append({
                    "trace_id": rec["trace_id"], "sql": sql,
                    "kind": rec["kind"], "wall_ms": cp["wall_ms"],
                    "coverage": cp["coverage"],
                    "connected": cp["connected"],
                    "non_device_ms": cp["non_device_ms"],
                    "dominant_span": cp["dominant_span"],
                    "dominant_class": cp["dominant_class"],
                    "dominant_ms": cp["dominant_ms"],
                    **{f"{cls}_ms": cp["classes"].get(cls, 0.0)
                       for cls in critpath.CLASSES},
                })
            except Exception:                # noqa: BLE001 — analysis
                pass                         # must never fail a query
        self.profiles.append(rec)

    def _audit(self, sql: str, ok: bool, kind: str) -> None:
        """Audit trail for mutating statements (the ydb/core/audit sink):
        CRC-framed records in <root>/audit.bin, replayable like any WAL.
        SELECTs are not audited (matching the reference's default); the
        kind comes from THIS statement's parse (not last_stats, which a
        nested execute may have reassigned)."""
        if kind not in self._AUDITED_KINDS or self.catalog.store is None:
            return
        import time as _time
        from ydb_tpu.storage import blobfile as _B
        try:
            _B.wal_append(
                os.path.join(self.catalog.store.root, "audit.bin"),
                {"ts": _time.time(), "kind": kind, "sql": sql[:500],
                 "status": "ok" if ok else "error",
                 "rows": int(getattr(self, "last_rows_affected", 0))},
                sync=False)
        except OSError:
            pass    # auditing must not fail the statement

    def trace_to_topic(self, topic_name: str) -> None:
        """Export finished traces into a topic (the OTLP uploader seat,
        `wilson_uploader.cpp`): each trace is one message, schema-
        stamped. `v: 2` + `timebase: "router"` declare that every
        span's start_ms is already rebased onto THIS engine's tracer
        clock (cross-worker spans via the DqRunTask clock-offset
        estimate) — v1 messages shipped raw worker-local clocks, which
        downstream consumers could not compare across workers."""
        t = self.topic(topic_name)
        self.tracer.sink = lambda spans: t.write(
            {"v": 2, "timebase": "router", "spans": spans})

    def _execute_traced(self, sql: str, session=None,
                        kind_box: Optional[list] = None) -> HostBlock:
        from ydb_tpu.utils.metrics import GLOBAL, QueryStats, Timer
        session = session or self._default_session
        t = Timer()
        stats = QueryStats(sql=sql)
        # per-statement group-by trace window (thread-local): whatever
        # sorted group-bys THIS statement freshly compiles lands in
        # stats.groupby for EXPLAIN ANALYZE / query history. Mark/delta,
        # not reset/snapshot — a nested same-thread statement (DQ router
        # merge stage) must not wipe the outer statement's window
        from ydb_tpu.ops.xla_exec import groupby_trace_mark
        stats._gb_mark = groupby_trace_mark()
        # span-window mark: THIS statement's phase breakdown must only
        # cover spans recorded from here on — a nested statement (the DQ
        # router merge) shares the trace with already-ingested worker
        # spans whose device time is NOT this statement's
        stats._span_mark = len(self.tracer.spans)
        with self.tracer.span("parse"):
            stmt = parse(sql)
        stats.parse_ms = t.lap()
        stats.kind = type(stmt).__name__.lower()
        if kind_box is not None:
            kind_box.append(stats.kind)
        self.last_rows_affected = 0
        GLOBAL.inc("engine/statements")
        self.last_stats = stats
        tx = session.tx
        snap = tx.snapshot if tx is not None else self.snapshot()
        try:
            from ydb_tpu.tx import TxAborted, TxCommitTorn
            if isinstance(stmt, (ast.Begin, ast.Commit, ast.Rollback)):
                with self.lock:
                    try:
                        if isinstance(stmt, ast.Begin):
                            session.begin()
                        elif isinstance(stmt, ast.Commit):
                            session.commit()
                        else:
                            session.rollback()
                    except (TxAborted, TxCommitTorn) as e:
                        # TxCommitTorn keeps its "internal: ... torn"
                        # message — SQL clients see the distinct error
                        # text; session-API clients get the distinct type
                        raise QueryError(str(e)) from e
                    if isinstance(stmt, ast.Commit):
                        # a tx commit lands its CDC events at stamp time —
                        # give lagging views a chance to fold off-read
                        for vt in list(self.views._by_source):
                            self.views.on_commit(vt)
                return _unit_block()
            if isinstance(stmt, ast.Explain):
                return self._explain_stmt(stmt, session)
            if isinstance(stmt, (ast.SetOp, ast.Select)):
                # read locks FIRST — every select path (fused, windowed,
                # set-op, materialized) must register conflicts
                names = self._referenced_tables(stmt)
                stats.tables = sorted(names)
                if tx is not None:
                    for name in names:
                        if self.catalog.has(name):
                            tx.lock(self.catalog.table(name))
                # register the snapshot: auto-compaction must not restamp
                # portions this lock-free read still scans. Autocommit
                # reads re-take the snapshot ATOMICALLY with registration;
                # tx snapshots are already coordinator-pinned, so their
                # registration has no gap to race.
                if tx is None:
                    snap = self._register_read()
                else:
                    self._enter_read(snap.plan_step)
                try:
                    return self._execute_read(stmt, sql, snap, stats, t)
                finally:
                    self._exit_read(snap.plan_step)
            # everything below mutates shared state — one writer at a time
            # (readers above run lock-free over their MVCC snapshots)
            with self.lock:   # noqa: SIM117
                # re-take the autocommit snapshot UNDER the lock: two
                # UPDATE v = v + 1 statements that both snapshotted before
                # serializing here would otherwise read the same state and
                # lose an update
                snap = tx.snapshot if tx is not None else self.snapshot()
                if isinstance(stmt, ast.CreateTable):
                    if tx is not None:
                        raise QueryError("DDL inside a transaction is not "
                                         "supported")
                    return self._create_table(stmt)
                if isinstance(stmt, ast.CreateMaterializedView):
                    if tx is not None:
                        raise QueryError("DDL inside a transaction is not "
                                         "supported")
                    from ydb_tpu.views import UnsupportedView
                    try:
                        self.views.create(stmt.name, stmt.query, stmt.sql)
                    except UnsupportedView as e:
                        raise QueryError(
                            f"unsupported materialized view: {e}") from e
                    return _unit_block()
                if isinstance(stmt, ast.DropMaterializedView):
                    if tx is not None:
                        raise QueryError("DDL inside a transaction is not "
                                         "supported")
                    self.views.drop(stmt.name, stmt.if_exists)
                    return _unit_block()
                if isinstance(stmt, ast.DropTable):
                    if tx is not None:
                        raise QueryError("DDL inside a transaction is not "
                                         "supported")
                    if stmt.if_exists and not self.catalog.has(stmt.name):
                        return _unit_block()
                    deps = self.views.on_table(stmt.name)
                    if deps:
                        raise QueryError(
                            f"table {stmt.name!r} feeds materialized "
                            "view(s): "
                            + ", ".join(sorted(v.name for v in deps)))
                    self.catalog.drop_table(stmt.name)
                    if self._changefeeds.pop(stmt.name, None) is not None:
                        self._cdc_since.pop(stmt.name, None)
                        self._save_topics()   # else the topic stays pinned
                    return _unit_block()
                if isinstance(stmt, ast.AlterTable):
                    if tx is not None:
                        raise QueryError("DDL inside a transaction is not "
                                         "supported")
                    return self._alter_table(stmt)
                if isinstance(stmt, (ast.CreateIndex, ast.DropIndex)):
                    if tx is not None:
                        raise QueryError("DDL inside a transaction is not "
                                         "supported")
                    if not self.catalog.has(stmt.table):
                        raise QueryError(f"unknown table {stmt.table!r}")
                    t = self._table(stmt.table)
                    if getattr(t, "store_kind", "column") != "row":
                        raise QueryError(
                            "secondary indexes are row-store only (column "
                            "tables index via per-portion min/max stats)")
                    try:
                        if isinstance(stmt, ast.CreateIndex):
                            t.create_index(stmt.name, stmt.column)
                        else:
                            t.drop_index(stmt.name)
                    except ValueError as e:
                        raise QueryError(str(e)) from e
                    if self.catalog.store is not None:
                        self.catalog.store.save_catalog(self.catalog)
                    return _unit_block()
                if isinstance(stmt, ast.Insert):
                    return self._insert(stmt, snap, tx)
                if isinstance(stmt, ast.Update):
                    return self._update(stmt, snap, tx)
                if isinstance(stmt, ast.Delete):
                    return self._delete(stmt, snap, tx)
                raise QueryError(
                    f"unsupported statement {type(stmt).__name__}")
        except (BindError, PlanError) as e:
            raise QueryError(str(e)) from e

    def _execute_read(self, stmt, sql: str, snap, stats, t) -> HostBlock:
        """SELECT / set-op execution — lock-free, runs concurrently."""
        from ydb_tpu.utils.metrics import GLOBAL
        # collect this read's view-serving decisions (thread-local:
        # reads run concurrently) for QueryStats / EXPLAIN ANALYZE
        self._view_tls.notes = []
        if isinstance(stmt, ast.SetOp):
            block = self._execute_set_op(stmt, snap)
            self.executor.last_path = "set-op"
            self._finish_stats(stats, t, block)
            return block
        from ydb_tpu.query import window as W
        if W.has_window(stmt):
            block = self._execute_windowed(stmt, snap)
            self._finish_stats(stats, t, block)
            return block
        if stmt.relation is None:
            block = self._select_without_from(stmt, snap)
            self.executor.last_path = "literal"
            self._finish_stats(stats, t, block)
            return block
        if self._needs_materialize(stmt):
            block = self._execute_materialized(stmt, snap)
            self._finish_stats(stats, t, block)
            return block
        from ydb_tpu.ops.xla_exec import late_mat_enabled
        from ydb_tpu.query.bounds import bounds_enabled
        # the bounds/late-mat levers change plan STRUCTURE (carry keys,
        # stamped bounds, latemat annotations) — they must invalidate
        # cached plans like a schema change
        fp = (self._table_fingerprint(stmt, stats.tables),
              bounds_enabled(), late_mat_enabled())
        cached = self._plan_cache.get(sql) \
            if self.config.flag("enable_plan_cache") else None
        if cached is not None and cached[0] == fp:
            plan = cached[1]
            self.plan_cache_hits += 1
            stats.plan_cache_hit = True
            GLOBAL.inc("engine/plan_cache_hits")
        else:
            with self.tracer.span("plan"):
                plan = self.planner.plan_select(stmt)
            if self.config.flag("enable_plan_cache"):
                self._plan_cache[sql] = (fp, plan)
            GLOBAL.inc("engine/plan_cache_misses")
        stats.plan_ms = t.lap()
        # memory admission (kqp_rm_service analog): reserve the
        # scan+build estimate; oversubscribed queries queue here
        from ydb_tpu.query.admission import (
            AdmissionTimeout, estimate_plan_bytes,
        )
        # floor: even column-less scans (count(*)) reserve a
        # nominal slot so admission can actually bound concurrency
        est = max(estimate_plan_bytes(self.catalog, plan, snap), 1 << 20)
        # admission calibration: the ledger compares this estimate to
        # the measured peak at close (`admission/est_error_pct`)
        from ydb_tpu.utils import memledger
        memledger.note_admission(est)
        # compile-ahead lane (ydb_tpu/progstore): a novel plan shape
        # starts its fused program fill on the background pool NOW —
        # store deserialize or fresh AOT compile, single-flight deduped
        # with the dispatch below — overlapped with the window/admission
        # wait it would otherwise serialize behind
        self.executor.compile_ahead(plan, plan.params, snap)
        try:
            block = None
            if self._batch_lane is not None:
                # batched dispatch lane: same-shape arrivals coalesce
                # into one stacked execution (window + admission handled
                # by the batch leader — members hold neither)
                block = self._batch_lane.try_run(plan, snap, est, stats)
            if block is None:
                block = self._dispatch_and_drain(plan, snap, est)
        except AdmissionTimeout as e:
            raise QueryError(str(e)) from e
        self._finish_stats(stats, t, block)
        return block

    def _dispatch_and_drain(self, plan, snap, est: int) -> HostBlock:
        """The concurrent query pipeline: a *dispatch phase* (plan →
        compile-cache hit → device enqueue, `Executor.execute_async`)
        followed by a *readout phase* that resolves the device-result
        future lock-free — so while this query drains D2H, the next
        one's dispatch is already in flight (overlapped dispatches
        pipeline ~35 ms → ~10 ms on the measured hardware, PERF.md).

        The admission reservation spans BOTH phases (result buffers
        live in device memory until drained), and `pipeline_window`
        bounds dispatched-but-undrained queries on top of the byte
        budget."""
        # window slot FIRST, byte reservation second: a query parked
        # behind the window must not sit on admission bytes it isn't
        # using (that would shed concurrent large queries with spurious
        # AdmissionTimeouts). Sem holders waiting on admission shed via
        # its deadline and release the slot — no circular wait — and the
        # slot wait itself is BOUNDED by the same deadline, so a window
        # saturated by admission-queued queries sheds instead of
        # head-of-line blocking every later SELECT indefinitely.
        from ydb_tpu.query.admission import AdmissionTimeout
        from ydb_tpu.utils.metrics import GLOBAL
        if not self._pipe_sem.acquire(timeout=self.admission.timeout_s):
            GLOBAL.inc("pipeline/window_timeouts")
            raise AdmissionTimeout(
                f"pipeline window saturated: {self.pipeline_window} "
                "queries dispatched-or-queued for longer than the "
                "admission deadline")
        try:
            import time as _time
            t_adm = _time.perf_counter()
            with self.admission.admit(est):
                wait_ms = (_time.perf_counter() - t_adm) * 1000.0
                if wait_ms >= 1.0:
                    # the statement QUEUED behind the byte budget:
                    # record the wait as its own (already-elapsed) span
                    # so critical-path extraction can class it
                    # admission_wait instead of burying it in a gap
                    sp = self.tracer.attach_span(
                        "admission-wait", admitted_mb=est >> 20)
                    if sp is not None:
                        sp.start_ms = round(sp.start_ms - wait_ms, 3)
                        sp.dur_ms = round(wait_ms, 3)
                return self._dispatch_drain_admitted(plan, snap, est)
        finally:
            self._pipe_sem.release()

    def _dispatch_drain_admitted(self, plan, snap, est: int) -> HostBlock:
        """Body of the pipeline once the window slot + byte reservation
        are held: dispatch, account the in-flight overlap, drain."""
        from ydb_tpu.utils.metrics import GLOBAL, Timer
        entered = False
        try:
            with self.tracer.span("execute", admitted_mb=est >> 20):
                fut = self.executor.execute_async(plan, snap)
            with self._pipe_mu:
                self._pipe_inflight += 1
                entered = True
                if self._pipe_inflight > 1:
                    # another query was dispatched and undrained when
                    # this one entered: the pipeline genuinely
                    # overlapped (the counter the threaded throughput
                    # test asserts on)
                    GLOBAL.inc("pipeline/overlap_hits")
                GLOBAL.set("pipeline/in_flight", self._pipe_inflight)
            GLOBAL.inc("pipeline/dispatched")
            t_read = Timer()
            with self.tracer.span("readout"):
                block = fut.result()
            GLOBAL.inc("pipeline/readout_ms", t_read.ms())
            return block
        finally:
            if entered:
                with self._pipe_mu:
                    self._pipe_inflight -= 1
                    GLOBAL.set("pipeline/in_flight", self._pipe_inflight)

    def _select_without_from(self, sel: ast.Select,
                             snap: Optional[Snapshot] = None) -> HostBlock:
        """Constant SELECT (`select 1 + 1 as x`): fold each item host-side
        — one row, no scan (the literal-executer analog). Scalar
        subqueries evaluate first (the q88 report shape: a row of
        independent counts)."""
        from ydb_tpu.core import dtypes as dt
        from ydb_tpu.core.dictionary import Dictionary
        from ydb_tpu.query.binder import _try_fold

        def eval_subs(e):
            import dataclasses
            if isinstance(e, ast.ScalarSubquery):
                blk = self._run_select(e.query, snap)
                if len(blk.schema.names) != 1:
                    raise QueryError("scalar subquery must select one "
                                     "column")
                if blk.length > 1:
                    raise QueryError("scalar subquery returned "
                                     f"{blk.length} rows")
                if blk.length == 0:
                    return ast.Literal(None)     # SQL: empty → NULL
                v = blk.to_pandas().iloc[0, 0]
                if v is None or (isinstance(v, float) and np.isnan(v)):
                    return ast.Literal(None)
                if hasattr(v, "item"):
                    v = v.item()   # numpy scalar → python
                return ast.Literal(v)
            if not hasattr(e, "__dataclass_fields__"):
                return e

            def rw(v):
                if isinstance(v, tuple):
                    return tuple(rw(x) for x in v)
                if hasattr(v, "__dataclass_fields__"):
                    return eval_subs(v)
                return v
            return dataclasses.replace(
                e, **{fld: rw(getattr(e, fld))
                      for fld in e.__dataclass_fields__})

        cols, arrays, valids, dicts = [], {}, {}, {}
        for i, item in enumerate(sel.items):
            expr2 = eval_subs(item.expr)
            if isinstance(expr2, ast.Literal) and expr2.value is None:
                name = item.alias or f"column{i}"
                cols.append(Column(name, dt.DType(dt.Kind.INT64, True)))
                arrays[name] = np.zeros(1, np.int64)
                valids[name] = np.zeros(1, bool)
                continue
            folded = _try_fold(expr2)
            if folded is None:
                raise QueryError(
                    "SELECT without FROM supports constant expressions only")
            name = item.alias or f"column{i}"
            v = folded.value
            if v is None:
                cols.append(Column(name, dt.DType(dt.Kind.INT64, True)))
                arrays[name] = np.zeros(1, np.int64)
                valids[name] = np.zeros(1, bool)
            elif isinstance(v, bool):
                cols.append(Column(name, dt.DType(dt.Kind.BOOL, False)))
                arrays[name] = np.array([v])
            elif isinstance(v, int):
                cols.append(Column(name, dt.DType(dt.Kind.INT64, False)))
                arrays[name] = np.array([v], np.int64)
            elif isinstance(v, float):
                cols.append(Column(name, dt.DType(dt.Kind.FLOAT64, False)))
                arrays[name] = np.array([v], np.float64)
            else:
                d = Dictionary()
                cols.append(Column(name, dt.DType(dt.Kind.STRING, False)))
                arrays[name] = d.encode([str(v)])
                dicts[name] = d
        return HostBlock.from_arrays(Schema(cols), arrays, valids, dicts)

    def _finish_stats(self, stats, t, block) -> None:
        from ydb_tpu.ops.xla_exec import groupby_trace_delta
        from ydb_tpu.utils.metrics import GLOBAL, GLOBAL_HIST
        from ydb_tpu.utils.tracing import phase_breakdown
        stats.execute_ms = t.lap()
        stats.total_ms = stats.parse_ms + stats.plan_ms + stats.execute_ms
        stats.rows_out = block.length
        stats.fused = self.executor.last_path == "fused"
        stats.distributed = self.executor.last_path == "distributed"
        stats.view_serving = getattr(self._view_tls, "notes", None) or []
        delta = groupby_trace_delta(getattr(stats, "_gb_mark", {}))
        # the bounds-lattice gauges ride the same trace window under a
        # `bounds_` prefix — split them into their own stats surface
        stats.bounds = {k[len("bounds_"):]: v for k, v in delta.items()
                        if k.startswith("bounds_")}
        stats.groupby = {k: v for k, v in delta.items()
                         if not k.startswith("bounds_")}
        if self.tracer.sampled:
            stats.phases = phase_breakdown(
                self.tracer.spans[getattr(stats, "_span_mark", 0):])
        # resource-ledger rollup as of NOW (the ledger closes in
        # execute() after this statement returns; EXPLAIN ANALYZE and
        # bench read stats.memory, so the live summary attaches here)
        from ydb_tpu.utils import memledger
        led = memledger.current()
        if led is not None:
            stats.memory = led.summary()
        # program roofline rollup (utils/progstats.py): which compiled
        # programs this statement executed, their measured device ms
        # joined to the compiler's cost model — the `-- programs:` block
        from ydb_tpu.utils import progstats
        ps = progstats.current()
        if ps is not None:
            stats.programs = ps.summary()
        # per-statement critical path over the same span window (the
        # EXPLAIN ANALYZE `-- critical path:` source, joined with the
        # live ledger's bytes); the full-tree extraction with counters
        # and the sysview ring happens once in _record_profile
        from ydb_tpu.utils import critpath
        if self.tracer.sampled and critpath.enabled():
            window = self.tracer.spans[getattr(stats, "_span_mark", 0):]
            # root the window under a CLOSED copy of the still-open
            # statement span: un-spanned statement-interior time (binder
            # work, dictionary predicate evaluation, CTE/derived-table
            # materialization — the q13 host lane) then classifies as
            # the statement's host_lane self-time instead of vanishing
            # into a virtual-root scheduler gap
            stk = self.tracer._stack
            if stk:
                import dataclasses as _dc
                window = [_dc.replace(
                    stk[-1],
                    dur_ms=self.tracer._now() - stk[-1].start_ms)] \
                    + window
            if window:
                try:
                    stats.critical_path = critpath.summarize(
                        critpath.extract(window, memory=stats.memory))
                except Exception:            # noqa: BLE001 — analysis
                    pass                     # must never fail a query
        # latency histograms count USER statements once: a nested
        # internal statement (EXPLAIN ANALYZE's re-entrant execute, the
        # DQ router-merge SELECT — its trace depth is >1) must not add a
        # second, cheaper sample that drags p50 down and doubles count.
        # Worker-side DQ stage programs are excluded via dq_stage_depth,
        # NOT trace depth — an unsampled task opens no trace, and the
        # histogram contents must not depend on the sampling rate
        if self.tracer._state().depth <= 1 \
                and not self.executor.dq_stage_depth:
            GLOBAL_HIST.observe("query/latency_ms", stats.total_ms)
            GLOBAL_HIST.observe("query/parse_ms", stats.parse_ms)
            GLOBAL_HIST.observe("query/plan_ms", stats.plan_ms)
            GLOBAL_HIST.observe("query/execute_ms", stats.execute_ms)
            # slow-query bookkeeping is USER-statement-scoped too: DQ
            # stage/merge SQL embeds per-query uuid temp names that can
            # never match a future run — remembering them would churn
            # the bounded forced-trace set and inflate slow_query/*
            self._note_slow(stats.sql, stats.total_ms, stats.kind)
        GLOBAL.inc("engine/rows_out", block.length)
        GLOBAL.inc("engine/queries")
        self.query_history.append(stats)

    def _note_slow(self, sql: str, total_ms: float, kind: str) -> None:
        """Slow-query log counter family + the forced-sampling set: a
        statement over the threshold is counted, and its TEXT is
        remembered so its next run is traced even at sample rate 0."""
        if total_ms < self.slow_query_ms or not sql:
            return
        from ydb_tpu.utils.metrics import GLOBAL
        GLOBAL.inc("slow_query/count")
        GLOBAL.inc(f"slow_query/{kind or 'other'}")
        GLOBAL.set_max("slow_query/worst_ms", total_ms)
        with self._trace_mu:
            if len(self._slow_sqls) >= 256 and sql not in self._slow_sqls:
                # bounded: drop the least-slow remembered offender
                victim = min(self._slow_sqls, key=self._slow_sqls.get)
                del self._slow_sqls[victim]
            self._slow_sqls[sql] = max(self._slow_sqls.get(sql, 0.0),
                                       total_ms)

    def counters(self) -> dict:
        """Live counter snapshot (the /counters endpoint payload)."""
        from ydb_tpu.ops.xla_exec import _GLOBAL_CACHE
        from ydb_tpu.utils.metrics import GLOBAL, GLOBAL_HIST, HIST_FAMILIES
        c = GLOBAL.snapshot()
        c.update(GLOBAL_HIST.snapshot())
        # the fixed histogram families are always visible (zeros before
        # the first observation), like the counter families below
        for fam in HIST_FAMILIES:
            for q in ("count", "p50", "p95", "p99", "max"):
                c.setdefault(f"hist/{fam}/{q}", 0)
        c.update({
            "engine/plan_cache_size": len(self._plan_cache),
            "executor/fused_plans": len(self.executor._fused_cache),
            "device_cache/hits": self.executor.device_cache.hits,
            "device_cache/misses": self.executor.device_cache.misses,
            "device_cache/bytes": self.executor.device_cache.bytes,
            "program_cache/hits": _GLOBAL_CACHE.hits,
            "program_cache/misses": _GLOBAL_CACHE.misses,
            "coordinator/plan_step": self.coordinator.last_plan_step,
            "pipeline/window": self.pipeline_window,
            "batch/window_ms": self.batch_window_ms,
        })
        # always-visible counters (zero before the first SELECT / fresh
        # compile), so dashboards/probes never see missing keys — the
        # set is the registry's [viz] marks, one source of truth
        from ydb_tpu.utils.metrics import ALWAYS_VISIBLE
        for k in ALWAYS_VISIBLE:
            c.setdefault(k, 0)
        c.setdefault("trace/sample_rate", self.trace_sample)
        c.setdefault("trace/profiles_held", len(self.profiles))
        return c

    def prewarm(self, tables=None) -> int:
        """Upload table columns into the HBM cache ahead of queries (the
        buffer-pool warmup analog; see `Executor.prewarm`)."""
        return self.executor.prewarm(tables)

    def _explain_stmt(self, stmt: ast.Explain, session) -> HostBlock:
        """EXPLAIN [ANALYZE] — plan text (+ live execution stats), the
        `kqp_query_plan.cpp` plan-with-stats analog."""
        from ydb_tpu.core.dictionary import Dictionary
        from ydb_tpu.core import dtypes as dt
        if self._needs_materialize(stmt.query):
            # CTE/derived-table stages materialize at run time; their
            # sub-plans depend on intermediate results
            lines = ["(materialized CTE/derived-table stages; run EXPLAIN "
                     "ANALYZE for live stats)"]
        elif stmt.query.relation is None:
            lines = ["(constant SELECT — literal executer, no scan)"]
        else:
            try:
                lines = explain(
                    self.planner.plan_select(stmt.query)).split("\n")
            except (BindError, PlanError, KeyError) as e:
                raise QueryError(str(e)) from e
        if isinstance(stmt.query, ast.Select):
            # serving-mode probe (no fold): which way would this read go
            snap = self.snapshot()
            for name in sorted(self._referenced_tables(stmt.query)):
                view = self.views.get(name)
                if view is not None:
                    mode = view.peek_mode(snap)
                    serving = (f"state @ plan_step {view.watermark}"
                               if mode == "state"
                               else f"base-query fallback ({mode})")
                    lines.append(
                        f"-- view {name}: watermark plan_step="
                        f"{view.watermark}, serving={serving}")
        if stmt.analyze:
            block = self.execute(stmt.sql, session=session, _internal=True)
            lines += self.last_stats.render().split("\n")
            tr = self.tracer.render()
            if tr:
                lines += ["-- trace:"] + tr.split("\n")
        d = Dictionary()
        codes = d.encode(lines)
        schema = Schema([Column("plan", dt.DType(dt.Kind.STRING, False))])
        return HostBlock.from_arrays(schema, {"plan": codes},
                                     dictionaries={"plan": d})

    def _run_select(self, sel,
                    snap: Optional[Snapshot] = None) -> HostBlock:
        """Execute an in-memory Select/SetOp AST (DML subflows, CTE
        bodies, window inner queries) — no text-keyed plan cache."""
        from ydb_tpu.query import window as W
        snap = snap or self.snapshot()
        if isinstance(sel, ast.SetOp):
            return self._execute_set_op(sel, snap)
        if W.has_window(sel):
            return self._execute_windowed(sel, snap)
        if self._needs_materialize(sel):
            return self._execute_materialized(sel, snap)
        plan = self.planner.plan_select(sel)
        return self.executor.execute(plan, snap)

    def _execute_set_op(self, stmt: ast.SetOp,
                        snap: Optional[Snapshot] = None) -> HostBlock:
        """UNION / UNION ALL: CTEs materialize once (visible to every
        arm), arms run through the normal device path, the combine (and
        dedup for UNION) runs host-side."""
        from ydb_tpu.query import window as W
        snap = snap or self.snapshot()
        temps: list = []
        try:
            rewritten = self._rewrite_sel(stmt, {}, temps, snap)
            # combine/dedup is host pandas work: spanned so it ranks as
            # host_lane on the critical path (arms' device spans nest
            # inside and classify themselves)
            with self.tracer.span("setop-host-lane"):
                df = self._eval_setop_df(rewritten, snap)
                try:
                    df = W.apply_order_limit(df, stmt.order_by,
                                             stmt.limit, stmt.offset)
                except ValueError as e:
                    raise QueryError(str(e)) from e
            return HostBlock.from_pandas(df)
        finally:
            for tn in temps:
                if self.catalog.has(tn):
                    self.catalog.drop_table(tn)

    def _eval_setop_df(self, node, snap):
        """Evaluate an already-rewritten SetOp tree to a pandas frame."""
        import pandas as pd
        if isinstance(node, ast.SetOp):
            left = self._eval_setop_df(node.left, snap)
            right = self._eval_setop_df(node.right, snap)
            if len(left.columns) != len(right.columns):
                raise QueryError("UNION arms have different arity")
            right.columns = left.columns
            # the combined frame is the actual host job — guard it too
            # (N arms each under the limit can still combine over it);
            # count=False: rows were already counted at their leaf arms
            self._host_lane_guard(len(left) + len(right), "setop",
                                  count=False)
            if node.op in ("union", "union_all"):
                out = pd.concat([left, right], ignore_index=True)
                if node.op == "union":
                    out = out.drop_duplicates(ignore_index=True)
                return out
            cols = list(left.columns)

            def counts(lf, rf, how):
                """Per-distinct-row multiplicities of both arms."""
                lc = lf.groupby(cols, dropna=False).size() \
                       .rename("__l").reset_index()
                rc = rf.groupby(cols, dropna=False).size() \
                       .rename("__r").reset_index()
                return lc.merge(rc, on=cols, how=how)

            if node.op == "intersect":
                return left.drop_duplicates().merge(
                    right.drop_duplicates(), on=cols, how="inner") \
                    .reset_index(drop=True)
            if node.op == "intersect_all":
                m = counts(left, right, "inner")
                reps = np.minimum(m["__l"], m["__r"]).to_numpy()
            elif node.op == "except":
                m = left.drop_duplicates().merge(
                    right.drop_duplicates(), on=cols, how="left",
                    indicator=True)
                return m[m["_merge"] == "left_only"][cols] \
                    .reset_index(drop=True)
            else:                    # except_all: multiplicity difference
                m = counts(left, right, "left")
                reps = np.maximum(m["__l"] - m["__r"].fillna(0), 0) \
                    .astype(int).to_numpy()
            return m[cols].loc[m.index.repeat(reps)] \
                          .reset_index(drop=True)
        arm = self._run_select(node, snap)
        self._host_lane_guard(arm.length, "setop")
        return arm.to_pandas()

    def _host_lane_guard(self, rows: int, lane: str,
                         count: bool = True) -> None:
        """Host pandas lanes (windows, set-op combine) degrade loudly: a
        counter records the rows crossing to host (`count=False` for
        re-checks of already-counted rows, e.g. set-op combine levels),
        and frames above the configured limit refuse instead of silently
        becoming single-core pandas jobs."""
        from ydb_tpu.utils.metrics import GLOBAL
        if count:
            GLOBAL.inc(f"engine/host_lane/{lane}_rows", rows)
        if rows > self.config.host_lane_max_rows:
            raise QueryError(
                f"{lane} host-fallback lane refused a {rows}-row frame "
                f"(host_lane_max_rows={self.config.host_lane_max_rows}; "
                f"raise it in config to accept the single-core cost)")

    def _execute_windowed(self, sel: ast.Select,
                          snap: Optional[Snapshot] = None) -> HostBlock:
        """Window functions: the inner query (scan/filter/join/agg) runs
        on the device; the window pass runs host-side over its (usually
        post-aggregation) result — see `ydb_tpu/query/window.py`."""
        from ydb_tpu.query import window as W
        snap = snap or self.snapshot()
        try:
            inner, outer, post = W.split_windowed(sel)
        except ValueError as e:
            raise QueryError(str(e)) from e
        inner_block = self._run_select(inner, snap)
        df = None
        device_ok = self.config.flag("enable_device_windows") \
            and inner_block.length >= self.config.window_device_min_rows
        if device_ok and post is None and not sel.distinct \
                and sel.limit is not None:
            # final ORDER BY + LIMIT pushable: every output leaves the
            # device sliced to offset+limit rows (O(rows) egress was the
            # dominant window cost — PERF.md r5)
            fs = self._final_sort_spec(sel, outer)
            if fs is not None:
                with self.tracer.span("window-device",
                                      rows=inner_block.length):
                    done = self._windows_on_device(inner_block, outer,
                                                   final_sort=fs,
                                                   limit=sel.limit,
                                                   offset=sel.offset
                                                   or 0)
                if done is not None:
                    lo = sel.offset or 0
                    return HostBlock.from_pandas(
                        done.iloc[lo:lo + sel.limit]
                        .reset_index(drop=True))
        if device_ok:
            with self.tracer.span("window-device",
                                  rows=inner_block.length):
                df = self._windows_on_device(inner_block, outer)
        if df is None:
            self._host_lane_guard(inner_block.length, "window")
            try:
                # its own span so the single-core pandas lane ranks as
                # host_lane on the critical path (the q13 class), not
                # as unattributed statement self-time
                with self.tracer.span("window-host-lane",
                                      rows=inner_block.length):
                    df = W.compute_windows(inner_block.to_pandas(),
                                           outer)
            except ValueError as e:
                raise QueryError(str(e)) from e
        if post is not None:
            # window results used INSIDE expressions: evaluate the
            # rewritten items as a second pass over the computed frame.
            # NULL-bearing numeric columns come back from to_pandas as
            # object dtype — coerce them back, or from_pandas would
            # classify them as STRING and the post arithmetic would run
            # on dictionary codes
            import pandas as pd
            win_cols = {p["alias"] for k, p in outer if k == "win"}
            for c in df.columns:
                if df[c].dtype != object:
                    continue
                numeric = c in win_cols or (
                    inner_block.schema.has(c)
                    and not inner_block.schema.dtype(c).is_string)
                if numeric:
                    df[c] = pd.to_numeric(df[c])
            temps: list = []
            try:
                tname = self._register_temp(HostBlock.from_pandas(df),
                                            temps, snap)
                final = ast.Select(items=post,
                                   relation=ast.TableRef(tname))
                df = self._run_select(final, snap).to_pandas()
            finally:
                for tn in temps:
                    if self.catalog.has(tn):
                        self.catalog.drop_table(tn)
        if sel.distinct:
            df = df.drop_duplicates(ignore_index=True)
        try:
            df = W.apply_order_limit(df, sel.order_by, sel.limit,
                                     sel.offset)
        except ValueError as e:
            raise QueryError(str(e)) from e
        return HostBlock.from_pandas(df)

    def _final_sort_spec(self, sel, outer):
        """[(output name, ascending)] when every ORDER BY key is a plain
        output-column reference with default NULL placement; None
        otherwise (the host tail handles the exotic cases)."""
        names = set()
        for kind, payload in outer:
            names.add(payload if kind == "col" else payload["alias"])
        fs = []
        for o in sel.order_by:
            if not isinstance(o.expr, ast.Name) \
                    or o.expr.parts[-1] not in names \
                    or o.nulls_first is not None:
                return None
            fs.append((o.expr.parts[-1], o.ascending))
        return fs

    def _windows_on_device(self, inner_block: HostBlock, outer,
                           final_sort=None, limit=None, offset=0):
        """Device window lane (`ops/window_dev.py`): every spec computed
        in one scatter-free jitted program — sort, segment boundaries,
        prefix-scan formulas — with a single device→host transfer for
        all outputs (sliced to offset+limit rows when the final sort
        pushes down). Returns the assembled frame, or None when a spec
        requires the pandas lane (which then counts its host rows)."""
        import pandas as pd

        from ydb_tpu.ops.window_dev import compute_windows_device
        from ydb_tpu.utils.metrics import GLOBAL
        try:
            dev = compute_windows_device(inner_block, outer,
                                         final_sort=final_sort,
                                         limit=limit, offset=offset)
        except Exception:                # noqa: BLE001 — lane, not law
            GLOBAL.inc("engine/window_device_errors")
            return None
        if dev is None:
            return None
        GLOBAL.inc("engine/window_device_rows", inner_block.length)
        if final_sort is not None:
            GLOBAL.inc("engine/window_device_pushdown")

        def series(vals, valid, dic):
            if dic is not None:
                s = pd.Series(dic.decode(vals), dtype=object)
            else:
                s = pd.Series(vals)
            if valid is not None and not valid.all():
                s = s.where(pd.Series(valid))
            return s

        if final_sort is not None:
            sliced, _n = dev
            cols = {}
            for kind, payload in outer:
                name = payload if kind == "col" else payload["alias"]
                cols[name] = series(*sliced[name])
            return pd.DataFrame(cols)
        base = inner_block.to_pandas()
        cols = {}
        for kind, payload in outer:
            if kind == "col":
                cols[payload] = base[payload]
            else:
                cols[payload["alias"]] = series(*dev[payload["alias"]])
        return pd.DataFrame(cols)

    def explain(self, sql: str) -> str:
        stmt = parse(sql)
        if not isinstance(stmt, ast.Select):
            raise QueryError("EXPLAIN supports SELECT only")
        return explain(self.planner.plan_select(stmt))

    def query(self, sql: str):
        """Execute and return a pandas DataFrame (tests / CLI)."""
        return self.execute(sql).to_pandas()

    def _table_fingerprint(self, sel: ast.Select, names=None):
        """(name, uid, data_version) of every table the statement touches —
        the plan-cache validity key (reference keys its compile cache on
        query text + schema version, `kqp_compile_service.cpp:411`).
        `names`: pass an already-computed `_referenced_tables` set so the
        hot SELECT path walks the AST once, not twice."""
        out = []
        for n in sorted(names if names is not None
                        else self._referenced_tables(sel)):
            if self.catalog.has(n):
                t = self.catalog.table(n)
                out.append((n, t.uid, t.data_version))
        return tuple(out)

    def _referenced_tables(self, sel: ast.Select) -> set:
        """Every table name the statement touches (plan-cache keys and
        transaction read-lock acquisition)."""
        names: set = set()

        def walk_sel(s):
            if isinstance(s, ast.SetOp):
                walk_sel(s.left)
                walk_sel(s.right)
                return
            for (_n, body) in s.ctes:
                walk_sel(body)
            if s.relation is not None:
                walk_rel(s.relation)
            for e in ([i.expr for i in s.items] + [s.where, s.having]
                      + list(s.group_by) + [o.expr for o in s.order_by]):
                walk_expr(e)

        def walk_rel(r):
            if isinstance(r, ast.TableRef):
                names.add(r.name)
            elif isinstance(r, ast.Join):
                walk_rel(r.left)
                walk_rel(r.right)
                walk_expr(r.on)
            elif isinstance(r, ast.SubqueryRef):
                walk_sel(r.query)

        def walk_expr(e):
            if e is None or not hasattr(e, "__dataclass_fields__"):
                return
            if isinstance(e, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
                walk_sel(e.query)
                if isinstance(e, ast.InSubquery):
                    walk_expr(e.arg)
                return
            def walk_val(v):
                if isinstance(v, tuple):
                    for x in v:
                        walk_val(x)
                else:
                    walk_expr(v)

            for f in e.__dataclass_fields__:
                walk_val(getattr(e, f))

        walk_sel(sel)
        return names

    # -- CTE / derived-table materialization -------------------------------
    #
    # WITH bodies and FROM subqueries materialize into transient column
    # tables before the outer statement plans — the stage-materialization
    # strategy of DQ precompute stages (`dq_opt_phy_finalizing.cpp`
    # DqBuildStages: a stage result becomes the next stage's source).

    def _needs_materialize(self, sel) -> bool:
        if isinstance(sel, ast.SetOp):
            return True
        if sel.ctes:
            return True
        from ydb_tpu.scheme import sysview as SV
        refs = self._referenced_tables(sel)
        if any(SV.is_sysview(n) for n in refs):
            return True               # `.sys/...` materializes at plan time
        if any(self.views.has(n) for n in refs):
            return True               # view reads serve from folded state

        def rel_has(r):
            if isinstance(r, ast.SubqueryRef):
                return True
            if isinstance(r, ast.Join):
                return rel_has(r.left) or rel_has(r.right)
            return False

        def expr_has(e):
            if e is None or not hasattr(e, "__dataclass_fields__"):
                return False
            if isinstance(e, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
                sub = self._needs_materialize(e.query)
                if isinstance(e, ast.InSubquery):
                    return sub or expr_has(e.arg)
                return sub

            def any_in(v):
                if isinstance(v, tuple):
                    return any(any_in(x) for x in v)
                return expr_has(v)

            return any(any_in(getattr(e, f))
                       for f in e.__dataclass_fields__)

        if sel.relation is not None and rel_has(sel.relation):
            return True
        for e in ([i.expr for i in sel.items] + [sel.where, sel.having]
                  + list(sel.group_by) + [o.expr for o in sel.order_by]):
            if expr_has(e):
                return True
        return False

    def _execute_materialized(self, sel: ast.Select,
                              snap: Optional[Snapshot] = None) -> HostBlock:
        snap = snap or self.snapshot()
        temps: list = []
        try:
            sel2 = self._rewrite_sel(sel, {}, temps, snap)
            plan = self.planner.plan_select(sel2)
            return self.executor.execute(plan, snap)
        finally:
            for t in temps:
                if self.catalog.has(t):
                    self.catalog.drop_table(t)

    def _rewrite_sel(self, sel, cte_map: dict,
                     temps: list, snap: Optional[Snapshot] = None):
        if isinstance(sel, ast.SetOp):
            cte_map = dict(cte_map)
            for (name, body) in sel.ctes:
                cte_map[name] = self._materialize(
                    self._rewrite_sel(body, cte_map, temps, snap), temps,
                    snap)
            out = ast.SetOp(
                sel.op,
                self._rewrite_sel(sel.left, cte_map, temps, snap),
                self._rewrite_sel(sel.right, cte_map, temps, snap),
                sel.order_by, sel.limit, sel.offset)
            return out
        cte_map = dict(cte_map)
        for (name, body) in sel.ctes:
            cte_map[name] = self._materialize(
                self._rewrite_sel(body, cte_map, temps, snap), temps,
                snap)

        def rewrite_rel(r):
            if isinstance(r, ast.TableRef):
                t = cte_map.get(r.name)
                if t is not None:
                    return ast.TableRef(t, r.alias or r.name)
                view = self.views.get(r.name)
                if view is not None:
                    vsnap = snap or self.snapshot()
                    blk, mode = view.serve(vsnap)
                    notes = getattr(self._view_tls, "notes", None)
                    if notes is not None:
                        notes.append({"view": r.name, "mode": mode,
                                      "watermark": view.watermark})
                    if blk is not None:
                        tname = self._register_temp(blk, temps, vsnap)
                        return ast.TableRef(tname, r.alias or r.name)
                    # base-query fallback: materialize the defining
                    # SELECT at this read's snapshot
                    from ydb_tpu.sql.parser import parse
                    sub = self._rewrite_sel(parse(view.vp.sql), {},
                                            temps, vsnap)
                    tname = self._materialize(sub, temps, vsnap)
                    return ast.TableRef(tname, r.alias or r.name)
                from ydb_tpu.scheme import sysview as SV
                if SV.is_sysview(r.name):
                    try:
                        blk = SV.sysview_block(self, r.name)
                    except KeyError as e:
                        raise QueryError(str(e.args[0])) from e
                    tname = self._register_temp(blk, temps, snap)
                    return ast.TableRef(tname, r.alias or "sys")
                return r
            if isinstance(r, ast.Join):
                return ast.Join(r.kind, rewrite_rel(r.left),
                                rewrite_rel(r.right),
                                rewrite_expr(r.on))
            if isinstance(r, ast.SubqueryRef):
                t = self._materialize(
                    self._rewrite_sel(r.query, cte_map, temps, snap), temps,
                    snap)
                return ast.TableRef(t, r.alias)   # Select OR SetOp body
            return r

        def rewrite_expr(e):
            import dataclasses
            if e is None or not hasattr(e, "__dataclass_fields__"):
                return e
            if isinstance(e, (ast.Exists, ast.InSubquery,
                              ast.ScalarSubquery)):
                q = self._rewrite_sel(e.query, cte_map, temps, snap)
                if isinstance(q, ast.SetOp):
                    # plan over a materialized temp: the planner only
                    # decorrelates plain selects (explicit column items —
                    # Star would lose the planner's naming contract)
                    tname = self._materialize(q, temps, snap)
                    cols = self.catalog.table(tname).schema.names
                    q = ast.Select(
                        items=[ast.SelectItem(ast.Name((c,)), c)
                               for c in cols],
                        relation=ast.TableRef(tname))
                kw = {"query": q}
                if isinstance(e, ast.InSubquery):
                    kw["arg"] = rewrite_expr(e.arg)
                return dataclasses.replace(e, **kw)

            def rw(v):
                if isinstance(v, tuple):
                    return tuple(rw(x) for x in v)
                return rewrite_expr(v)

            kw = {f: rw(getattr(e, f)) for f in e.__dataclass_fields__}
            return dataclasses.replace(e, **kw)

        out = ast.Select(**{**sel.__dict__})
        out.ctes = []
        if out.relation is not None:
            out.relation = rewrite_rel(out.relation)
        out.where = rewrite_expr(out.where)
        out.having = rewrite_expr(out.having)
        out.items = [ast.SelectItem(rewrite_expr(i.expr), i.alias)
                     for i in out.items]
        out.group_by = [rewrite_expr(g) for g in out.group_by]
        out.order_by = [ast.OrderItem(rewrite_expr(o.expr), o.ascending,
                                      o.nulls_first) for o in out.order_by]
        return out

    def _materialize(self, sel, temps: list,
                     snap: Optional[Snapshot] = None) -> str:
        """Materialize an already-rewritten Select or SetOp into a
        transient table; returns its name."""
        from ydb_tpu.query import window as W
        snap = snap or self.snapshot()
        if isinstance(sel, ast.SetOp):
            df = self._eval_setop_df(sel, snap)
            try:
                df = W.apply_order_limit(df, sel.order_by, sel.limit,
                                         sel.offset)
            except ValueError as e:
                raise QueryError(str(e)) from e
            block = HostBlock.from_pandas(df)
        elif W.has_window(sel):
            block = self._execute_windowed(sel, snap)
        else:
            block = self.executor.execute(self.planner.plan_select(sel),
                                          snap)
        return self._register_temp(block, temps, snap)

    def _register_temp(self, block: HostBlock, temps: list,
                       snap: Optional[Snapshot] = None) -> str:
        snap = snap or self.snapshot()
        tname = f"__tmp{next(self._tmp_ids)}"
        # temps inherit the engine's block size: the default (1<<20) would
        # jit-compile every downstream program at 1M-row capacity even for
        # tiny CTE results
        t = self.catalog.create_table(tname, block.schema,
                                      [block.schema.names[0]], shards=1,
                                      portion_rows=self.executor.block_rows,
                                      transient=True)
        t.dictionaries = {n: cd.dictionary
                          for n, cd in block.columns.items()
                          if cd.dictionary is not None}
        if block.length:
            # committed INSIDE the driving snapshot (tx snapshots are
            # pinned — a fresh coordinator step would be invisible); the
            # temp is private and dropped right after, so the early
            # version leaks nowhere
            t.commit(t.write(block), WriteVersion(snap.plan_step, 0))
            t.indexate()
        temps.append(tname)
        return tname

    # -- DDL / DML ---------------------------------------------------------

    def _create_table(self, stmt: ast.CreateTable) -> HostBlock:
        if self.catalog.has(stmt.name):
            if stmt.if_not_exists:
                return _unit_block()
            raise QueryError(f"table {stmt.name!r} already exists")
        if self.views.has(stmt.name):
            raise QueryError(
                f"{stmt.name!r} already names a materialized view")
        cols = [Column(name, sql_type_to_dtype(ty, not_null))
                for (name, ty, not_null) in stmt.columns]
        pk = stmt.primary_key or [cols[0].name]
        schema = Schema(cols)
        if stmt.ttl_days and not stmt.ttl_column:
            raise QueryError("ttl_days needs ttl_column")
        if stmt.ttl_column:            # validate BEFORE creating anything
            from ydb_tpu.core.dtypes import Kind as _K
            if not schema.has(stmt.ttl_column):
                raise QueryError(f"unknown TTL column {stmt.ttl_column!r}")
            if schema.dtype(stmt.ttl_column).kind not in (_K.DATE32,
                                                          _K.INT64):
                raise QueryError("TTL column must be Date or Int64 "
                                 "(unix seconds)")
            if stmt.ttl_days <= 0:
                raise QueryError("ttl_days must be positive")
        t = self.catalog.create_table(stmt.name, schema, pk,
                                      shards=max(1, stmt.partition_count),
                                      store_kind=stmt.store)
        serial_cols = [n for (n, ty, _nn) in stmt.columns
                       if ty.lower() in ("serial", "bigserial")]
        if serial_cols:
            t.serial_next = {c: 1 for c in serial_cols}
        if stmt.ttl_column:
            t.ttl = (stmt.ttl_column, stmt.ttl_days)
        if (serial_cols or stmt.ttl_column) \
                and self.catalog.store is not None:
            self.catalog.store.save_catalog(self.catalog)
        return _unit_block()

    def run_ttl(self, now: Optional[float] = None) -> dict:
        """Evict expired rows from every TTL-configured table (the
        background `ttl.cpp` change in the reference — here an explicit
        maintenance entry point, like `indexate`). `now`: unix seconds
        (defaults to wall clock; tests pass a fixed value). Returns
        {table: rows evicted}."""
        import datetime as _dt
        import time as _time
        from ydb_tpu.core.dtypes import Kind as _K
        now = _time.time() if now is None else now
        out = {}
        for name in list(self.catalog.tables):
            t = self.catalog.table(name)
            ttl = getattr(t, "ttl", None)
            if not ttl or getattr(t, "transient", False):
                continue
            col, days = ttl
            if t.schema.dtype(col).kind is _K.DATE32:
                cutoff_days = int(now // 86400) - days
                d = _dt.date(1970, 1, 1) + _dt.timedelta(days=cutoff_days)
                pred = f"{col} < date '{d.isoformat()}'"
            else:
                pred = f"{col} < {int(now) - days * 86400}"
            self.execute(f"delete from {name} where {pred}",
                         _internal=True)
            out[name] = self.last_rows_affected
            from ydb_tpu.utils.metrics import GLOBAL
            GLOBAL.inc("engine/ttl_evicted", self.last_rows_affected)
        return out

    def _table(self, name: str):
        """Catalog lookup with a user-facing error (not a raw KeyError)."""
        try:
            return self.catalog.table(name)
        except KeyError as e:
            raise QueryError(str(e.args[0])) from e

    def _alter_table(self, stmt: ast.AlterTable) -> HostBlock:
        """ADD/DROP COLUMN (the schemeshard alter-table suboperation
        analog): schema evolves in place, old portions serve nulls for
        added columns, the plan cache invalidates via data_version."""
        if not self.catalog.has(stmt.name):
            raise QueryError(f"unknown table {stmt.name!r}")
        t = self._table(stmt.name)
        if stmt.action == "add":
            if t.schema.has(stmt.column):
                raise QueryError(
                    f"column {stmt.column!r} already exists")
            if stmt.not_null and (
                    t.num_rows > 0
                    or getattr(t, "store_kind", "column") == "row"):
                # existing rows have no value for it; row tables replay
                # their full mutation log at boot, so even an empty one
                # cannot prove future replays satisfy NOT NULL
                raise QueryError(
                    "ADD COLUMN NOT NULL needs an empty column table "
                    "(no default-value backfill yet)")
            if stmt.col_type.lower() in ("serial", "bigserial"):
                raise QueryError("ADD COLUMN Serial is not supported "
                                 "(sequences initialize at CREATE TABLE)")
            col = Column(stmt.column,
                         sql_type_to_dtype(stmt.col_type, stmt.not_null))
            t.add_column(col)
        else:
            if not t.schema.has(stmt.column):
                raise QueryError(f"unknown column {stmt.column!r}")
            if stmt.column in t.key_columns \
                    or stmt.column in (t.partition_by or []):
                raise QueryError(
                    f"cannot drop key/partition column {stmt.column!r}")
            ttl = getattr(t, "ttl", None)
            if ttl is not None and ttl[0] == stmt.column:
                raise QueryError(
                    f"column {stmt.column!r} is the TTL column")
            serial = getattr(t, "serial_next", None)
            if serial is not None:
                serial.pop(stmt.column, None)
            try:
                t.drop_column(stmt.column)
            except ValueError as e:     # e.g. column still indexed
                raise QueryError(str(e)) from e
        if self.catalog.store is not None:
            self.catalog.store.save_catalog(self.catalog)
            self.catalog.store.save_dictionaries(t)
        return _unit_block()

    def _insert(self, stmt: ast.Insert, snap=None, tx=None) -> HostBlock:
        table = self._table(stmt.table)
        if tx is not None:
            # a blind VALUES insert/upsert only WRITES the target:
            # pk-granular write locks (row stores) or commuting appends
            # (column stores) — duplicate-pk races are caught by the
            # point-conflict check at commit. INSERT ... SELECT may READ
            # the target (self-reference) and its source reads aren't
            # separately locked, so it keeps the table-granular lock.
            tx.lock(table, read=stmt.query is not None)
        if stmt.query is not None:
            return self._insert_select(stmt, table, snap, tx)
        names = stmt.columns or table.schema.names
        data: dict[str, list] = {n: [] for n in names}
        from ydb_tpu.query.binder import _try_fold
        for row in stmt.rows:
            if len(row) != len(names):
                raise QueryError("VALUES arity mismatch")
            for n, lit in zip(names, row):
                if isinstance(lit, ast.Literal) and lit.value is None:
                    data[n].append(None)
                    continue
                folded = _try_fold(lit)   # literals, -x, DATE '...', CAST
                if folded is None:
                    raise QueryError("VALUES must be constant expressions")
                data[n].append(folded.value)

        # SERIAL columns omitted from the column list draw from the
        # table's sequence (the sequenceshard analog); counters persist
        # via the catalog and heal from data maxima at recovery
        serial = getattr(table, "serial_next", None)
        if serial:
            n_rows = len(stmt.rows)
            changed = False
            for c, nxt in list(serial.items()):
                if c not in data:
                    data[c] = list(range(nxt, nxt + n_rows))
                    names = list(names) + [c]
                    serial[c] = nxt + n_rows
                    changed = True
                else:
                    # explicit values advance the counter past their max
                    # (same-session duplicates, not just post-restart heal)
                    mx = max((int(v) for v in data[c] if v is not None),
                             default=0)
                    if mx >= serial[c]:
                        serial[c] = mx + 1
                        changed = True
            if changed and self.catalog.store is not None:
                self.catalog.store.save_catalog(self.catalog)

        if getattr(table, "store_kind", "column") == "row":
            ops = []
            for i in range(len(stmt.rows)):
                ops.append((stmt.mode, {n: data[n][i] for n in names}))
            try:
                self._apply_row_ops(table, ops, tx)
                self.last_rows_affected = len(ops)
            except ValueError as e:
                raise QueryError(str(e)) from e
            return _unit_block()

        arrays, valids = {}, {}
        n_rows = len(stmt.rows)
        for c in table.schema:
            if c.name in data:
                vals = data[c.name]
                mask = np.array([v is not None for v in vals])
                if c.dtype.is_string:
                    codes = table.dictionaries[c.name].encode(
                        [None if v is None else str(v) for v in vals])
                    arrays[c.name] = codes
                else:
                    arrays[c.name] = np.array(
                        [0 if v is None else v for v in vals], dtype=c.dtype.np)
                if not mask.all():
                    if not c.dtype.nullable:
                        raise QueryError(f"NULL in NOT NULL column {c.name}")
                    valids[c.name] = mask
            else:
                if not c.dtype.nullable:
                    raise QueryError(f"missing NOT NULL column {c.name}")
                arrays[c.name] = np.zeros(n_rows, dtype=c.dtype.np)
                valids[c.name] = np.zeros(n_rows, dtype=bool)
        block = HostBlock.from_arrays(table.schema, arrays, valids,
                                      dict(table.dictionaries))
        if tx is not None:
            writes = table.write(block, tx=tx.tx_id)
            tx.col_writes.append((table, writes))
            tx.note_self_bump(table)   # staged write bumps data_version
            self.last_rows_affected = block.length
            return _unit_block()
        writes = table.write(block)
        with self._commit_step() as version:
            table.commit(writes, version)
        self.last_rows_affected = block.length
        table.indexate(self._maintenance_watermark(),
                       compact=self.config.flag("enable_auto_compaction"))
        self._maybe_split(table)
        return _unit_block()

    def _maybe_split(self, table) -> None:
        """Auto-split trigger at commit points (the table-stats split of
        `schemeshard__table_stats.cpp`, collapsed to a row threshold)."""
        if not getattr(table, "maybe_split", None):
            return
        if table.maybe_split(self.config.shard_split_rows):
            from ydb_tpu.utils.metrics import GLOBAL
            GLOBAL.inc("engine/shard_splits")
            if self.catalog.store is not None:
                self.catalog.store.save_catalog(self.catalog)

    def _apply_row_ops(self, table, ops, tx) -> None:
        """Row-table mutation: immediate at a fresh version (autocommit)
        or staged under the open transaction."""
        if not ops:
            return
        if tx is not None:
            table.apply(ops, None, durable=False, tx=tx.tx_id)
            tx.row_writes.append((table, ops))
            # pk-granular write lock: a tx that only WRITES this table
            # validates point conflicts on these keys, not the whole
            # table's data_version
            tx.note_self_bump(table, write_pks=table.pks_of_ops(ops))
        else:
            with self._commit_step() as version:
                table.apply(ops, version)
            # threshold-fold for this table's views: keeps read-time
            # drains to one small tail (non-blocking, no-op without views)
            self.views.on_commit(table.name)


    # -- UPDATE / DELETE ---------------------------------------------------
    #
    # Row tables (DataShard analog): evaluate the WHERE through the normal
    # query path, then apply point mutations on the version chains — MVCC
    # snapshots keep seeing the old rows.
    #
    # Column tables: evaluated the same way, then applied as MVCC delete
    # marks on immutable portions (storage/portion.py DeleteMark) — time
    # travel preserved, transactional staging supported; UPDATE commits
    # its marks and re-inserts through one intent-journal record.

    def _update(self, stmt: ast.Update, snap=None, tx=None) -> HostBlock:
        table = self._table(stmt.table)
        if tx is not None:
            tx.lock(table)
        set_cols = [c for (c, _e) in stmt.assignments]
        for c in set_cols:
            if c in table.key_columns:
                raise QueryError("UPDATE of primary key columns is not "
                                 "supported (DELETE + INSERT)")
        # constant assignments (incl. string literals, which the binder
        # cannot type outside comparisons) apply directly; computed
        # expressions evaluate through the query path
        from ydb_tpu.query.binder import _try_fold
        const_vals: dict = {}
        computed: list = []
        for (c, e) in stmt.assignments:
            if isinstance(e, ast.Literal) and e.value is None:
                const_vals[c] = None
                continue
            folded = _try_fold(e)
            if folded is not None:
                const_vals[c] = folded.value
            else:
                computed.append((c, e))

        if getattr(table, "store_kind", "column") == "row":
            items = [ast.SelectItem(ast.Name((k,)), k)
                     for k in table.key_columns]
            items += [ast.SelectItem(e, f"__set_{c}")
                      for (c, e) in computed]
            df = self._run_select(ast.Select(
                items=items, relation=ast.TableRef(stmt.table),
                where=stmt.where), snap).to_pandas()
            ops = []
            for row in df.to_dict("records"):
                vals = {k: _native(row[k]) for k in table.key_columns}
                vals.update(const_vals)
                vals.update({c: _native(row[f"__set_{c}"])
                             for (c, _e) in computed})
                ops.append(("upsert", vals))
            self._apply_row_ops(table, ops, tx)
            self.last_rows_affected = len(ops)
            return _unit_block()
        # column table: select full updated rows at the snapshot, mark the
        # originals deleted (MVCC delete marks — historical snapshots keep
        # the old rows), re-insert the new versions at the same commit
        items = [ast.SelectItem(ast.Name((c,)), c)
                 for c in table.schema.names]
        items += [ast.SelectItem(e, f"__set_{c}") for (c, e) in computed]
        df = self._run_select(ast.Select(
            items=items, relation=ast.TableRef(stmt.table),
            where=stmt.where), snap).to_pandas()
        for (c, _e) in computed:
            df[c] = df.pop(f"__set_{c}")
        for c, v in const_vals.items():
            df[c] = v
        hits = self._column_delete_hits(table, stmt.where, snap)
        n_hits = sum(len(rows) for (_s, _p, rows) in hits)
        if tx is not None:
            if n_hits != len(df):
                # portion hits only cover indexed rows: a mismatch means
                # the predicate matched rows STAGED by this same open tx
                # (indexation cannot convert them) — marking would miss
                # them and the re-insert would duplicate
                raise QueryError(
                    "UPDATE of rows inserted in the same transaction is "
                    "not supported yet (commit the insert first)")
            if not len(df):
                self.last_rows_affected = 0
                return _unit_block()
            handles = table.stage_deletes(hits, tx.tx_id)
            if handles:
                tx.note_self_bump(table)      # stage_deletes bump
                tx.col_deletes.append((table, handles))
            block = HostBlock.from_pandas(
                df[list(table.schema.names)], schema=table.schema,
                dictionaries=table.dictionaries)
            writes = table.write(block, tx=tx.tx_id)
            tx.col_writes.append((table, writes))
            tx.note_self_bump(table)  # staged write bump
        else:
            if not len(df):
                self.last_rows_affected = 0
                return _unit_block()
            block = HostBlock.from_pandas(
                df[list(table.schema.names)], schema=table.schema,
                dictionaries=table.dictionaries)
            writes = table.write(block)
            # marks + new rows in ONE commit (one intent record): a crash
            # must never leave a pure delete or a duplicating insert
            with self._commit_step() as version:
                table.commit(writes, version, deletes=hits)
            table.indexate(self._maintenance_watermark(),
                           compact=self.config.flag(
                               "enable_auto_compaction"))
        self.last_rows_affected = len(df)
        return _unit_block()

    def _delete(self, stmt: ast.Delete, snap=None, tx=None) -> HostBlock:
        table = self._table(stmt.table)
        if tx is not None:
            tx.lock(table)
        if getattr(table, "store_kind", "column") == "row":
            items = [ast.SelectItem(ast.Name((k,)), k)
                     for k in table.key_columns]
            df = self._run_select(ast.Select(
                items=items, relation=ast.TableRef(stmt.table),
                where=stmt.where), snap).to_pandas()
            ops = [("delete", {k: _native(row[k])
                               for k in table.key_columns})
                   for row in df.to_dict("records")]
            self._apply_row_ops(table, ops, tx)
            self.last_rows_affected = len(ops)
            return _unit_block()
        hits = self._column_delete_hits(table, stmt.where, snap)
        n = sum(len(rows) for (_s, _p, rows) in hits)
        if tx is not None:
            cnt = int(self._run_select(ast.Select(
                items=[ast.SelectItem(
                    ast.FuncCall("count", (), star=True), "c")],
                relation=ast.TableRef(stmt.table),
                where=stmt.where), snap).to_pandas().iloc[0, 0])
            if n != cnt:
                raise QueryError(
                    "DELETE of rows inserted in the same transaction is "
                    "not supported yet (commit the insert first)")
            handles = table.stage_deletes(hits, tx.tx_id)
            if handles:
                tx.note_self_bump(table)
                tx.col_deletes.append((table, handles))
        elif hits:
            with self._commit_step() as version:
                table.apply_deletes(hits, version)
        self.last_rows_affected = n
        return _unit_block()

    def _column_delete_hits(self, table, where, snap=None) -> list:
        """Matching rows per portion at the snapshot: [(shard, portion,
        row indices)] — the input of the MVCC delete-mark path (the r3
        portion-rewrite delete destroyed time travel; marks preserve it)."""
        keys = table.key_columns
        pks = self._run_select(ast.Select(
            items=[ast.SelectItem(ast.Name((k,)), k) for k in keys],
            relation=ast.TableRef(table.name),
            where=where), snap).to_pandas().drop_duplicates()
        if pks.empty:
            return []
        # inserts → portions first: marks attach to portions (staged
        # inserts are transient; indexation makes them markable)
        table.indexate(self._maintenance_watermark(),
                       compact=self.config.flag("enable_auto_compaction"))
        snap = snap or self.snapshot()
        hits = []
        for shard in table.shards:
            for p in shard.portions:
                if not snap.includes(p.version):
                    continue
                kdf = p.block.select(keys).to_pandas()
                kdf["__pos"] = np.arange(len(kdf))
                hit = kdf.merge(pks, on=keys, how="inner")["__pos"] \
                         .to_numpy()
                dead = p.visible_dead(snap)
                if dead is not None:
                    hit = np.setdiff1d(hit, dead)
                if len(hit):
                    hits.append((shard, p, hit))
        return hits

    def _insert_select(self, stmt: ast.Insert, table, snap=None,
                       tx=None) -> HostBlock:
        block = self._run_select(stmt.query, snap)
        df = block.to_pandas()
        self.last_rows_affected = len(df)
        names = stmt.columns or table.schema.names
        if len(df.columns) != len(names):
            raise QueryError("INSERT ... SELECT arity mismatch")
        df.columns = names
        # SERIAL columns draw from the sequence here too (the VALUES path
        # does the same); explicit values advance the counter
        serial = getattr(table, "serial_next", None)
        if serial:
            changed = False
            for c, nxt in list(serial.items()):
                if c not in df.columns:
                    df[c] = range(nxt, nxt + len(df))
                    names = list(names) + [c]
                    serial[c] = nxt + len(df)
                    changed = True
                else:
                    vals = [int(v) for v in df[c] if v is not None]
                    mx = max(vals, default=0)
                    if mx >= serial[c]:
                        serial[c] = mx + 1
                        changed = True
            if changed and self.catalog.store is not None:
                self.catalog.store.save_catalog(self.catalog)
        if getattr(table, "store_kind", "column") == "row":
            # ops carry only the named columns — "upsert" must keep the
            # unmentioned ones, so no null-filling here (apply() enforces
            # NOT NULL for genuinely absent values)
            ops = [(stmt.mode, {c: _native(v) for c, v in row.items()})
                   for row in df.to_dict("records")]
            try:
                self._apply_row_ops(table, ops, tx)
            except ValueError as e:
                raise QueryError(str(e)) from e
            return _unit_block()
        # null-fill unspecified columns (the VALUES path's semantics)
        for c in table.schema:
            if c.name not in df.columns:
                if not c.dtype.nullable:
                    raise QueryError(f"missing NOT NULL column {c.name}")
                df[c.name] = None
        df = df[list(table.schema.names)]
        if tx is not None and len(df):
            from ydb_tpu.core.block import HostBlock as _HB
            blk = _HB.from_pandas(df, schema=table.schema,
                                  dictionaries=table.dictionaries)
            writes = table.write(blk, tx=tx.tx_id)
            tx.col_writes.append((table, writes))
            tx.note_self_bump(table)   # staged write bumps data_version
            return _unit_block()
        if len(df):
            with self._commit_step() as version:
                table.bulk_upsert(df, version)
        return _unit_block()


def _native(v):
    """pandas cell → python native (None for NA; unwrap numpy scalars)."""
    import pandas as pd
    if v is None or (isinstance(v, float) and v != v):
        return None
    try:
        if pd.isna(v):
            return None
    except (TypeError, ValueError):
        pass
    return v.item() if hasattr(v, "item") else v


def _unit_block() -> HostBlock:
    return HostBlock(Schema([]), {}, 0)
