"""Window functions and set operations — engine-level evaluation.

The reference expands window functions in the logical optimizer
(`yql/core/common_opt/` window expansion) into partition-sorted traversals,
and UNION ALL into `Extend` callables. Here both evaluate over the result
of the core columnar engine: the inner query (scan/filter/join/aggregate)
runs on the device through the normal fused path; the window pass and the
set combine run host-side over the (usually post-aggregation, small)
result — the "host fallback lane" of SURVEY §7. Device-native segmented
window kernels can replace the host pass without changing the SQL surface.

Supported: ROW_NUMBER / RANK / DENSE_RANK (PARTITION BY + ORDER BY),
SUM/MIN/MAX/COUNT/AVG OVER (PARTITION BY [ORDER BY → running aggregates,
ROWS semantics]). Frames (ROWS BETWEEN ...) are not parsed yet.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from ydb_tpu.sql import ast

WINDOW_FUNCS = {"row_number", "rank", "dense_rank", "sum", "min", "max",
                "count", "avg", "lead", "lag"}


def _contains_window(e) -> bool:
    if isinstance(e, ast.WindowFunc):
        return True
    if not hasattr(e, "__dataclass_fields__"):
        return False

    def any_in(v):
        if isinstance(v, tuple):
            return any(any_in(x) for x in v)
        return hasattr(v, "__dataclass_fields__") and _contains_window(v)
    return any(any_in(getattr(e, f)) for f in e.__dataclass_fields__)


def has_window(sel: ast.Select) -> bool:
    return any(_contains_window(i.expr) for i in sel.items
               if not isinstance(i.expr, ast.Star))


def split_windowed(sel: ast.Select):
    """Split a windowed select into (inner select, outer plan, post).

    inner: every non-window item plus synthesized aliases for each window
    function's args / partition keys / order keys.
    outer: ordered [(kind, payload)] describing how to assemble the final
    frame — ("col", alias) or ("win", spec dict).
    post: None, or final SelectItems to evaluate over the computed frame
    when a window function appears INSIDE an expression (e.g. the q98
    ratio `rev * 100 / sum(rev) over (partition by class)`) — those
    expressions run as a second engine pass over the frame.
    """
    inner_items: list = []
    outer: list = []
    post_items: list = []
    any_nested = False

    def win_spec(e: ast.WindowFunc, alias: str, tag: str) -> dict:
        if e.func not in WINDOW_FUNCS:
            raise ValueError(f"unsupported window function {e.func}")
        if e.distinct:
            raise ValueError(
                "DISTINCT inside a window function is not supported")
        spec = {"func": e.func, "args": [], "part": [], "order": [],
                "asc": [], "alias": alias, "frame": e.frame}
        for j, a in enumerate(e.args):
            al = f"__{tag}a{j}"
            inner_items.append(ast.SelectItem(a, al))
            spec["args"].append(al)
        for j, p in enumerate(e.partition_by):
            al = f"__{tag}p{j}"
            inner_items.append(ast.SelectItem(p, al))
            spec["part"].append(al)
        for j, o in enumerate(e.order_by):
            al = f"__{tag}o{j}"
            inner_items.append(ast.SelectItem(o.expr, al))
            spec["order"].append(al)
            spec["asc"].append(o.ascending)
        return spec

    name_map: dict = {}
    agg_map: dict = {}
    wx_count = [0]

    def rewrite(e):
        """Replace nested WindowFuncs with frame-column refs, plain
        AGGREGATES with inner-select aliases (the inner select carries
        the GROUP BY — `sum(v) * 100 / sum(sum(v)) over ()` needs sum(v)
        computed there, not over the frame), and source Names with
        passthrough aliases (the frame is a temp table; the original
        scope is gone by the time the post pass runs)."""
        import dataclasses
        from ydb_tpu.query.binder import AGG_NAMES
        if isinstance(e, ast.WindowFunc):
            alias = f"__wx{wx_count[0]}"
            wx_count[0] += 1
            outer.append(("win", win_spec(e, alias, alias.strip("_"))))
            return ast.Name((alias,))
        if isinstance(e, ast.FuncCall) and e.name in AGG_NAMES \
                and not _contains_window(e):
            key = repr(e)
            al = agg_map.get(key)
            if al is None:
                al = f"__wg{len(agg_map)}"
                agg_map[key] = al
                inner_items.append(ast.SelectItem(e, al))
                outer.append(("col", al))
            return ast.Name((al,))
        if isinstance(e, ast.Name):
            al = name_map.get(e.parts)
            if al is None:
                al = f"__wc{len(name_map)}"
                name_map[e.parts] = al
                inner_items.append(ast.SelectItem(e, al))
                outer.append(("col", al))
            return ast.Name((al,))
        if not hasattr(e, "__dataclass_fields__"):
            return e

        def rw(v):
            if isinstance(v, tuple):
                return tuple(rw(x) for x in v)
            if hasattr(v, "__dataclass_fields__"):
                return rewrite(v)
            return v
        return dataclasses.replace(
            e, **{f: rw(getattr(e, f)) for f in e.__dataclass_fields__})

    nested = [not isinstance(i.expr, ast.WindowFunc)
              and _contains_window(i.expr) for i in sel.items]
    any_nested = any(nested)

    for idx, item in enumerate(sel.items):
        e = item.expr
        if nested[idx]:
            alias = item.alias or f"column{idx}"
            post_items.append(ast.SelectItem(rewrite(e), alias))
            continue
        if isinstance(e, ast.WindowFunc):
            alias = item.alias or f"column{idx}"
            outer.append(("win", win_spec(e, alias, f"w{idx}")))
            if any_nested:
                post_items.append(ast.SelectItem(ast.Name((alias,)),
                                                 alias))
        else:
            alias = item.alias
            if alias is None and isinstance(e, ast.Name):
                alias = e.parts[-1]
            alias = alias or f"column{idx}"
            inner_items.append(ast.SelectItem(e, alias))
            outer.append(("col", alias))
            if any_nested:
                post_items.append(ast.SelectItem(ast.Name((alias,)),
                                                 alias))
    # SQL applies DISTINCT to the FINAL output, after window evaluation —
    # the engine dedups the computed frame, never the inner query
    inner = ast.Select(items=inner_items, relation=sel.relation,
                       where=sel.where, group_by=list(sel.group_by),
                       having=sel.having, distinct=False)
    inner.ctes = list(sel.ctes)
    return inner, outer, (post_items if any_nested else None)


def _constant_arg(s: pd.DataFrame, args: list, idx: int, fn: str,
                  what: str, default):
    """lead/lag offset/default arguments must be CONSTANT over the frame
    (SQL requires literal offsets); a per-row value would silently apply
    only its first row's value, so refuse instead."""
    if len(args) <= idx:
        return default
    col = s[args[idx]]
    if not len(col):
        return default
    first = col.iloc[0]
    if pd.isna(first):
        if col.isna().all():
            return None if what == "default" else default
        raise ValueError(f"{fn} {what} must be a constant")
    if col.nunique(dropna=False) > 1:
        raise ValueError(f"{fn} {what} must be a constant")
    return int(first) if what == "offset" else first


def _frame_agg_group(g: pd.Series, fn: str, frame: tuple) -> pd.Series:
    """One partition's ROWS-BETWEEN aggregate, vectorized: sums/counts/
    averages via prefix sums over the [i+lo, i+hi] row window; min/max
    via sliding windows (bounded frames) or running accumulation
    (UNBOUNDED PRECEDING .. CURRENT ROW)."""
    _tag, lo, hi = frame
    lo_unb = isinstance(lo, tuple)
    hi_unb = isinstance(hi, tuple)
    v = g.to_numpy(dtype=np.float64, na_value=np.nan)
    L = len(v)
    idx = np.arange(L)
    start = np.zeros(L, np.int64) if lo_unb \
        else np.clip(idx + lo, 0, L)
    end1 = np.full(L, L, np.int64) if hi_unb \
        else np.clip(idx + hi + 1, 0, L)
    if fn in ("sum", "count", "avg"):
        filled = np.nan_to_num(v)
        nn = (~np.isnan(v)).astype(np.int64)
        cs = np.concatenate([[0.0], np.cumsum(filled)])
        cc = np.concatenate([[0], np.cumsum(nn)])
        ssum = cs[end1] - cs[np.minimum(start, end1)]
        scnt = cc[end1] - cc[np.minimum(start, end1)]
        if fn == "count":
            out = scnt.astype(np.float64)
        elif fn == "sum":
            out = np.where(scnt > 0, ssum, np.nan)
        else:
            out = np.where(scnt > 0, ssum / np.maximum(scnt, 1), np.nan)
        return pd.Series(out, index=g.index)
    # min / max
    if lo_unb and not hi_unb and hi == 0:
        acc = (np.fmin.accumulate if fn == "min"
               else np.fmax.accumulate)(v)
        return pd.Series(acc, index=g.index)
    if not lo_unb and not hi_unb and hi >= lo:
        # out[i] = agg(v[i+lo : i+hi+1]): pad with NaN so every window
        # is the same width, then slide
        w = hi - lo + 1
        pad = np.concatenate([np.full(max(-lo, 0), np.nan), v,
                              np.full(max(hi, 0), np.nan)])
        sw = np.lib.stride_tricks.sliding_window_view(pad, w)
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")   # all-NaN windows
            out = (np.nanmin(sw, axis=1) if fn == "min"
                   else np.nanmax(sw, axis=1))
        out = out[idx + max(lo, 0)]
        empty = start >= end1                # frame fully out of range
        out = np.where(empty, np.nan, out)
        return pd.Series(out, index=g.index)
    raise ValueError(
        f"{fn} over this ROWS frame is not supported yet "
        "(supported: bounded frames, or UNBOUNDED PRECEDING .. "
        "CURRENT ROW)")


def compute_windows(df: pd.DataFrame, outer: list) -> pd.DataFrame:
    """Evaluate the window specs over the inner result, returning the
    final frame with columns in the original item order."""
    work = df.copy()
    work["__row"] = np.arange(len(work))
    cols = []
    for kind, payload in outer:
        if kind == "col":
            cols.append(payload)
            continue
        spec = payload
        out_name = spec["alias"]
        cols.append(out_name)
        part = spec["part"] or ["__const"]
        if "__const" in part and "__const" not in work.columns:
            work["__const"] = 0
        by = part + spec["order"]
        asc = [True] * len(part) + list(spec["asc"])
        s = work.sort_values(by, ascending=asc, kind="stable")
        grp = s.groupby(part, sort=False, dropna=False)
        fn = spec["func"]
        if fn == "row_number":
            vals = grp.cumcount() + 1
        elif fn in ("lead", "lag"):
            col = s[spec["args"][0]]
            off = _constant_arg(s, spec["args"], 1, fn, "offset", 1)
            keys = [s[c] for c in part]
            grp2 = col.groupby(keys, sort=False, dropna=False)
            vals = grp2.shift(off if fn == "lag" else -off)
            if len(spec["args"]) > 2:
                # 3-arg form: rows whose frame position falls outside
                # the partition get the DEFAULT, not NULL — and a NULL
                # value inside the partition stays NULL
                default = _constant_arg(s, spec["args"], 2, fn,
                                        "default", None)
                pos = s.groupby(part, sort=False, dropna=False).cumcount()
                size = pos.groupby([s[c] for c in part], sort=False,
                                   dropna=False).transform("size")
                oob = (pos < off) if fn == "lag" else (pos >= size - off)
                vals = vals.mask(oob, default)
        elif fn in ("rank", "dense_rank"):
            rn = grp.cumcount() + 1
            if spec["order"]:
                okeys = s[spec["order"]]
                newkey = okeys.ne(okeys.shift()).any(axis=1)
            else:
                newkey = pd.Series(False, index=s.index)
            first_of_part = rn == 1
            newkey = newkey | first_of_part
            if fn == "rank":
                vals = rn.where(newkey).groupby(
                    [s[c] for c in part], sort=False, dropna=False).ffill()
            else:
                vals = newkey.astype(np.int64).groupby(
                    [s[c] for c in part], sort=False, dropna=False).cumsum()
            vals = vals.astype(np.int64)
        elif spec.get("frame"):
            # explicit ROWS BETWEEN frame
            arg = spec["args"][0] if spec["args"] else None
            col = s[arg] if arg is not None \
                else pd.Series(1.0, index=s.index)
            keys = [s[c] for c in part]
            pieces = [_frame_agg_group(g, fn, spec["frame"])
                      for _k, g in col.groupby(keys, sort=False,
                                               dropna=False)]
            vals = pd.concat(pieces).reindex(s.index) if pieces \
                else pd.Series(np.nan, index=s.index)
        else:
            arg = spec["args"][0] if spec["args"] else None
            running = bool(spec["order"])
            if fn == "count" and arg is None:
                vals = (grp.cumcount() + 1 if running
                        else grp["__row"].transform("size"))
            else:
                col = s[arg]
                if col.dtype == object:
                    # NULL-bearing numerics round-trip to_pandas as
                    # object; grouped cumsum/cummin refuse object dtype.
                    # String-valued args (min/max/count over Utf8) must
                    # stay object — coerce only when everything parses.
                    try:
                        col = pd.to_numeric(col)
                    except (ValueError, TypeError):
                        pass
                keys = [s[c] for c in part]
                g = col.groupby(keys, sort=False, dropna=False)
                if running:       # SQL default frame with ORDER BY
                    # NULL rows don't contribute, but the running value
                    # at a NULL row still reflects the frame so far
                    nn = col.notna().groupby(keys, sort=False,
                                             dropna=False).cumsum()
                    filled = col.fillna(0).groupby(
                        keys, sort=False, dropna=False)
                    if fn == "sum":
                        vals = filled.cumsum().where(nn > 0)
                    elif fn == "count":
                        vals = nn
                    elif fn == "avg":
                        vals = (filled.cumsum() / nn).where(nn > 0)
                    else:          # min / max: patch NULL-row gaps
                        cm = g.cummin() if fn == "min" else g.cummax()
                        vals = cm.groupby(keys, sort=False,
                                          dropna=False).ffill().where(
                                              nn > 0)
                else:
                    vals = g.transform({"sum": "sum", "min": "min",
                                        "max": "max", "count": "count",
                                        "avg": "mean"}[fn])
        work.loc[s.index, out_name] = vals
        if spec["func"] in ("row_number", "rank", "dense_rank") or (
                spec["func"] == "count"):
            work[out_name] = work[out_name].astype(np.int64)
    out = work.sort_values("__row", kind="stable")
    return out[cols].reset_index(drop=True)


def apply_order_limit(df: pd.DataFrame, order_by, limit, offset):
    """Trailing ORDER BY/LIMIT over a host frame (set ops, window tails).
    Order expressions must reference output columns by name. NULL
    placement honors each key's nulls_first (default = YQL's
    NULL-is-smallest: first when ascending)."""
    if order_by:
        keys = []
        for o in order_by:
            if not isinstance(o.expr, ast.Name):
                raise ValueError(
                    "ORDER BY over a set/window result must reference "
                    "output columns by name")
            name = o.expr.parts[-1]
            if name not in df.columns:
                raise ValueError(f"unknown ORDER BY column {name!r}")
            nf = o.nulls_first
            if nf is None:
                nf = o.ascending
            keys.append((name, o.ascending, nf))
        # per-key NULL placement: stable sorts applied minor-key-first
        for name, asc, nf in reversed(keys):
            df = df.sort_values(name, ascending=asc, kind="stable",
                                na_position="first" if nf else "last")
    lo = offset or 0
    if limit is not None:
        df = df.iloc[lo:lo + limit]
    elif lo:
        df = df.iloc[lo:]
    return df.reset_index(drop=True)
