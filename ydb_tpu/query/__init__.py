from ydb_tpu.query.engine import QueryEngine  # noqa: F401
