from ydb_tpu.query.engine import QueryEngine, QueryError  # noqa: F401
