"""Name resolution and expression binding: SQL AST → columnar op IR.

Combines the roles of the reference's type annotation
(`ydb/library/yql/core/type_ann/`) and the KQP OLAP lambda compiler
(`ydb/core/kqp/query_compiler/kqp_olap_compiler.cpp:33` — AST comparisons/
arithmetic → SSA assign/filter commands).

String predicates never reach the device as bytes: any pure function of a
single dictionary-encoded column compared against literals is folded into a
lookup-table Param evaluated over the dictionary host-side, and the device
program gathers through it (`take_lut`) — the TPU-native counterpart of the
reference's string UDF kernels (`ydb/library/yql/udfs/common/`,
hyperscan/re2) applied at `custom_registry.cpp:95`.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from ydb_tpu.core import dtypes as dt
from ydb_tpu.core.dictionary import Dictionary
from ydb_tpu.ops import ir
from ydb_tpu.sql import ast


class BindError(Exception):
    pass


AGG_NAMES = {"sum", "count", "min", "max", "avg", "some"}

_TYPE_MAP = {
    "int64": dt.Kind.INT64, "bigint": dt.Kind.INT64, "int": dt.Kind.INT32,
    "serial": dt.Kind.INT64, "bigserial": dt.Kind.INT64,
    "int32": dt.Kind.INT32, "integer": dt.Kind.INT32, "int16": dt.Kind.INT16,
    "int8": dt.Kind.INT8, "uint64": dt.Kind.UINT64, "uint32": dt.Kind.UINT32,
    "uint16": dt.Kind.UINT16, "uint8": dt.Kind.UINT8,
    "double": dt.Kind.FLOAT64, "float64": dt.Kind.FLOAT64,
    "float": dt.Kind.FLOAT32, "float32": dt.Kind.FLOAT32,
    "real": dt.Kind.FLOAT64, "decimal": dt.Kind.FLOAT64,
    "numeric": dt.Kind.FLOAT64,
    "bool": dt.Kind.BOOL, "boolean": dt.Kind.BOOL,
    "date": dt.Kind.DATE32, "date32": dt.Kind.DATE32,
    "timestamp": dt.Kind.TIMESTAMP, "datetime": dt.Kind.TIMESTAMP,
    "utf8": dt.Kind.STRING, "string": dt.Kind.STRING, "text": dt.Kind.STRING,
    "varchar": dt.Kind.STRING, "char": dt.Kind.STRING,
}


def sql_type_to_dtype(name: str, not_null: bool = False) -> dt.DType:
    kind = _TYPE_MAP.get(name.lower())
    if kind is None:
        raise BindError(f"unsupported type {name!r}")
    return dt.DType(kind, nullable=not not_null)


def parse_date_literal(s: str) -> int:
    m = re.fullmatch(r"(\d{4})-(\d{2})-(\d{2})", s.strip())
    if not m:
        raise BindError(f"bad date literal {s!r}")
    from ydb_tpu.bench.tpch_gen import date32
    return date32(int(m.group(1)), int(m.group(2)), int(m.group(3)))


def _civil_from_days(days: int) -> tuple[int, int, int]:
    z = days + 719468
    era = (z if z >= 0 else z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + 3 if mp < 10 else mp - 9
    return (y + 1 if m <= 2 else y, m, d)


def shift_date(days: int, qty: int, unit: str) -> int:
    from ydb_tpu.bench.tpch_gen import date32
    if unit in ("day", "days"):
        return days + qty
    y, m, d = _civil_from_days(days)
    if unit in ("month", "months"):
        t = (y * 12 + (m - 1)) + qty
        y, m = divmod(t, 12)
        m += 1
    elif unit in ("year", "years"):
        y += qty
    else:
        raise BindError(f"unsupported interval unit {unit!r}")
    leap = (y % 4 == 0 and y % 100 != 0) or y % 400 == 0
    month_len = [31, 29 if leap else 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31]
    return date32(y, m, min(d, month_len[m - 1]))


def like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "".join(out)


@dataclass
class ColumnBinding:
    internal: str                  # internal column name in the pipeline
    dtype: dt.DType
    dictionary: Optional[Dictionary] = None


@dataclass
class Scope:
    """alias.column and unqualified-column resolution."""
    by_alias: dict = field(default_factory=dict)   # alias -> {col -> ColumnBinding}

    def add(self, alias: str, col: str, binding: ColumnBinding):
        self.by_alias.setdefault(alias, {})[col] = binding

    def resolve(self, parts: tuple) -> ColumnBinding:
        if len(parts) == 2:
            cols = self.by_alias.get(parts[0])
            if cols is None or parts[1] not in cols:
                raise BindError(f"unknown column {'.'.join(parts)}")
            return cols[parts[1]]
        name = parts[0]
        hits = [cols[name] for cols in self.by_alias.values() if name in cols]
        if not hits:
            raise BindError(f"unknown column {name}")
        if len(hits) > 1:
            raise BindError(f"ambiguous column {name}")
        return hits[0]

    def try_resolve(self, parts: tuple) -> Optional[ColumnBinding]:
        try:
            return self.resolve(parts)
        except BindError:
            return None

    def by_internal(self, internal: str) -> Optional[ColumnBinding]:
        for cols in self.by_alias.values():
            for b in cols.values():
                if b.internal == internal:
                    return b
        return None


class ParamPool:
    """Array/scalar runtime parameters collected during binding."""

    def __init__(self, prefix: str = "p"):
        self.values: dict = {}
        self._n = 0
        self._prefix = prefix
        # param name -> Dictionary for derived string columns (the LUT maps
        # source codes to codes of this new dictionary)
        self.param_dicts: dict = {}

    def add(self, value, dtype: dt.DType, is_array: bool = False) -> ir.Param:
        name = f"{self._prefix}{self._n}"
        self._n += 1
        self.values[name] = value
        return ir.Param(name, dtype, is_array)


# -- constant folding ------------------------------------------------------


@dataclass(frozen=True)
class _FoldedConst:
    value: object
    dtype: dt.DType
    hint: Optional[str] = None     # "date" | "interval_<unit>"


_INT_KINDS = (dt.Kind.INT8, dt.Kind.INT16, dt.Kind.INT32, dt.Kind.INT64,
              dt.Kind.UINT8, dt.Kind.UINT16, dt.Kind.UINT32, dt.Kind.UINT64,
              dt.Kind.TIMESTAMP)


def _coerce_text_literal(text: str, target: dt.DType):
    """Re-type a text literal into a non-string column's domain (PG text
    protocol: every bound parameter arrives as a string). None = the
    text does not parse as the target type."""
    k = target.kind
    try:
        if k in _INT_KINDS:
            if re.fullmatch(r"[+-]?\d+", text.strip()):
                return ast.Literal(int(text))
        elif k in (dt.Kind.FLOAT64, dt.Kind.FLOAT32):
            return ast.Literal(float(text))
        elif k is dt.Kind.BOOL:
            lv = text.strip().lower()
            if lv in ("t", "true", "1", "on", "y", "yes"):
                return ast.Literal(True)
            if lv in ("f", "false", "0", "off", "n", "no"):
                return ast.Literal(False)
        elif k is dt.Kind.DATE32:
            if re.fullmatch(r"\d{4}-\d{2}-\d{2}", text.strip()):
                return ast.Literal(text.strip(), type_hint="date")
    except ValueError:
        return None
    return None


def _numify_folded(f: "_FoldedConst") -> "_FoldedConst":
    """A folded STRING constant that parses as a number becomes that
    number (arithmetic context only — comparisons coerce by column)."""
    if not isinstance(f.value, str) or f.hint is not None:
        return f
    s = f.value.strip()
    if re.fullmatch(r"[+-]?\d+", s):
        return _FoldedConst(int(s), dt.DType(dt.Kind.INT64, False))
    if re.fullmatch(r"[+-]?\d*\.\d+([eE][+-]?\d+)?", s):
        return _FoldedConst(float(s), dt.DType(dt.Kind.FLOAT64, False))
    return f


def _try_fold(e: ast.Expr):
    """Literal / date / interval constant folding (host-side, bind time)."""
    if isinstance(e, ast.Literal):
        if e.type_hint == "date":
            return _FoldedConst(parse_date_literal(e.value),
                                dt.DType(dt.Kind.DATE32, False), "date")
        if e.type_hint and e.type_hint.startswith("interval_"):
            return _FoldedConst(e.value, dt.DType(dt.Kind.INT64, False),
                                e.type_hint)
        if isinstance(e.value, bool):
            return _FoldedConst(e.value, dt.DType(dt.Kind.BOOL, False))
        if isinstance(e.value, int):
            return _FoldedConst(e.value, dt.DType(dt.Kind.INT64, False))
        if isinstance(e.value, float):
            return _FoldedConst(e.value, dt.DType(dt.Kind.FLOAT64, False))
        if isinstance(e.value, str):
            return _FoldedConst(e.value, dt.DType(dt.Kind.STRING, False))
        return None
    if isinstance(e, ast.UnaryOp) and e.op == "-":
        f = _try_fold(e.arg)
        if f is not None and isinstance(f.value, (int, float)):
            return _FoldedConst(-f.value, f.dtype, f.hint)
        return None
    if isinstance(e, ast.Cast):
        f = _try_fold(e.arg)
        if f is None:
            return None
        if e.to == "date" and isinstance(f.value, str):
            return _FoldedConst(parse_date_literal(f.value),
                                dt.DType(dt.Kind.DATE32, False), "date")
        try:
            target = sql_type_to_dtype(e.to, not_null=True)
        except BindError:
            return None
        if target.is_numeric and isinstance(f.value, (int, float)):
            v = float(f.value) if target.is_float else int(f.value)
            return _FoldedConst(v, target)
        return None
    if isinstance(e, ast.BinOp) and e.op in ("+", "-", "*", "/"):
        lf, rf = _try_fold(e.left), _try_fold(e.right)
        if lf is None or rf is None:
            return None
        # date ± interval (interval + date only for '+')
        pairs = [(lf, rf)] + ([(rf, lf)] if e.op == "+" else [])
        for a, b in pairs:
            if a.hint == "date" and b.hint and b.hint.startswith("interval_"):
                unit = b.hint.split("_", 1)[1]
                qty = b.value if e.op == "+" else -b.value
                return _FoldedConst(shift_date(a.value, qty, unit),
                                    dt.DType(dt.Kind.DATE32, False), "date")
        # text-protocol parameter in arithmetic ('5' + 1): a numeric-
        # looking string operand participates as its number
        lf, rf = _numify_folded(lf), _numify_folded(rf)
        if isinstance(lf.value, (int, float)) and isinstance(rf.value, (int, float)) \
                and lf.hint is None and rf.hint is None:
            x, y = lf.value, rf.value
            v = (x + y if e.op == "+" else x - y if e.op == "-"
                 else x * y if e.op == "*" else x / y)
            kind = dt.Kind.FLOAT64 if isinstance(v, float) else dt.Kind.INT64
            return _FoldedConst(v, dt.DType(kind, False))
        return None
    return None


# -- string folding (dictionary LUTs) --------------------------------------


def _string_fn(e: ast.Expr, scope: Scope, udfs=None):
    """If `e` is a pure function of ONE dictionary-encoded column returning a
    python string, return (binding, fn: str|None -> str|None). `udfs`:
    optional UDF registry — string-returning UDFs compose with the
    builtins in EITHER direction (substring(url_host(x)) and
    url_host(substring(x)) both work)."""
    if isinstance(e, ast.Name):
        b = scope.try_resolve(e.parts)
        if b is not None and b.dtype.is_string and b.dictionary is not None:
            return b, (lambda s: s)
        return None
    if isinstance(e, ast.FuncCall) and e.name == "substring":
        inner = _string_fn(e.args[0], scope, udfs)
        if inner is None:
            return None
        b, f = inner
        start_f = _try_fold(e.args[1])
        if start_f is None:
            return None
        start = int(start_f.value) - 1  # SQL 1-based
        length = None
        if len(e.args) > 2:
            len_f = _try_fold(e.args[2])
            if len_f is None:
                return None
            length = int(len_f.value)

        def g(s, f=f, start=start, length=length):
            s = f(s)
            if s is None:
                return None
            return s[start:start + length] if length is not None else s[start:]
        return b, g
    if isinstance(e, ast.FuncCall) and e.name in _STR_UNARY \
            and len(e.args) == 1:
        inner = _string_fn(e.args[0], scope, udfs)
        if inner is None:
            return None
        b, f = inner
        g0 = _STR_UNARY[e.name]
        return b, (lambda s, f=f, g0=g0:
                   None if f(s) is None else g0(f(s)))
    if isinstance(e, ast.FuncCall) and e.name == "replace" \
            and len(e.args) == 3:
        inner = _string_fn(e.args[0], scope, udfs)
        old_f, new_f = _try_fold(e.args[1]), _try_fold(e.args[2])
        if inner is None or old_f is None or new_f is None:
            return None
        b, f = inner
        return b, (lambda s, f=f, o=str(old_f.value), n=str(new_f.value):
                   None if f(s) is None else f(s).replace(o, n))
    if isinstance(e, ast.FuncCall) and e.name == "regexp_replace" \
            and len(e.args) == 3:
        inner = _string_fn(e.args[0], scope, udfs)
        pat_f, rep_f = _try_fold(e.args[1]), _try_fold(e.args[2])
        if inner is None or pat_f is None or rep_f is None:
            return None
        b, f = inner
        rx = re.compile(str(pat_f.value))
        return b, (lambda s, f=f, rx=rx, r=str(rep_f.value):
                   None if f(s) is None else rx.sub(r, f(s)))
    if isinstance(e, ast.BinOp) and e.op == "||":
        lf = _try_fold(e.right)
        if lf is not None and isinstance(lf.value, str):
            inner = _string_fn(e.left, scope, udfs)
            if inner is not None:
                b, f = inner
                return b, (lambda s, f=f, suf=lf.value:
                           None if f(s) is None else f(s) + suf)
        rf = _try_fold(e.left)
        if rf is not None and isinstance(rf.value, str):
            inner = _string_fn(e.right, scope, udfs)
            if inner is not None:
                b, f = inner
                return b, (lambda s, f=f, pre=rf.value:
                           None if f(s) is None else pre + f(s))
        return None
    # string-returning UDFs compose like any builtin string transform
    if isinstance(e, ast.FuncCall) and udfs is not None and e.name in udfs:
        u = udfs.get(e.name)
        if u.returns != "string" or not e.args \
                or not (u.min_args <= len(e.args) <= u.max_args):
            if u.returns == "string" and e.args:
                raise BindError(f"udf {u.name} takes {u.min_args}"
                                f"..{u.max_args} arguments")
            return None
        inner = _string_fn(e.args[0], scope, udfs)
        if inner is None:
            return None
        b, f = inner
        lits = []
        for a in e.args[1:]:
            lf2 = _try_fold(a)
            if lf2 is None:
                return None
            lits.append(lf2.value)

        def g(s, f=f, fn=u.fn, lits=tuple(lits)):
            return fn(f(s) if s is not None else None, *lits)
        return b, g
    return None


# pure python string transforms usable inside the dictionary-LUT lane
# (the analog of the reference's String/Unicode UDF modules,
# ydb/library/yql/udfs/common/string)
_STR_UNARY: dict[str, Callable] = {
    "lower": str.lower,
    "upper": str.upper,
    "trim": str.strip,
    "ltrim": str.lstrip,
    "rtrim": str.rstrip,
}


def _lut_pred_vec(binding: ColumnBinding, series_pred: Callable,
                  pool: ParamPool) -> ir.Expr:
    """Vectorized bool-LUT over a RAW dictionary column: `series_pred`
    maps a pandas Series of the value set to a bool mask in one C-engine
    pass — the dictionary-degeneracy answer for URL-cardinality columns
    (reference: hyperscan/re2 UDFs, `ydb/library/yql/udfs/common/`)."""
    import pandas as pd
    d = binding.dictionary
    vals = d.values_array()
    if len(vals):
        m = series_pred(pd.Series(vals, dtype=object))
        lut = m.fillna(False).to_numpy(dtype=np.bool_)
    else:
        lut = np.zeros(1, dtype=np.bool_)
    p = pool.add(lut, dt.DType(dt.Kind.BOOL, False), is_array=True)
    return ir.call("take_lut", ir.Col(binding.internal), p)


def _lut_pred(binding: ColumnBinding, fn: Callable, pool: ParamPool) -> ir.Expr:
    """bool-LUT gather over a dictionary column."""
    d = binding.dictionary
    lut = np.zeros(max(len(d), 1), dtype=np.bool_)
    for i, v in enumerate(d.values_array()):
        lut[i] = bool(fn(v))
    p = pool.add(lut, dt.DType(dt.Kind.BOOL, False), is_array=True)
    return ir.call("take_lut", ir.Col(binding.internal), p)


def _lut_typed(binding: ColumnBinding, fn: Callable, pool: ParamPool,
               kind) -> ir.Expr:
    """Typed nullable LUT gather: value + validity LUTs over a
    dictionary column — fn returning None lands as SQL NULL (the int64/
    float64 UDF result path; `_lut_int` keeps its non-null contract for
    length() and friends)."""
    d = binding.dictionary
    n = max(len(d), 1)
    npdt = np.int64 if kind is dt.Kind.INT64 else np.float64
    vals = np.zeros(n, dtype=npdt)
    ok = np.zeros(n, dtype=np.bool_)
    for i, v in enumerate(d.values_array()):
        r = fn(v)
        if r is not None:
            vals[i] = r
            ok[i] = True
    pv = pool.add(vals, dt.DType(kind, False), is_array=True)
    pb = pool.add(ok, dt.DType(dt.Kind.BOOL, False), is_array=True)
    val_e = ir.call("take_lut", ir.Col(binding.internal), pv)
    ok_e = ir.call("take_lut", ir.Col(binding.internal), pb)
    return ir.call("if", ok_e, val_e, ir.call("typed_null", val_e))


def _lut_int(binding: ColumnBinding, fn: Callable, pool: ParamPool) -> ir.Expr:
    """int64-LUT gather over a dictionary column (length() and friends)."""
    d = binding.dictionary
    lut = np.zeros(max(len(d), 1), dtype=np.int64)
    for i, v in enumerate(d.values_array()):
        r = fn(v)
        lut[i] = 0 if r is None else int(r)
    p = pool.add(lut, dt.DType(dt.Kind.INT64, False), is_array=True)
    return ir.call("take_lut", ir.Col(binding.internal), p)


# -- the binder ------------------------------------------------------------


class ExprBinder:
    """Binds row-level AST expressions over a Scope into op-IR."""

    def __init__(self, scope: Scope, pool: ParamPool, udfs=None):
        self.scope = scope
        self.pool = pool
        # UDF registry (`query/udf.py`): unknown functions resolve here
        # last — scalar string functions evaluated per DISTINCT value
        # into LUTs the device gathers through
        self.udfs = udfs

    def bind(self, e: ast.Expr) -> ir.Expr:
        f = _try_fold(e)
        if f is not None:
            if isinstance(f.value, str):
                raise BindError("string literal outside a string comparison")
            return ir.Const(f.value, f.dtype)

        if isinstance(e, ast.Name):
            return ir.Col(self.scope.resolve(e.parts).internal)

        if isinstance(e, ast.BoundParam):
            p = ir.Param(e.name, e.dtype)
            if e.dtype.nullable:
                # scalar-subquery params can be NULL: the executor supplies
                # a `<name>__valid` companion and a typed zero placeholder,
                # so NULL propagates through ANY dtype (not just the old
                # NaN-coercion trick that only worked for float compares)
                valid = ir.Param(e.name + "__valid",
                                 dt.DType(dt.Kind.BOOL, False))
                return ir.call("if", valid, p, ir.call("typed_null", p))
            return p

        # string-VALUED expression (substring/concat of a dict column) used
        # as a value (group key / output): map source codes to a fresh
        # dictionary via an int32 LUT. (Names returned above.)
        sf = _string_fn(e, self.scope, self.udfs)
        if sf is not None:
            return self._derived_string(e, sf)

        if isinstance(e, ast.BinOp):
            return self._bin(e)

        if isinstance(e, ast.UnaryOp):
            if e.op == "not":
                return ir.call("not", self.bind(e.arg))
            if e.op == "-":
                return ir.call("neg", self.bind(e.arg))
            raise BindError(f"unary {e.op}")

        if isinstance(e, ast.Like):
            sf = _string_fn(e.arg, self.scope, self.udfs)
            if sf is None:
                raise BindError("LIKE on a non-string expression")
            b, fn = sf
            if isinstance(e.arg, ast.Name) and b.dictionary is not None:
                # identity transform: evaluate the pattern over the whole
                # dictionary VECTORIZED (pandas str engine) — the Python
                # per-value loop is minutes at URL-scale cardinality
                rx_s = like_to_regex(e.pattern)
                pred = _lut_pred_vec(
                    b, lambda s: s.str.fullmatch(rx_s, flags=re.DOTALL),
                    self.pool)
            else:
                rx = re.compile(like_to_regex(e.pattern), re.DOTALL)
                pred = _lut_pred(
                    b, lambda s: s is not None and fn(s) is not None
                    and rx.fullmatch(fn(s)) is not None, self.pool)
            return ir.call("not", pred) if e.negated else pred

        if isinstance(e, ast.Between):
            lo, hi = self._coerce_vs(e.arg, e.lo), self._coerce_vs(e.arg, e.hi)
            arg = self.bind(e.arg)
            lo, hi = self.bind(lo), self.bind(hi)
            expr = ir.call("and", ir.call("ge", arg, lo), ir.call("le", arg, hi))
            return ir.call("not", expr) if e.negated else expr

        if isinstance(e, ast.InList):
            from dataclasses import replace as _dc_replace
            e = _dc_replace(
                e, items=tuple(self._coerce_vs(e.arg, it) for it in e.items))
            sf = _string_fn(e.arg, self.scope, self.udfs)
            if sf is not None:
                b, fn = sf
                values = set()
                for item in e.items:
                    f2 = _try_fold(item)
                    if f2 is None or not isinstance(f2.value, str):
                        sf = None
                        break
                    values.add(f2.value)
                if sf is not None:
                    pred = _lut_pred(
                        b, lambda s: fn(s) in values if s is not None else False,
                        self.pool)
                    return ir.call("not", pred) if e.negated else pred
            arg = self.bind(e.arg)
            expr = None
            for item in e.items:
                term = ir.call("eq", arg, self.bind(item))
                expr = term if expr is None else ir.call("or", expr, term)
            if expr is None:
                expr = ir.Const(False, dt.DType(dt.Kind.BOOL, False))
            return ir.call("not", expr) if e.negated else expr

        if isinstance(e, ast.IsNull):
            arg = self.bind(e.arg)
            return ir.call("is_not_null" if e.negated else "is_null", arg)

        if isinstance(e, ast.Case):
            return self._case(e)

        if isinstance(e, ast.Cast):
            arg = self.bind(e.arg)
            target = sql_type_to_dtype(e.to)
            return ir.call("cast", arg, to=target.kind.value)

        if isinstance(e, ast.FuncCall):
            return self._func(e)

        raise BindError(f"unsupported expression {type(e).__name__}")

    # -- helpers -----------------------------------------------------------

    def _derived_string(self, e: ast.Expr, sf) -> ir.Expr:
        from ydb_tpu.core.dictionary import Dictionary
        b, fn = sf
        # memoized on the AST: repeated bindings (group key vs SELECT item
        # vs ORDER BY) must yield the IDENTICAL expression, or group-key
        # matching would fail on the fresh param name
        cache = self.pool.__dict__.setdefault("_derived_cache", {})
        ckey = (repr(e), b.internal)
        hit = cache.get(ckey)
        if hit is not None:
            return hit
        new_dict = Dictionary()
        src = b.dictionary.values_array()
        lut = np.full(max(len(src), 1), -1, dtype=np.int32)
        for i, v in enumerate(src):
            r = fn(v)
            if r is not None:
                lut[i] = new_dict.encode([r])[0]
        p = self.pool.add(lut, dt.DType(dt.Kind.STRING, False), is_array=True)
        self.pool.param_dicts[p.name] = new_dict
        # null_neg: a -1 LUT entry means the transform produced NULL for
        # that (non-null) input — validity must reflect it
        out = ir.call("take_lut", ir.Col(b.internal), p, null_neg=True)
        cache[ckey] = out
        return out

    _BIN_KERNEL = {"+": "add", "-": "sub", "*": "mul", "/": "div", "%": "mod",
                   "=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt",
                   ">=": "ge", "and": "and", "or": "or"}

    def _bin(self, e: ast.BinOp) -> ir.Expr:
        # bare string column = literal → code comparison (prunable by stats)
        if e.op in ("=", "<>"):
            for a, bexp in ((e.left, e.right), (e.right, e.left)):
                if isinstance(a, ast.Name):
                    cb = self._maybe_string_col(a)
                    lit = _try_fold(bexp)
                    if cb is not None and cb.dictionary is not None \
                            and lit is not None and isinstance(lit.value, str):
                        code = cb.dictionary.encode_existing(lit.value)
                        kern = "eq" if e.op == "=" else "ne"
                        return ir.call(kern, ir.Col(cb.internal),
                                       ir.Const(code, dt.DType(dt.Kind.STRING, False)))
        # PG-driver literal coercion (ADVICE r4): text-protocol clients
        # bind EVERY parameter as text (pgwire oid 0), so '123' compared
        # against a numeric/date column means the value in the column's
        # domain, not the string — re-type the literal before string
        # binding sees it. Unparseable text against a non-string column
        # is a clear bind error instead of a silent string comparison.
        if e.op in ("=", "<>", "<", "<=", ">", ">="):
            left = self._coerce_vs(e.right, e.left)
            right = self._coerce_vs(e.left, e.right)
            if left is not e.left or right is not e.right:
                e = ast.BinOp(e.op, left, right)
        # string comparisons fold through the dictionary
        if e.op in ("=", "<>", "<", "<=", ">", ">="):
            for a, bexp, flip in ((e.left, e.right, False), (e.right, e.left, True)):
                sf = _string_fn(a, self.scope, self.udfs)
                lit = _try_fold(bexp)
                if sf is not None and lit is not None and isinstance(lit.value, str):
                    b, fn = sf
                    op = e.op
                    if flip:
                        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
                    tgt = lit.value
                    cmpf = {"=": lambda s: s == tgt, "<>": lambda s: s != tgt,
                            "<": lambda s: s < tgt, "<=": lambda s: s <= tgt,
                            ">": lambda s: s > tgt, ">=": lambda s: s >= tgt}[op]
                    return _lut_pred(
                        b, lambda s: s is not None and fn(s) is not None
                        and cmpf(fn(s)), self.pool)
            # string col = string col (shared dictionary only); any other
            # comparison touching a string-valued side must not fall
            # through to raw code comparison (codes from different
            # dictionaries are incomparable)
            lsf = _string_fn(e.left, self.scope, self.udfs)
            rsf = _string_fn(e.right, self.scope, self.udfs)
            if lsf is not None or rsf is not None:
                if e.op in ("=", "<>") and lsf is not None and rsf is not None:
                    lb, rb = lsf[0], rsf[0]
                    if isinstance(e.left, ast.Name) \
                            and isinstance(e.right, ast.Name) \
                            and lb.dictionary is rb.dictionary:
                        pass   # same-dictionary code equality is exact
                    else:
                        raise BindError(
                            "string comparison across different "
                            "dictionaries/expressions is not supported yet")
                else:
                    raise BindError(
                        "unsupported string comparison (fold it against a "
                        "literal, or compare same-dictionary columns)")
        kern = self._BIN_KERNEL.get(e.op)
        if kern is None:
            raise BindError(f"operator {e.op}")
        return ir.call(kern, self.bind(e.left), self.bind(e.right))

    def _coerce_vs(self, col_expr: ast.Expr, lit_expr: ast.Expr) -> ast.Expr:
        """Re-type a text literal compared against a non-string column
        (PG text protocol sends every parameter as text). Returns the
        rewritten literal, or `lit_expr` itself when no coercion applies."""
        if not isinstance(col_expr, ast.Name):
            return lit_expr
        cb = self.scope.try_resolve(col_expr.parts)
        lit = _try_fold(lit_expr)
        if cb is None or cb.dtype.is_string or lit is None \
                or not isinstance(lit.value, str) or lit.hint is not None:
            return lit_expr
        new = _coerce_text_literal(lit.value, cb.dtype)
        if new is None:
            raise BindError(
                f"cannot compare column {col_expr.parts[-1]!r} "
                f"({cb.dtype.kind.value}) with string literal "
                f"{lit.value!r}")
        return new

    def _maybe_string_col(self, e: ast.Expr) -> Optional[ColumnBinding]:
        if isinstance(e, ast.Name):
            b = self.scope.try_resolve(e.parts)
            if b is not None and b.dtype.is_string:
                return b
        return None

    def _case(self, e: ast.Case) -> ir.Expr:
        sc = self._maybe_string_case(e)
        if sc is not None:
            return sc
        whens = []
        for cond, res in e.whens:
            if e.operand is not None:
                cond = ast.BinOp("=", e.operand, cond)
            whens.append((self.bind(cond), self.bind(res)))
        if e.default is not None:
            out = self.bind(e.default)
        else:
            out = ir.call("typed_null", whens[-1][1])
        for cond, res in reversed(whens):
            out = ir.call("if", cond, res, out)
        return out

    def _maybe_string_case(self, e: ast.Case) -> Optional[ir.Expr]:
        """String-valued CASE: branch values are string expressions of one
        source column and/or string literals. All branches encode into ONE
        fresh derived dictionary; the device selects int32 codes with the
        `if` kernel (the string CASE in ClickBench Q39's Src column).
        Mirrors how the reference keeps CASE over utf8 inside the block
        engine via dictionary-encoded arrays."""
        from ydb_tpu.core.dictionary import Dictionary
        branches = [res for _, res in e.whens]
        if e.default is not None:
            branches.append(e.default)
        kinds = []               # ("lit", str) | ("col", binding, fn)
        src_binding = None
        any_string = False
        for r in branches:
            f = _try_fold(r)
            if f is not None and isinstance(f.value, str):
                kinds.append(("lit", f.value))
                any_string = True
                continue
            sf = _string_fn(r, self.scope)
            if sf is None:
                return None      # non-string branch → normal CASE path
            b, fn = sf
            if src_binding is not None and b.internal != src_binding.internal:
                raise BindError(
                    "string CASE branches must derive from one column")
            src_binding = b
            kinds.append(("col", b, fn))
            any_string = True
        if not any_string:
            return None
        cache = self.pool.__dict__.setdefault("_derived_cache", {})
        ckey = ("case", repr(e))
        hit = cache.get(ckey)
        if hit is not None:
            return hit
        nd = Dictionary()
        irs = []
        lut_params = []
        for kind in kinds:
            if kind[0] == "lit":
                code = int(nd.encode([kind[1]])[0])
                irs.append(ir.Const(code, dt.DType(dt.Kind.STRING, False)))
            else:
                _, b, fn = kind
                src = b.dictionary.values_array()
                lut = np.full(max(len(src), 1), -1, dtype=np.int32)
                for i, v in enumerate(src):
                    r = fn(v)
                    if r is not None:
                        lut[i] = nd.encode([r])[0]
                p = self.pool.add(lut, dt.DType(dt.Kind.STRING, False),
                                  is_array=True)
                lut_params.append(p.name)
                irs.append(ir.call("take_lut", ir.Col(b.internal), p))
        default_ir = irs.pop() if e.default is not None else ir.Const(
            -1, dt.DType(dt.Kind.STRING, False))
        out = default_ir
        conds = []
        for cond, _ in e.whens:
            if e.operand is not None:
                cond = ast.BinOp("=", e.operand, cond)
            conds.append(self.bind(cond))
        for cond_ir, res_ir in zip(reversed(conds), reversed(irs)):
            out = ir.call("if", cond_ir, res_ir, out)
        for pname in lut_params:
            self.pool.param_dicts[pname] = nd
        # all-literal CASE has no take_lut param to carry the dictionary —
        # key it on the root IR node identity (the memo cache returns this
        # exact object for every rebinding)
        self.pool.__dict__.setdefault("expr_dicts", {})[id(out)] = nd
        cache[ckey] = out
        return out

    def _func(self, e: ast.FuncCall) -> ir.Expr:
        name = e.name
        if name in AGG_NAMES:
            raise BindError(f"aggregate {name} not allowed here")
        # string-valued if/coalesce must share ONE derived dictionary —
        # route through the string-CASE path (independent dictionaries
        # would decode each other's codes)
        if name == "if" and len(e.args) == 3:
            sc = self._maybe_string_case(ast.Case(
                None, ((e.args[0], e.args[1]),), e.args[2]))
            if sc is not None:
                return sc
        if name == "coalesce" and len(e.args) >= 2:
            sc = self._maybe_string_case(ast.Case(
                None, tuple((ast.IsNull(a, negated=True), a)
                            for a in e.args[:-1]), e.args[-1]))
            if sc is not None:
                return sc
        simple = {"year": "year", "month": "month", "day": "day_of_month",
                  "hour": "hour_of_day", "minute": "minute_of_hour",
                  "second": "second_of_minute",
                  "abs": "abs", "floor": "floor", "ceil": "ceil",
                  "sqrt": "sqrt", "exp": "exp", "ln": "ln", "round": "round",
                  "coalesce": "coalesce", "if": "if"}
        if name in simple:
            return ir.call(simple[name], *[self.bind(a) for a in e.args])
        if name == "power":
            return ir.call("pow", *[self.bind(a) for a in e.args])
        if name == "length":
            if len(e.args) != 1:
                raise BindError("length takes one argument")
            sf = _string_fn(e.args[0], self.scope, self.udfs)
            if sf is None:
                raise BindError("length needs a string expression")
            b, fn = sf
            return _lut_int(
                b, lambda s: None if s is None or fn(s) is None
                else len(fn(s)), self.pool)
        if name in ("startswith", "endswith", "contains_string"):
            sf = _string_fn(e.args[0], self.scope, self.udfs)
            lit = _try_fold(e.args[1])
            if sf is None or lit is None:
                raise BindError(f"{name} needs a string column and literal")
            b, fn = sf
            tgt = lit.value
            if isinstance(e.args[0], ast.Name) and b.dictionary is not None:
                # raw column: vectorized over the whole dictionary
                vec = {"startswith":
                       lambda s: s.str.startswith(tgt),
                       "endswith": lambda s: s.str.endswith(tgt),
                       "contains_string":
                       lambda s: s.str.contains(tgt, regex=False)}[name]
                return _lut_pred_vec(b, vec, self.pool)
            test = {"startswith": lambda s: s.startswith(tgt),
                    "endswith": lambda s: s.endswith(tgt),
                    "contains_string": lambda s: tgt in s}[name]
            return _lut_pred(b, lambda s: s is not None and test(fn(s)),
                             self.pool)
        if self.udfs is not None and name in self.udfs:
            return self._bind_udf(self.udfs.get(name), e)
        raise BindError(f"unknown function {name}")

    def _bind_udf(self, u, e: ast.FuncCall) -> ir.Expr:
        """Scalar UDF over a dictionary column: evaluate once per
        DISTINCT value host-side, gather through a LUT on device
        (`query/udf.py` — the loadable-UDF seat, re2/url/json/ip udfs).
        First arg = string expression of one dictionary column; the rest
        fold to literals."""
        if not (u.min_args <= len(e.args) <= u.max_args):
            raise BindError(f"udf {u.name} takes {u.min_args}"
                            f"..{u.max_args} arguments")
        lit0 = _try_fold(e.args[0])
        if lit0 is not None and isinstance(lit0.value, str) \
                and u.returns != "string":
            # constant input: evaluate once at bind time
            lits0 = []
            for a in e.args[1:]:
                lf = _try_fold(a)
                if lf is None:
                    raise BindError(f"udf {u.name}: arguments after the "
                                    "first must fold to literals")
                lits0.append(lf.value)
            try:
                r = u.fn(lit0.value, *lits0)
            except Exception as ex:          # noqa: BLE001 — user code
                raise BindError(f"udf {u.name} failed: "
                                f"{type(ex).__name__}: {ex}") from ex
            kind0 = {"int64": dt.Kind.INT64, "float64": dt.Kind.FLOAT64,
                     "bool": dt.Kind.BOOL}[u.returns]
            if r is None:
                return ir.call("typed_null",
                               ir.Const(0, dt.DType(kind0, False)))
            # coerce like the LUT paths do (bool() / int() / float())
            r = {"int64": int, "float64": float,
                 "bool": bool}[u.returns](r)
            return ir.Const(r, dt.DType(kind0, False))
        sf = _string_fn(e.args[0], self.scope, self.udfs)
        if sf is None:
            raise BindError(
                f"udf {u.name} needs a dictionary-encoded string "
                f"expression as its first argument")
        b, f = sf
        lits = []
        for a in e.args[1:]:
            lf = _try_fold(a)
            if lf is None:
                raise BindError(f"udf {u.name}: arguments after the "
                                "first must fold to literals")
            lits.append(lf.value)

        def call(s, f=f, fn=u.fn, lits=tuple(lits), name=u.name):
            inner = f(s) if s is not None else None
            try:
                return fn(inner, *lits)
            except Exception as ex:          # noqa: BLE001 — user code
                raise BindError(
                    f"udf {name} failed on {inner!r}: "
                    f"{type(ex).__name__}: {ex}") from ex

        if u.returns == "string":
            return self._derived_string(e, (b, call))
        if u.returns == "bool":
            # predicate LUT: fn-None and input-NULL both read as FALSE
            return _lut_pred(b, lambda s: bool(call(s)), self.pool)
        kind = dt.Kind.INT64 if u.returns == "int64" else dt.Kind.FLOAT64
        return _lut_typed(b, call, self.pool, kind)
