"""Physical plan executor (single-node).

The analog of the KQP scan-executer + compute-actor run loop
(`kqp_scan_executer.cpp`, `dq_compute_actor_impl.h:295`): streams blocks
from shard scans through the device-compiled pipeline (pushdown program →
broadcast-join probes → partial aggregation), merges partials, and runs the
final stage (merge GroupBy, HAVING, output expressions, sort, limit).

Every block-level compute step runs on the device via the jit pattern cache
(`ops/xla_exec.py`); the host only routes blocks and (for now) concatenates
partials — the role the DQ channels play in the reference.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.ops import join as J
from ydb_tpu.ops.device import DeviceBlock, to_device, to_host
from ydb_tpu.ops.sort import sort_block
from ydb_tpu.ops.xla_exec import compress_block, run_on_device
from ydb_tpu.query.plan import JoinStep, Pipeline, QueryPlan, SortKey
from ydb_tpu.storage.mvcc import MAX_SNAPSHOT, Snapshot

DEFAULT_BLOCK_ROWS = 1 << 20


class Executor:
    def __init__(self, catalog, block_rows: int = DEFAULT_BLOCK_ROWS):
        self.catalog = catalog
        self.block_rows = block_rows

    # -- entry -------------------------------------------------------------

    def execute(self, plan: QueryPlan,
                snapshot: Snapshot = MAX_SNAPSHOT) -> HostBlock:
        partials = self._run_pipeline(plan.pipeline, plan.params, snapshot)
        merged = HostBlock.concat(partials)

        if plan.final_program is not None:
            merged = to_host(run_on_device(plan.final_program,
                                           to_device(merged), plan.params))

        if plan.sort:
            merged = self._sort(merged, plan.sort, plan.limit, plan.offset)
        elif plan.limit is not None or plan.offset:
            lo = plan.offset or 0
            hi = lo + plan.limit if plan.limit is not None else merged.length
            merged = merged.slice(lo, min(hi, merged.length))

        return self._project_output(merged, plan.output)

    # -- pipelines ---------------------------------------------------------

    def _run_pipeline(self, pipe: Pipeline, params: dict,
                      snapshot: Snapshot) -> list:
        """Partial-result HostBlocks for a pipeline (≥1 block: an empty scan
        still runs the programs once so global aggregates emit their row)."""
        builds = [self._prepare_join(step, params, snapshot)
                  for kind, step in pipe.steps if kind == "join"]
        out = [self._run_block(pipe, block, builds, params)
               for block in self._scan_blocks(pipe, snapshot)]
        if not out:
            out = [self._run_block(pipe, self._empty_scan_block(pipe),
                                   builds, params)]
        return out

    def _run_block(self, pipe: Pipeline, block: HostBlock, builds: list,
                   params: dict) -> HostBlock:
        d = to_device(block)
        if pipe.pre_program is not None:
            d = run_on_device(pipe.pre_program, d, params)
        bi = 0
        for kind, step in pipe.steps:
            if kind == "join":
                table = builds[bi]
                bi += 1
                rename = {}
                d, sel = J.probe(d, table, step.probe_key, step.kind,
                                 sel=None, rename=rename)
                d = compress_block(d, sel)
            else:
                d = run_on_device(step, d, params)
        if pipe.partial is not None:
            d = run_on_device(pipe.partial, d, params)
        return to_host(d)

    def _prepare_join(self, step: JoinStep, params: dict,
                      snapshot: Snapshot) -> J.BuildTable:
        built = HostBlock.concat(self._run_pipeline(step.build, params,
                                                    snapshot))
        return J.build(built, step.build_key, list(step.payload))

    def _scan_blocks(self, pipe: Pipeline, snapshot: Snapshot):
        table = self.catalog.table(pipe.scan.table)
        storage_names = [s for (s, _i) in pipe.scan.columns]
        rename = {s: i for (s, i) in pipe.scan.columns}
        for shard in table.shards:
            for block in shard.scan(storage_names, snapshot,
                                    prune_predicates=pipe.scan.prune or None,
                                    block_rows=self.block_rows):
                yield _rename_block(block, rename)

    def _empty_scan_block(self, pipe: Pipeline) -> HostBlock:
        """Zero-row block with the scan's schema and dictionaries."""
        table = self.catalog.table(pipe.scan.table)
        cols, schema_cols = {}, []
        for (storage, internal) in pipe.scan.columns:
            c = table.schema.col(storage)
            cols[internal] = ColumnData(
                np.zeros(0, dtype=c.dtype.np), None,
                table.dictionaries.get(storage))
            schema_cols.append(Column(internal, c.dtype))
        return HostBlock(Schema(schema_cols), cols, 0)

    # -- final sort / output ----------------------------------------------

    def _sort(self, block: HostBlock, sort_keys: list,
              limit: Optional[int], offset: Optional[int]) -> HostBlock:
        if block.length == 0:
            return block
        prog = ir.Program()
        keys = []
        drop = []
        pool_params = {}
        for j, sk in enumerate(sort_keys):
            dtype = block.schema.dtype(sk.name)
            cd = block.columns[sk.name]
            if dtype.is_string and cd.dictionary is not None:
                # order by lexicographic rank, not dictionary code
                vals = cd.dictionary.values_array()
                ranks = np.argsort(np.argsort(vals)).astype(np.int32) \
                    if len(vals) else np.zeros(0, np.int32)
                pname = f"__rank{j}"
                pool_params[pname] = ranks
                rank_col = f"__sortrank{j}"
                from ydb_tpu.core import dtypes as dt
                prog.assign(rank_col, ir.call(
                    "take_lut", ir.Col(sk.name),
                    ir.Param(pname, dt.DType(dt.Kind.INT32, False),
                             is_array=True)))
                keys.append((rank_col, sk.ascending, sk.nulls_first))
                drop.append(rank_col)
            else:
                keys.append((sk.name, sk.ascending, sk.nulls_first))
        d = to_device(block)
        if prog.commands:
            d = run_on_device(prog, d, pool_params)
        d = sort_block(d, keys, limit=(None if offset else limit))
        out = to_host(d)
        if drop:
            out = out.select([n for n in out.schema.names if n not in drop])
        lo = offset or 0
        if lo or limit is not None:
            hi = lo + limit if limit is not None else out.length
            out = out.slice(lo, min(hi, out.length))
        return out

    def _project_output(self, block: HostBlock, output: list) -> HostBlock:
        cols = {}
        schema_cols = []
        used = set()
        for (internal, label) in output:
            lbl = label
            k = 2
            while lbl in used:
                lbl = f"{label}_{k}"
                k += 1
            used.add(lbl)
            cd = block.columns[internal]
            cols[lbl] = ColumnData(cd.data, cd.valid, cd.dictionary)
            schema_cols.append(Column(lbl, block.schema.dtype(internal)))
        return HostBlock(Schema(schema_cols), cols, block.length)


def _rename_block(block: HostBlock, rename: dict) -> HostBlock:
    cols = {}
    schema_cols = []
    for c in block.schema:
        new = rename.get(c.name, c.name)
        cols[new] = block.columns[c.name]
        schema_cols.append(Column(new, c.dtype))
    return HostBlock(Schema(schema_cols), cols, block.length)
