"""Physical plan executor (single-node).

The analog of the KQP scan-executer + compute-actor run loop
(`kqp_scan_executer.cpp`, `dq_compute_actor_impl.h:295`): streams per-portion
device blocks (HBM column cache) through the device-compiled pipeline
(pushdown program → broadcast-join probes → partial aggregation), then runs
ONE fused device program for the whole final stage — device-side concat of
the partials, merge GroupBy, HAVING, output expressions, sort and limit —
so a query costs K partial dispatches + 1 finalize dispatch + 1 transfer,
not a host round-trip per stage (the dispatch economy matters doubly on a
tunneled TPU).
"""

from __future__ import annotations

from functools import partial as _partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.ops import join as J
from ydb_tpu.ops.device import (
    DeviceBlock, DeviceResultFuture, bucket_capacity, to_device, to_host,
    to_host_async,
)
from ydb_tpu.ops.sort import sort_env
from ydb_tpu.ops.xla_exec import (
    _trace_program, compress, compress_block, groupby_tuning, run_on_device,
)
from ydb_tpu.progstore import buckets as shape_buckets
from ydb_tpu.progstore import compile_ahead as ca_lane
from ydb_tpu.query.plan import JoinStep, Pipeline, QueryPlan, SortKey
from ydb_tpu.storage.mvcc import MAX_SNAPSHOT, Snapshot
from ydb_tpu.utils import progstats

DEFAULT_BLOCK_ROWS = 1 << 20


def _xla_scope(name: str):
    """`jax.profiler.TraceAnnotation`-compatible named scope around the
    device phases of fused/batched dispatch, named IDENTICALLY to our
    tracer spans — an on-chip XLA profile (Perfetto from
    `jax.profiler.trace`) lines its slices up with the engine's own
    span names. Effectively a no-op on CPU (and a nullcontext wherever
    the profiler API is absent); never allowed to fail a query."""
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(name)
    except Exception:                    # noqa: BLE001 — observability
        from contextlib import nullcontext
        return nullcontext()


def _fused_evict_hook(key) -> None:
    """Map a fused-cache eviction back to its program-inventory kind:
    batched-lane entries key on a ("batched", ...) tuple, everything
    else captured from this cache is a fused program (tile entries are
    not inventoried — mark_evicted on an unknown key is a no-op)."""
    kind = "batched" if isinstance(key, tuple) and key \
        and key[0] == "batched" else "fused"
    progstats.mark_evicted(kind, key)


class Executor:
    def __init__(self, catalog, block_rows: int = DEFAULT_BLOCK_ROWS,
                 device_cache=None, mesh=None):
        from ydb_tpu.storage.device_cache import DeviceColumnCache
        from ydb_tpu.ops.exec_cache import ExecCache
        self.catalog = catalog
        self.block_rows = block_rows
        self.device_cache = device_cache or DeviceColumnCache()
        # compiled-program caches share one process-wide live-executable
        # budget with LRU eviction (ops/exec_cache.py) — unbounded dicts
        # here accumulated executables until the platform compile service
        # wedged (r4 cleared them manually between queries)
        self._finalize_cache = ExecCache("finalize")
        self._fused_cache = ExecCache("fused")
        # LRU evictions of fused/batched programs surface in the
        # program inventory (`.sys/compiled_programs`, state=evicted) —
        # the cache keys carry a "batched" head for lane entries, so
        # the kind is recovered from the key itself
        self._fused_cache.on_evict = _fused_evict_hook
        # device mesh for distributed execution (None / size-1 mesh →
        # single-device). The analog of the KQP task graph + DQ hash-shuffle
        # channels (`dq_tasks_graph.h:43`): scans are row-partitioned across
        # mesh devices, the partial→final aggregation boundary is an ICI
        # all_to_all hash shuffle.
        self.mesh = mesh
        self._dist_aggs = ExecCache("dist-agg")
        self._shuffle_joins = ExecCache("shuffle-join")
        # feature flag (utils/config.py): the whole-query single-dispatch
        # path; off = always the portioned streaming path (debug lever)
        self.enable_fused = True
        # engine-provided tracer (utils/tracing.Tracer) — None = no spans
        self.tracer = None
        # which path the last execute() took (THREAD-LOCAL — concurrent
        # sessions each observe their own):
        # fused | fused-tiled[...] | portioned | distributed | literal
        import threading as _threading
        self._tls = _threading.local()
        # build sides above this estimate hash-partition into a GraceJoin
        # (host-DRAM partitions probed one at a time — the spill budget)
        import os as _os
        self.grace_budget_bytes = int(
            _os.environ.get("YDB_TPU_GRACE_BUDGET", 1 << 29))
        # scans whose stacked superblock estimate exceeds this stream
        # through the tiled fused path instead of residing in HBM
        self.fused_scan_budget_bytes = int(
            _os.environ.get("YDB_TPU_FUSED_SCAN_BUDGET", 6 << 30))
        # HBM bytes per scan tile on the tiled path (2 tiles in flight)
        self.tile_budget_bytes = int(
            _os.environ.get("YDB_TPU_TILE_BUDGET", 1 << 30))
        # partial-agg states above this estimate spill to host DRAM and
        # merge per key-hash partition (WideCombiner ProcessSpilled analog)
        self.merge_budget_bytes = int(
            _os.environ.get("YDB_TPU_MERGE_BUDGET", 1 << 30))
        # mesh joins: build sides above this estimate hash-partition across
        # devices (shuffle join) instead of replicating to every device
        self.dist_broadcast_budget_bytes = int(
            _os.environ.get("YDB_TPU_DIST_BROADCAST_BUDGET", 256 << 20))
        # fused-program complexity cap: plans with more join steps than
        # this stream portioned — a 7-join whole-query program has been
        # observed to SIGSEGV the platform's TPU compiler service
        self.fuse_max_joins = int(
            _os.environ.get("YDB_TPU_FUSE_MAX_JOINS", 6))
        # cross-query join-build cache (query/build_cache.py): finished
        # device-resident BuildTables keyed by build-plan fingerprint +
        # visible data + probe dictionary — the r4 profile's dominant
        # slow-query cost was per-query build re-execution + LUT re-upload
        from ydb_tpu.query.build_cache import BuildCache
        self.build_cache = BuildCache(int(
            _os.environ.get("YDB_TPU_BUILD_CACHE_BUDGET", 2 << 30)),
            device_cache=self.device_cache)
        # single-flight dedup for fused/batched program fills: a client
        # storm on a fresh shape compiles ONCE (one leader traces and
        # compiles, followers block on its future and share the handle)
        # — the compile-ahead lane launches through the same flight so a
        # background warm and a synchronous dispatch never double-compile
        self._sflight = ca_lane.SingleFlight()
        # (table, data_version, lift_sig) triples the compile-ahead lane
        # has already warmed — a repeated statement must not re-walk plan
        # setup on the background pool every time it runs
        self._warm_seen: set = set()
        self._warm_mu = _threading.Lock()
        # build-time trace deltas parked by the compile-ahead worker,
        # keyed by (kind, cache key): the thread-local groupby/bounds
        # gauges a background build records would otherwise vanish —
        # the FIRST foreground statement to consume the warmed entry
        # folds them into its own window (guarded-by: _warm_mu)
        self._trace_debt: dict = {}
        # trace+compile wall-ms of warm-lane builds, parked the same
        # way: the statement that consumes the warmed entry reports the
        # build it triggered in its `compile_ms` phase — byte-equal
        # with the lane off, where the same statement compiles inline
        # (guarded-by: _warm_mu)
        self._compile_debt: dict = {}
        # bound-sized compaction (late materialization): measured live
        # row counts per compact-free fused key, monotone max — an
        # overflow rerun teaches every future sizing of the same shape.
        # Plain dict: values are ints, reads/writes are GIL-atomic.
        self._compact_memo: dict = {}
        # chosen compact capacities per compact-free fused key — sticky
        # so within-headroom data growth reuses the compiled program
        self._compact_caps: dict = {}
    # DQ task-graph runtime (`ydb_tpu/dq/`): >0 while THIS THREAD is
    # running a statement as a stage program of a distributed task — the
    # worker's share of a multi-process graph, or the 1-worker degenerate
    # case. Thread-local: a worker serving a DQ task concurrently with a
    # plain query on another thread must not count the plain query.
    # Counted on /counters (`dq/local_stage_execs`) so workers show
    # their stage traffic.
    @property
    def dq_stage_depth(self) -> int:
        return getattr(self._tls, "dq_stage_depth", 0)

    @dq_stage_depth.setter
    def dq_stage_depth(self, v: int):
        self._tls.dq_stage_depth = v

    # device-resident stage spine: while True on THIS THREAD, a fused
    # statement's result is handed back as a `DeviceStageBlock` (device
    # arrays by reference, host readback deferred) instead of being
    # drained through `fetch_fused_result`. Armed by `dq/task.py` around
    # stage statements so multi-stage plans flow device→device; plain
    # client statements never see it.
    @property
    def dq_device_capture(self) -> bool:
        return getattr(self._tls, "dq_device_capture", False)

    @dq_device_capture.setter
    def dq_device_capture(self, v: bool):
        self._tls.dq_device_capture = v

    @property
    def last_path(self) -> str:
        return getattr(self._tls, "last_path", "")

    @last_path.setter
    def last_path(self, v: str):
        self._tls.last_path = v

    def _span(self, name: str, **attrs):
        if self.tracer is not None:
            return self.tracer.span(name, **attrs)
        from ydb_tpu.utils.tracing import _NullSpanCtx
        return _NullSpanCtx()   # yields a throwaway span (attrs writable)

    # -- cache warmup ------------------------------------------------------

    def prewarm(self, tables=None, snapshot: Snapshot = MAX_SNAPSHOT) -> int:
        """Upload every column of the given tables (default: all) into the
        HBM superblock cache — the buffer-pool warmup analog
        (`ydb/core/tablet_flat` shared cache fills on demand; here warmup
        matters doubly because this platform's host→device link degrades
        ~20x after the first device→host readout, so uploads queued
        before any result is fetched run at full bandwidth — PERF.md).

        Returns the number of bytes resident in the cache afterwards.
        Tables whose stacked estimate exceeds the fused-scan budget are
        skipped (they will stream through the tiled path anyway)."""
        from ydb_tpu.storage.device_cache import (
            enumerate_scan_sources, estimate_scan_bytes,
        )
        names = tables if tables is not None else list(self.catalog.tables)
        for tname in names:
            table = self.catalog.table(tname)
            storage_names = list(table.schema.names)
            try:
                sources, _ids = enumerate_scan_sources(table, snapshot, None)
            except AttributeError:       # row tables scan uncached
                continue
            if not sources:
                continue
            Kb = shape_buckets.bucket_sources(len(sources))
            est = estimate_scan_bytes(sources, storage_names, pad_to=Kb)
            if est > self.fused_scan_budget_bytes:
                continue
            self.device_cache.superblock(table, storage_names, {}, snapshot,
                                         None, sources, _ids, pad_to=Kb)
        return self.device_cache.bytes

    # -- entry -------------------------------------------------------------

    def execute(self, plan: QueryPlan,
                snapshot: Snapshot = MAX_SNAPSHOT) -> HostBlock:
        return self.execute_async(plan, snapshot).result()

    def execute_async(self, plan: QueryPlan,
                      snapshot: Snapshot = MAX_SNAPSHOT
                      ) -> DeviceResultFuture:
        """Dispatch phase of a SELECT: plan → compile-cache hit → device
        enqueue, WITHOUT blocking on the device→host readout. Returns a
        `DeviceResultFuture` whose `result()` performs the single pytree
        `device_get` (plus host unpack / projection) — the engine drains
        it lock-free, so query N+1 dispatches while query N's result
        crosses the link (the ~35 ms post-readout dispatch cliff
        pipelines down to ~10 ms when overlapped, PERF.md). Paths that
        must materialize host-side mid-flight (distributed, tiled,
        spill) resolve eagerly and return a completed future."""
        if self.dq_stage_depth:
            from ydb_tpu.utils.metrics import GLOBAL
            GLOBAL.inc("dq/local_stage_execs")
        params = dict(plan.params)
        # precompute stage: uncorrelated scalar subqueries → params
        for (pname, subplan) in plan.init_subplans:
            sub = self.execute(subplan, snapshot)
            if sub.length > 1:
                raise RuntimeError("scalar subquery produced more than one row")
            col = sub.columns[sub.schema.names[0]]
            if sub.length == 0 or (col.valid is not None
                                   and not col.valid[0]):
                # NULL scalar: typed zero placeholder + validity companion
                # (the binder wraps nullable params in if(valid, v, null))
                params[pname] = np.zeros((), col.data.dtype)[()]
                params[pname + "__valid"] = False
            else:
                params[pname] = col.data[0]
                params[pname + "__valid"] = True

        if self.mesh is not None and self.mesh.devices.size > 1:
            if self._can_distribute(plan):
                prebuilt: dict = {}
                sj = self._try_execute_shuffle_join(plan, params, snapshot,
                                                    prebuilt)
                if sj is not None:
                    self.last_path = "distributed-shuffle-join"
                    return DeviceResultFuture.completed(
                        self._project_output(sj, plan.output))
                self.last_path = "distributed"
                merged = self._execute_distributed(plan, params, snapshot,
                                                   prebuilt)
                return DeviceResultFuture.completed(
                    self._project_output(merged, plan.output))
            if self._can_distribute_map(plan, snapshot):
                self.last_path = "distributed-map"
                merged = self._execute_distributed_map(plan, params,
                                                       snapshot)
                return DeviceResultFuture.completed(
                    self._project_output(merged, plan.output))

        with self._span("fused-attempt"):
            fused = self._try_execute_fused(plan, params, snapshot,
                                            defer=True) \
                if self.enable_fused else None
        if isinstance(fused, tuple):           # tiled path: (kind, block)
            kind, block = fused
            self.last_path = kind
            return DeviceResultFuture.completed(
                self._project_output(block, plan.output))
        if isinstance(fused, DeviceResultFuture):
            self.last_path = "fused"
            return fused.map(
                lambda b: self._project_output(b, plan.output))

        # fused path declined: it may have prepared the join builds already
        self.last_path = "portioned"
        partials = self._run_pipeline(plan.pipeline, params, snapshot,
                                      builds=fused)
        fut = self._finalize(plan, partials, params, defer=True)
        return fut.map(lambda b: self._project_output(b, plan.output))

    # -- fused whole-query path --------------------------------------------

    def _try_execute_fused(self, plan: QueryPlan, params: dict,
                           snapshot: Snapshot, defer: bool = False,
                           _no_compact: bool = False):
        """Run the query as ONE fused device program (`ops/fused.py`) when
        its shape allows: single device, joins unique-keyed where
        payloads attach (expanding duplicate-key probes need a
        data-dependent output capacity, so they stay on the portioned
        path). Probes use a direct-address LUT when the build has one,
        an unrolled binary search otherwise (sparse spans, float keys).

        Returns the merged HostBlock on success (`defer=True`: a
        `DeviceResultFuture` deferring the single-pytree readout — the
        pipeline dispatch/readout seam); on fallback, the list of
        prepared join BuildTables (for `_run_pipeline` to reuse) or None
        if none were prepared."""
        from ydb_tpu.ops import fused as F

        pipe = plan.pipeline
        table = self.catalog.table(pipe.scan.table)

        # builds + fusability checks FIRST — the superblock stack/upload is
        # the expensive part and must not run for plans that always take
        # the portioned path
        join_steps = [step for kind, step in pipe.steps if kind == "join"]
        if len(join_steps) > self.fuse_max_joins:
            return None                  # program-complexity cap
        with self._span("join-builds", n=len(join_steps)):
            builds = self._prepare_builds(pipe, params, snapshot)
        for step, bt in zip(join_steps, builds):
            if isinstance(bt, J.PartitionedBuild) or (
                    not bt.unique and step.kind in ("inner", "left", "mark")):
                return builds   # partitioned / expanding probe

        plan0 = plan            # pre-rewrite plan (the overflow-rerun input)
        (plan, pipe, scan_cols, schema, partial_schema, dicts,
         join_metas, late_scan) = self._fused_plan_setup(plan, builds)

        storage_names = [s for (s, _i) in pipe.scan.columns]
        rename = {s: i for (s, i) in pipe.scan.columns}

        # HBM admission: a scan whose stacked superblock would not fit the
        # budget streams through the tiled path instead of OOMing the chip
        from ydb_tpu.storage.device_cache import (
            enumerate_scan_sources, estimate_scan_bytes,
        )
        sources, src_ids = enumerate_scan_sources(table, snapshot,
                                                  pipe.scan.prune or None)
        # shape buckets: quantize the source count so a growing table
        # reuses the bucket's program (zero-length pad rows, masked out
        # by the per-row length vector exactly like short real sources)
        Kb = shape_buckets.bucket_sources(len(sources))
        if sources and estimate_scan_bytes(sources, storage_names,
                                           pad_to=Kb) \
                > self.fused_scan_budget_bytes:
            return self._execute_fused_tiled(
                plan, params, pipe, sources, scan_cols, builds, join_metas,
                dicts, partial_schema)

        with self._span("superblock-upload"):
            sb = self.device_cache.superblock(table, storage_names, rename,
                                              snapshot,
                                              pipe.scan.prune or None,
                                              sources, src_ids, pad_to=Kb)
        if sb is None:
            return builds or None          # empty scan → portioned path
        arrays, valids, lengths, K, CAP, sb_dicts = sb
        sb_valid_names = frozenset(valids.keys())
        dicts.update(sb_dicts)
        # resource ledger: the scan's device working set is the stacked
        # (K, CAP) superblock; live rows come from the host-side source
        # blocks (no device sync)
        from ydb_tpu.utils import memledger
        memledger.record_padded_buffers(
            "superblock", "superblock",
            int(sum(b.length for b in sources)) if sources else 0,
            K * CAP, arrays, valids)

        sort_params, sort_spec, rank_assigns = self._sort_setup_fused(
            plan, schema, dicts)
        all_params = {**params, **sort_params}

        # lifted LIMIT (paramlift plans only): the clamp rides in as the
        # __lim2 device input and the program keys on the limit's
        # capacity bucket — `limit 3` and `limit 5` share one executable
        lift_limit, lim_key = self._lift_limit_setup(plan, all_params)

        builds_sig = tuple(F.build_inputs_sig(bt) for bt in builds)
        base_key = F.fused_cache_key(plan, scan_cols, K, CAP,
                                     sb_valid_names, builds_sig, sort_spec,
                                     rank_assigns,
                                     tuple(sorted(all_params)),
                                     lim_key=lim_key)
        # bound-sized device compaction: when the filters/joins provably
        # collapse the live count, an `ir.Compact` shrinks the pipeline
        # from scan capacity to a ladder-quantized bound before the
        # partial group-by, and every deferred late-mat gather compiles
        # at the small shape. Sized from CBO + FK selectivities plus the
        # measured-live memo; an underestimate trips the device overflow
        # flag in `fetch` and the statement reruns WITHOUT the compact —
        # loud and counted, never a silent truncation.
        compact_cap = None if _no_compact else self._compact_sizing(
            base_key, pipe, builds, sources, K * CAP)
        compact_prog = None
        key = base_key
        if compact_cap:
            compact_prog = ir.Program([ir.Compact(compact_cap)])
            key = F.fused_cache_key(plan, scan_cols, K, CAP,
                                    sb_valid_names, builds_sig, sort_spec,
                                    rank_assigns,
                                    tuple(sorted(all_params)),
                                    lim_key=lim_key,
                                    compact_cap=compact_cap)
        from ydb_tpu.utils.metrics import GLOBAL
        ndeferred = len(late_scan) + sum(
            len(m["payload_names"]) for m in join_metas if m["late"])
        if ndeferred:
            GLOBAL.inc("latemat/deferred_cols", ndeferred)
        if compact_cap:
            GLOBAL.inc("latemat/compact_plans")
            GLOBAL.inc("latemat/compact_capacity_rows", compact_cap)

        def _builder():
            fn, layout_box = F.build_fused_fn(
                pipe, plan.final_program, scan_cols, K, CAP, sb_valid_names,
                join_metas, rank_assigns, sort_spec, plan.limit, plan.offset,
                tuple(dict.fromkeys(n for (n, _lbl) in plan.output)),
                lift_limit=lift_limit, late_scan=late_scan,
                compact_prog=compact_prog)
            keep = list(dict.fromkeys(n for (n, _lbl) in plan.output))
            out_cols = [c for c in schema.columns if c.name in keep] \
                or list(schema.columns)
            return fn, layout_box, Schema(out_cols)

        entry = self._fused_cache.get(key)
        fresh_compile = entry is None
        if entry is not None:
            fn, layout_box, out_schema = entry
            progstats.record_hit(getattr(fn, "key_id", None))
            self._consume_trace_debt("fused", key)
        else:
            fn = layout_box = out_schema = None

        dev_params = {k: (jnp.asarray(v) if isinstance(v, np.ndarray) else v)
                      for k, v in all_params.items()}
        build_inputs = [F.build_traced_inputs(bt) for bt in builds]
        with self._span("device-dispatch", k=K, cap=CAP) as dsp, \
                _xla_scope("device-dispatch"):
            import time as _time
            t_disp = _time.perf_counter()
            fill_wait_ms = 0.0
            if fn is None:
                # fresh shapes fill INSIDE the dispatch span (the
                # compile stays at the span front for the critical-path
                # split and the phase breakdown): exec cache → the
                # persistent program store (a deserialize, compile_ms
                # ~= 0) → the program observatory's AOT capture
                # (`utils/progstats.capture` — lower().compile(), ONE
                # trace + ONE compile, cost and memory analysis
                # recorded, the executable serialized back to the
                # store); all under single-flight so a storm on this
                # shape compiles once
                (fn, layout_box, out_schema), fresh_compile = \
                    self._fused_fill(
                        "fused", key, _builder,
                        (arrays, valids, lengths, build_inputs,
                         dev_params))
                fill_wait_ms = (_time.perf_counter() - t_disp) * 1000.0
            data_stacks, valid_stack, length, aux = fn(
                arrays, valids, lengths, build_inputs, dev_params)
            if fresh_compile:
                # jit compiles synchronously inside the first call of a
                # fresh shape (AOT: in capture above); steady-state
                # dispatch is ~async enqueue — the delta IS this
                # program's trace+compile cost
                dsp.attrs["compile_ms"] = round(
                    (_time.perf_counter() - t_disp) * 1000.0, 3)
            else:
                # compile-ahead consumer: the build ran on the lane's
                # worker thread, triggered by THIS statement's own
                # planning — report the parked trace+compile cost here,
                # once, exactly as the lane-off inline compile would.
                # `compile_wait_ms` is the slice of that build the
                # dispatch actually blocked on (the rest overlapped
                # planning): the phase roll-up subtracts the wait, not
                # the whole off-thread build, from dispatch_ms
                with self._warm_mu:
                    warm_ms = self._compile_debt.pop(("fused", key), None)
                if warm_ms is not None:
                    dsp.attrs["compile_ms"] = warm_ms
                    dsp.attrs["compile_wait_ms"] = round(
                        min(fill_wait_ms, warm_ms), 3)
        # result buffers live in HBM until the future drains them
        memledger.record_alloc(
            "result_buffers",
            memledger.deep_nbytes((data_stacks, valid_stack)))

        # readout deferred into the result future: the dispatch above is
        # async, and `fetch_fused_result` performs the ONE device→host
        # pytree transfer when the result is consumed — concurrent
        # queries dispatch while this one drains D2H
        out_dicts = {n2: d for n2, d in dicts.items() if out_schema.has(n2)}
        out_dicts.update({n2: d for n2, d in plan.result_dicts.items()
                          if out_schema.has(n2)})
        lo = plan.offset or 0
        limit = plan.limit

        prog_kid = getattr(fn, "key_id", None)
        # stage-spine capture: read the thread-local flag at DISPATCH
        # time (the future may be resolved on another thread). An OFFSET
        # tail would force a host slice anyway, so those plans keep the
        # host readout.
        capture_device = bool(self.dq_device_capture) and not lo

        def fetch() -> HostBlock:
            # split the readout into on-device execute (block_until_ready
            # delta — the program is still running when the future is
            # consumed promptly) and the D2H transfer + host unpack, so
            # the trace attributes device time separately from link time
            with self._span("device-execute"), \
                    _xla_scope("device-execute"):
                import time as _time
                t_exec = _time.perf_counter()
                jax.block_until_ready((data_stacks, valid_stack, length))
                exec_ms = (_time.perf_counter() - t_exec) * 1000.0
            # roofline join: the measured device-execute delta against
            # this program's compiler-reported flops/bytes
            progstats.record_exec(prog_kid, exec_ms, fresh=fresh_compile)
            if aux:
                # compact live/overflow: 8 bytes of plan metadata the
                # loud-rerun decision needs host-side. The program is
                # already done executing, so these two scalars ride the
                # result drain — part of the readout's ONE boundary
                # transfer, not a second booked host sync
                live, ovf = (int(x) for x in jax.device_get(
                    (aux["compact_live"], aux["compact_ovf"])
                ))  # lint: transfer-ok(compact overflow check — two scalars riding the result drain)
                GLOBAL.inc("latemat/compact_live_rows", live)
                # measured-live memo (monotone max, keyed by the compact-
                # free program identity): future sizings of this shape
                # never undercut an observed live count
                prev_live = self._compact_memo.get(base_key, 0)
                if live > prev_live:
                    self._compact_memo[base_key] = live
                # live/padded account for the compacted shape: measured
                # live rows against the ladder rung every downstream op
                # ran at (unit-width lanes — the ratio is the signal;
                # the capacity-sized buffers this rung REPLACED never
                # entered the ledger, so this entry is the only place
                # the seam's padding collapse is visible)
                memledger.record_pad("compact", live, compact_cap,
                                     live * 8, compact_cap * 8)
                if ovf:
                    # the bound was forged low — rows past compact_cap
                    # were dropped ON DEVICE. Discard this result and
                    # rerun the statement without the compact (full
                    # capacity), loudly counted. Never serve a truncation.
                    GLOBAL.inc("latemat/compact_overflow_reruns")
                    prev_cap = self.dq_device_capture
                    self.dq_device_capture = capture_device
                    try:
                        redo = self._try_execute_fused(
                            plan0, params, snapshot, _no_compact=True)
                    finally:
                        self.dq_device_capture = prev_cap
                    if redo is None or isinstance(redo, (list, tuple)):
                        raise RuntimeError(
                            "compact overflow rerun declined the fused "
                            "path")
                    return redo
            if capture_device:
                # device-resident spine: hand the stage result back as
                # device arrays by reference — the 4-byte length scalar
                # is the ONLY thing that crosses the link (plan
                # metadata, counted as a device handoff, not a host
                # sync; the program is already done executing)
                from ydb_tpu.ops.device import DeviceStageBlock
                n = int(length)
                dev = F.capture_fused_device(data_stacks, valid_stack, n,
                                             layout_box, out_schema,
                                             out_dicts)
                blk = DeviceStageBlock(dev, n)
                memledger.record_device_handoff(
                    "query/executor.py::fused_capture", blk.live_nbytes())
                return blk
            with self._span("readout-transfer"):
                block = F.fetch_fused_result(data_stacks, valid_stack,
                                             length, layout_box,
                                             out_schema, out_dicts)
            return _apply_offset(block, lo, limit)

        fut = DeviceResultFuture(fetch)
        return fut if defer else fut.result()

    def _fused_fill(self, kind: str, key, builder, capture_args,
                    source: str = "fresh", cache: bool = True,
                    warm_lane: bool = False):
        """Single-flight fused/batched program fill. The miss ladder:
        exec cache (a concurrent filler won) → persistent program store
        (deserialize, `compile_ms ~= 0`, the trace-time `layout_box`/
        `out_schema` replayed from the stored extra) → `builder()` +
        AOT capture (the fresh executable — and its layout extra — is
        serialized back into the store inside `capture`).

        Concurrent fillers of the same (kind, key) dedup on one leader:
        the storm case compiles once and every follower shares the
        leader's `(handle, layout_box, out_schema)` triple. Returns
        `(triple, compiled_here)` — `compiled_here` False on every path
        that skipped the trace+compile (cache, store, follower).

        `cache=False`: return without parking the entry (the batched
        lane caches only after its first successful dispatch, so a
        trace-failing shape never wedges a dead entry in the budget).

        `warm_lane=True` (the compile-ahead worker): a fresh build's
        trace-time gauges land in the WORKER's thread-local window, so
        the leader parks its trace delta in `_trace_debt`; the first
        foreground fill of the same key (warm_lane=False) pops it and
        folds it into the consuming statement's window — EXPLAIN
        ANALYZE / `last_stats.bounds` report the build the statement
        triggered, whichever thread ran it."""
        import threading as _threading
        import time as _time

        from ydb_tpu.ops.xla_exec import (groupby_trace_delta,
                                          groupby_trace_mark)

        def _fill():
            ent = self._fused_cache.get(key)
            if ent is not None:
                progstats.record_hit(getattr(ent[0], "key_id", None))
                return ent, False, 0
            loaded = progstats.store_load(kind, key,
                                          lambda: builder()[0])
            if loaded is not None:
                handle, extra = loaded
                ent = (handle, extra["layout_box"], extra["out_schema"])
                if cache:
                    self._fused_cache[key] = ent
                return ent, False, 0
            mark = groupby_trace_mark() if warm_lane else None
            t_build = _time.perf_counter() if warm_lane else 0.0
            fn, layout_box, out_schema = builder()
            handle = progstats.capture(
                kind, key, fn, capture_args, consult_store=False,
                store_extra={"layout_box": layout_box,
                             "out_schema": out_schema}, source=source)
            ent = (handle, layout_box, out_schema)
            if cache:
                self._fused_cache[key] = ent
            if warm_lane:
                debt = groupby_trace_delta(mark)
                ms = round((_time.perf_counter() - t_build) * 1000.0, 3)
                with self._warm_mu:
                    if debt:
                        self._trace_debt[(kind, key)] = debt
                    self._compile_debt[(kind, key)] = ms
            return ent, True, _threading.get_ident()

        ent, compiled_here, leader_tid = \
            self._sflight.run((kind, key), _fill)
        if not warm_lane:
            self._consume_trace_debt(kind, key)
        # a follower that deduped onto another thread's compile did not
        # itself compile — its dispatch span and exec record stay lean
        return ent, compiled_here and \
            leader_tid == _threading.get_ident()

    def _consume_trace_debt(self, kind: str, key) -> None:
        """Fold a compile-ahead build's parked trace delta into the
        CURRENT thread's window — called from every foreground path
        that can consume a warm-lane-filled entry (the direct cache
        hit and the single-flight fill)."""
        if not self._trace_debt:
            return
        with self._warm_mu:
            debt = self._trace_debt.pop((kind, key), None)
        if debt:
            from ydb_tpu.ops.xla_exec import groupby_trace_fold
            groupby_trace_fold(debt)

    # -- compile-ahead lane ------------------------------------------------

    def compile_ahead(self, plan: QueryPlan, params: dict,
                      snapshot: Snapshot) -> bool:
        """Kick a background fill for this plan's fused program while
        the statement waits in the admission queue (`query/engine.py`
        calls this between planning and `admission.admit`). The warm
        thunk mirrors the synchronous fused setup up to the program key
        and then runs the SAME single-flight fill the dispatch path
        uses — store consult first (a warmed shape deserializes,
        near-free), fresh AOT compile otherwise — so when the statement
        clears admission the executable is ready, or in flight with the
        dispatch deduping onto it.

        Plan-level dedup keeps the lane cheap under repeat traffic: one
        launch per (table, data_version, lift_sig); non-lifted plans
        (no value-free identity) and mesh-distributed plans skip the
        lane. Returns True when a background fill was launched."""
        if not (self.enable_fused and ca_lane.enabled()
                and progstats.enabled()):
            return False
        if self.mesh is not None and self.mesh.devices.size > 1:
            return False
        sig = getattr(plan, "lift_sig", None)
        if sig is None:
            return False
        if getattr(plan, "init_subplans", None):
            # scalar-subquery params are computed at dispatch time; the
            # warm thunk would key on an incomplete param set
            return False
        pipe = plan.pipeline
        try:
            table = self.catalog.table(pipe.scan.table)
        except Exception:              # noqa: BLE001 — lane, not law
            return False
        warm_key = (pipe.scan.table, table.data_version, sig)
        with self._warm_mu:
            if warm_key in self._warm_seen:
                return False
            self._warm_seen.add(warm_key)
        params = dict(params)
        return self._sflight.launch(
            ("warm",) + warm_key,
            lambda: self._fused_warm(plan, params, snapshot))

    def _fused_warm(self, plan: QueryPlan, params: dict,
                    snapshot: Snapshot) -> bool:
        """Background half of the compile-ahead lane: the fused-path
        setup (builds, plan walk, superblock, key derivation) WITHOUT
        dispatch, landing in the same `_fused_fill` the synchronous
        path uses. Declines exactly where that path declines to fuse —
        a plan the dispatch would stream portioned/tiled must not burn
        background compile on a program nobody will run."""
        from ydb_tpu.ops import fused as F
        from ydb_tpu.storage.device_cache import (
            enumerate_scan_sources, estimate_scan_bytes,
        )
        from ydb_tpu.utils.metrics import GLOBAL

        pipe = plan.pipeline
        table = self.catalog.table(pipe.scan.table)
        join_steps = [step for kind, step in pipe.steps if kind == "join"]
        if len(join_steps) > self.fuse_max_joins:
            return False
        builds = self._prepare_builds(pipe, params, snapshot)
        for step, bt in zip(join_steps, builds):
            if isinstance(bt, J.PartitionedBuild) or (
                    not bt.unique and step.kind in ("inner", "left",
                                                    "mark")):
                return False
        (plan, pipe, scan_cols, schema, partial_schema, dicts,
         join_metas, late_scan) = self._fused_plan_setup(plan, builds)
        storage_names = [s for (s, _i) in pipe.scan.columns]
        rename = {s: i for (s, i) in pipe.scan.columns}
        sources, src_ids = enumerate_scan_sources(table, snapshot,
                                                  pipe.scan.prune or None)
        Kb = shape_buckets.bucket_sources(len(sources))
        if not sources or estimate_scan_bytes(sources, storage_names,
                                              pad_to=Kb) \
                > self.fused_scan_budget_bytes:
            return False                 # empty / tiled-class scan
        sb = self.device_cache.superblock(table, storage_names, rename,
                                          snapshot,
                                          pipe.scan.prune or None,
                                          sources, src_ids, pad_to=Kb)
        if sb is None:
            return False
        arrays, valids, lengths, K, CAP, sb_dicts = sb
        sb_valid_names = frozenset(valids.keys())
        dicts.update(sb_dicts)
        sort_params, sort_spec, rank_assigns = self._sort_setup_fused(
            plan, schema, dicts)
        all_params = {**params, **sort_params}
        lift_limit, lim_key = self._lift_limit_setup(plan, all_params)
        builds_sig = tuple(F.build_inputs_sig(bt) for bt in builds)
        base_key = F.fused_cache_key(plan, scan_cols, K, CAP,
                                     sb_valid_names, builds_sig, sort_spec,
                                     rank_assigns,
                                     tuple(sorted(all_params)),
                                     lim_key=lim_key)
        # MUST mirror the dispatch path's compact sizing exactly — a
        # warm on a different capacity would compile a program the
        # dispatch never asks for
        compact_cap = self._compact_sizing(base_key, pipe, builds,
                                           sources, K * CAP)
        compact_prog = None
        key = base_key
        if compact_cap:
            compact_prog = ir.Program([ir.Compact(compact_cap)])
            key = F.fused_cache_key(plan, scan_cols, K, CAP,
                                    sb_valid_names, builds_sig, sort_spec,
                                    rank_assigns,
                                    tuple(sorted(all_params)),
                                    lim_key=lim_key,
                                    compact_cap=compact_cap)
        if key in self._fused_cache:
            return False                 # already live — nothing to warm

        def _builder():
            fn, layout_box = F.build_fused_fn(
                pipe, plan.final_program, scan_cols, K, CAP, sb_valid_names,
                join_metas, rank_assigns, sort_spec, plan.limit, plan.offset,
                tuple(dict.fromkeys(n for (n, _lbl) in plan.output)),
                lift_limit=lift_limit, late_scan=late_scan,
                compact_prog=compact_prog)
            keep = list(dict.fromkeys(n for (n, _lbl) in plan.output))
            out_cols = [c for c in schema.columns if c.name in keep] \
                or list(schema.columns)
            return fn, layout_box, Schema(out_cols)

        dev_params = {k: (jnp.asarray(v) if isinstance(v, np.ndarray)
                          else v) for k, v in all_params.items()}
        build_inputs = [F.build_traced_inputs(bt) for bt in builds]
        self._fused_fill(
            "fused", key, _builder,
            (arrays, valids, lengths, build_inputs, dev_params),
            source="compile_ahead", warm_lane=True)
        # the program is ready before its first dispatch — whether it
        # was compiled here or deserialized from the store
        GLOBAL.inc("prog/compile_ahead_hits")
        return True

    def _sort_setup_fused(self, plan: QueryPlan, schema: Schema,
                          dicts: dict):
        """Rank-LUT sort params against the fused pipeline's final schema
        (mirrors `_sort_setup`, which works from partial-output blocks)."""
        from ydb_tpu.core import dtypes as dt
        sort_params, rank_assigns, spec = {}, [], []
        dicts = {**dicts, **plan.result_dicts}
        for j, sk in enumerate(plan.sort):
            dtype = schema.dtype(sk.name)
            dic = dicts.get(sk.name)
            if dtype.is_string and dic is not None:
                ranks = dic.sort_ranks()
                pname = f"__rank{j}"
                sort_params[pname] = ranks
                rank_col = f"__sortrank{j}"
                rank_assigns.append(ir.Assign(rank_col, ir.call(
                    "take_lut", ir.Col(sk.name),
                    ir.Param(pname, dt.DType(dt.Kind.INT32, False),
                             is_array=True))))
                spec.append((rank_col, sk.ascending, sk.nulls_first))
            else:
                spec.append((sk.name, sk.ascending, sk.nulls_first))
        return sort_params, tuple(spec), rank_assigns

    def _fused_plan_setup(self, plan: QueryPlan, builds: list):
        """Shared front half of the fused paths (single-query and
        batched): one schema walk over the pipeline collecting join
        metas (incl. the LUT-vs-bsearch probe choice per build) and
        landing on the final schema, plus the join-derived group-bound
        rewrite. Returns (plan, pipe, scan_cols, schema, partial_schema,
        dicts, join_metas, late_scan) — plan/pipe possibly rewritten
        (copies; a cached plan is never mutated); `late_scan` is the set
        of scan columns the fused body defers behind a row-position
        column (query/latemat.py), empty when the lever is off."""
        from ydb_tpu.core.dtypes import DType, Kind as _K
        from ydb_tpu.ops import fused as F
        from ydb_tpu.ops.xla_exec import late_mat_enabled
        from ydb_tpu.query import latemat

        late = late_mat_enabled()
        pipe = plan.pipeline
        table = self.catalog.table(pipe.scan.table)
        scan_cols = [Column(i, table.schema.dtype(s))
                     for (s, i) in pipe.scan.columns]

        dicts = {}
        join_metas = []
        bi = 0
        schema = Schema(list(scan_cols))
        if pipe.pre_program is not None:
            schema = ir.infer_schema(pipe.pre_program, schema)
        for kind, step in pipe.steps:
            if kind != "join":
                schema = ir.infer_schema(step, schema)
                continue
            bt = builds[bi]
            bi += 1
            payload_cols = []
            for name in bt.schema.names:
                payload_cols.append(
                    Column(name, bt.schema.dtype(name).with_nullable(True)))
                if name in bt.dictionaries:
                    dicts[name] = bt.dictionaries[name]
            if step.kind == "mark":
                payload_cols.append(Column(step.mark_col or "__mark",
                                           DType(_K.BOOL, False)))
            join_metas.append({
                "probe_key": step.probe_key,
                "kind": step.kind,
                "src_names": tuple(bt.schema.names),
                "payload_names": tuple(bt.schema.names),
                "mark_col": step.mark_col,
                "not_in": step.not_in,
                "payload_cols": payload_cols,
                # sparse key spans have no LUT; float PROBES must not
                # truncate through an integer LUT — both take the
                # unrolled binary search in the trace
                "bsearch": bt.lut is None
                or schema.dtype(step.probe_key).kind in (_K.FLOAT64,
                                                         _K.FLOAT32),
                # late materialization: inner/left payloads ride as a
                # (build row-id, match) pair and gather at first compute
                # reference or the bound-sized tail; semi/anti/mark
                # produce no payloads to defer
                "late": late and step.kind in ("inner", "left")
                and bool(bt.schema.names),
                "row_col": f"__lmr{bi - 1}",
                "found_col": f"__lmf{bi - 1}",
            })
            schema = F.apply_join_schema(schema, payload_cols)
        if pipe.partial is not None:
            schema = ir.infer_schema(pipe.partial, schema)
        partial_schema = schema            # tile-output schema (pre-final)
        if plan.final_program is not None:
            schema = ir.infer_schema(plan.final_program, schema)

        # join-derived group-bound: when every group key is pinned by an
        # inner/semi join's build side, ngroups ≤ build rows — stamp the
        # sorted group-by with the proven bound so per-group gathers run
        # at output cardinality (the q3/q9/q13 late-materialization win)
        plan, pipe = self._bounded_groupby_rewrite(plan, builds, join_metas)
        late_scan = latemat.deferrable_scan(
            pipe, [c.name for c in scan_cols]) if late else frozenset()
        return plan, pipe, scan_cols, schema, partial_schema, dicts, \
            join_metas, late_scan

    @staticmethod
    def _lift_limit_setup(plan: QueryPlan, all_params=None,
                          force: bool = False):
        """(lift_limit, lim_key) for a fused compile: lifted plans with a
        LIMIT pass limit+offset as the __lim2 device input and key the
        program on its capacity bucket; everything else keeps the baked
        constants (byte-identical compile key to the pre-lift path).

        `force`: the batched lane ALWAYS lifts a LIMIT — its shape sig
        groups on the bucket, so members whose only difference is the
        LIMIT/OFFSET value must still clamp per member (a zero-literal
        `limit 3` and `limit 5` coalesce; baking the leader's value
        would hand every member the leader's row count).
        `all_params`: when given, the leader's __lim2 is injected (the
        batched lane instead injects per member)."""
        from ydb_tpu.ops.fused import LIMIT_PARAM
        if plan.limit is None or not (
                force or getattr(plan, "lift_names", ())):
            return False, None
        lim2 = plan.limit + (plan.offset or 0)
        if all_params is not None:
            all_params[LIMIT_PARAM] = np.int32(lim2)
        return True, ("limB", bucket_capacity(lim2, minimum=128))

    def _compact_sizing(self, base_key, pipe, builds, sources,
                        cap0: int) -> Optional[int]:
        """Ladder-quantized capacity the fused pipeline compacts to
        after its join steps, or None when compaction isn't worth a
        shape (`ir.Compact` placement: `ops/fused._fused_body`).

        The estimate is sizing-quality, not correctness-bearing — the
        device overflow flag catches every underestimate and the
        statement reruns at full capacity (loud). Components:

        * live scan rows, tightened by the CBO's post-local-predicate
          estimate (`ScanSpec.est_rows`) when present;
        * per INNER join against a filtered build, a uniform-FK
          selectivity `min(1, build_rows / base_table_rows)` — the
          Selinger containment assumption (q7's nation-filtered
          supplier ~2/25);
        * per SEMI join whose build key is declared-UNIQUE, coverage
          `min(1, build_rows / key_domain)`: a unique build holds one
          row per covered key, so its cardinality IS the covered-key
          count and the ratio is the uniform-FK survival probability
          (q9's part-name semi keeps ~1/17 of lineitem; q18's
          300-quantity order set keeps ~60 of 1.5M orders). Non-unique
          semi builds deliberately do NOT reduce — there the probe
          survives on key COVERAGE, not build cardinality, and under FK
          fanout even a heavily filtered build covers most probe keys
          (the q4 shape before its subplan build deduped: 63% of
          lineitem rows covered ~98% of orders; applying the raw
          cardinality ratio forged the bound low and burned overflow
          reruns). `_semi_key_domain` picks the denominator: the probe
          key's own table when the probe key is its declared PK (q18's
          o_orderkey → orders), else the build's base table (q9's
          l_partkey probe → part);
        * the measured-live memo (monotone max per compact-free key):
          an observed live count is never undercut again;
        * 25% headroom, floor 1024, quantized UP on the fine segment
          ladder (`progstore/buckets.bucket_segment`) so data growth
          recompiles at ≤1.25x-ratio rungs, not per row count;
        * STICKY per compact-free key: once a capacity is chosen, data
          growth that still fits inside it reuses the compiled program
          (the headroom absorbs within-bucket growth — the shape-bucket
          churn pin stays intact); the capacity re-derives only when
          the estimate outgrows it.

        Only capacities under cap0/2 are worth the reshape."""
        from ydb_tpu.ops.xla_exec import late_mat_enabled
        if not late_mat_enabled():
            return None
        live = float(sum(b.length for b in sources)) if sources else 0.0
        if pipe.scan.est_rows >= 0:
            live = min(live, float(pipe.scan.est_rows))
        est = live
        bi = 0
        for kind, step in pipe.steps:
            if kind != "join":
                continue
            bt = builds[bi]
            bi += 1
            if step.not_in:
                continue
            if step.kind == "inner":
                base = self._build_base_rows(step)
                if base > 0:
                    est *= min(1.0, float(int(bt.n)) / base)
            elif step.kind == "left_semi":
                dom = self._semi_key_domain(step)
                if dom > 0:
                    est *= min(1.0, float(int(bt.n)) / dom)
        if pipe.out_bound and not (
                pipe.partial is not None
                and any(isinstance(c, ir.GroupBy)
                        for c in pipe.partial.commands)):
            # a pipeline bound proven at plan time bounds the PRE-partial
            # rows only when no partial group-by sits between
            est = min(est, float(pipe.out_bound))
        est = max(est, float(self._compact_memo.get(base_key, 0)))
        prev = self._compact_caps.get(base_key)
        if prev is not None and est <= prev:
            return prev
        cand = shape_buckets.bucket_segment(
            max(int(est * 1.25) + 1, 1024))
        if cand >= cap0 // 2:
            self._compact_caps.pop(base_key, None)
            return None
        self._compact_caps[base_key] = cand
        return cand

    def _build_base_rows(self, step: JoinStep) -> int:
        """Unfiltered base-table row count of a join's build side (the
        FK-selectivity denominator); 0 = unknown (no reduction
        assumed). The planner stamps `est_rows` POST-predicate; the
        denominator needs the unfiltered table, so resolve through the
        catalog like the bounds lattice does."""
        build = step.build
        pipe = getattr(build, "pipeline", build)   # QueryPlan | Pipeline
        scan = getattr(pipe, "scan", None)
        if scan is None:
            return 0
        try:
            tbl = self.catalog.table(scan.table)
        except Exception:              # noqa: BLE001 — sizing, not law
            return 0
        return int(getattr(tbl, "num_rows", 0))

    def _semi_key_domain(self, step: JoinStep) -> int:
        """Key-domain denominator for a semi join's coverage estimate,
        or 0 when the build key isn't declared-unique (no reduction —
        see `_compact_sizing`). A probe key that is itself the declared
        single-column PK of its aliased table names the domain exactly
        (q18: o_orderkey → orders rows). A plain-pipeline build whose
        scan PK is the key uses its base table (q9: part filter — every
        base row is one distinct key). A SUBPLAN build probed by a
        non-PK key gets no domain: its scan table counts ROWS, not
        keys, and under FK fanout that denominator forges the estimate
        low (q21's correlated-exists orderkey set over lineitem —
        4 rows per key → a 4x understatement and an overflow rerun)."""
        from ydb_tpu.query import bounds
        from ydb_tpu.query.plan import QueryPlan
        if not bounds._build_key_unique_declared(step, self.catalog):
            return 0
        if "." in step.probe_key:
            alias, col = step.probe_key.split(".", 1)
            try:
                tbl = self.catalog.table(alias)
                if list(tbl.key_columns) == [col]:
                    return int(getattr(tbl, "num_rows", 0))
            except Exception:          # noqa: BLE001 — sizing, not law
                pass
        if not isinstance(step.build, QueryPlan):
            return self._build_base_rows(step)
        return 0

    # -- multi-query batched dispatch --------------------------------------

    def execute_fused_batched(self, plan: QueryPlan, members: list,
                              snapshot: Snapshot):
        """ONE stacked fused execution for a batch of same-shape queries
        (the inference-serving lane, `query/batch_lane.py`): the shared
        scan superblock and join builds broadcast, each member's lifted
        literals stack along a leading batch axis, and a single vmapped
        executable (`ops/fused.build_fused_batched_fn`) serves the whole
        batch — one dispatch + one device→host readout instead of B.

        `plan`: the leader's plan with scan pruning STRIPPED (pruning is
        literal-dependent and cannot partition a shared execution; the
        lane already verified every member sees identical source sets).
        `members`: [(member_plan, member_params)] — same `lift_sig`,
        verified by the lane. Returns [HostBlock] projected per member,
        or None when this shape cannot batch (caller falls back to
        per-member execution)."""
        from ydb_tpu.ops import fused as F
        from ydb_tpu.storage.device_cache import (
            enumerate_scan_sources, estimate_scan_bytes,
        )
        from ydb_tpu.utils.metrics import GLOBAL

        pipe = plan.pipeline
        table = self.catalog.table(pipe.scan.table)
        join_steps = [step for kind, step in pipe.steps if kind == "join"]
        if len(join_steps) > self.fuse_max_joins:
            return None
        params0 = dict(members[0][1])
        with self._span("join-builds", n=len(join_steps)):
            builds = self._prepare_builds(pipe, params0, snapshot)
        for step, bt in zip(join_steps, builds):
            if isinstance(bt, J.PartitionedBuild) or (
                    not bt.unique and step.kind in ("inner", "left",
                                                    "mark")):
                return None
        (plan, pipe, scan_cols, schema, partial_schema, dicts,
         join_metas, late_scan) = self._fused_plan_setup(plan, builds)

        storage_names = [s for (s, _i) in pipe.scan.columns]
        rename = {s: i for (s, i) in pipe.scan.columns}
        sources, src_ids = enumerate_scan_sources(table, snapshot, None)
        Kb = shape_buckets.bucket_sources(len(sources))
        if not sources or estimate_scan_bytes(sources, storage_names,
                                              pad_to=Kb) \
                > self.fused_scan_budget_bytes:
            return None                  # empty / tiled-class scan
        with self._span("superblock-upload"):
            sb = self.device_cache.superblock(table, storage_names, rename,
                                              snapshot, None, sources,
                                              src_ids, pad_to=Kb)
        if sb is None:
            return None
        arrays, valids, lengths, K, CAP, sb_dicts = sb
        sb_valid_names = frozenset(valids.keys())
        dicts.update(sb_dicts)
        from ydb_tpu.utils import memledger
        memledger.record_padded_buffers(
            "superblock", "superblock",
            int(sum(b.length for b in sources)), K * CAP, arrays, valids)

        sort_params, sort_spec, rank_assigns = self._sort_setup_fused(
            plan, schema, dicts)

        # per-member param dicts (sort params are batch-invariant; a
        # LIMIT always lifts here — see _lift_limit_setup — so each
        # member clamps to ITS OWN limit+offset, not the leader's)
        lift_limit, lim_key = self._lift_limit_setup(plan, force=True)
        mem_params = []
        for (mp, prms) in members:
            p = {**prms, **sort_params}
            if lift_limit:
                p[F.LIMIT_PARAM] = np.int32(mp.limit + (mp.offset or 0))
            mem_params.append(p)
        names = sorted(mem_params[0])
        for p in mem_params[1:]:
            if sorted(p) != names:
                return None              # shape drift — lane sig was stale

        # stack only the params whose values actually differ across the
        # batch; batch-invariant ones (rank LUTs, shared pool arrays)
        # broadcast via in_axes=None instead of B device copies
        axes, stacked = {}, {}
        for n in names:
            vals = [p[n] for p in mem_params]
            if all(_param_values_equal(vals[0], v) for v in vals[1:]):
                axes[n] = None
                stacked[n] = vals[0]
            else:
                arrs = [np.asarray(v) for v in vals]
                if any(a.shape != arrs[0].shape or a.dtype != arrs[0].dtype
                       for a in arrs[1:]):
                    # array params whose SHAPES vary with the literal
                    # (integer IN lists) — Param fingerprints carry no
                    # shape, so the sig can't split these; decline
                    return None
                axes[n] = 0
                stacked[n] = np.stack(arrs)
        B = len(members)
        mapped = tuple(n for n in names if axes[n] == 0)
        if mapped:
            Bb = 1
            while Bb < B:
                Bb *= 2                  # batch-size buckets: one
            #                              executable per power-of-two size
            if Bb > B:
                pad = Bb - B             # pad by repeating the last member
                for n in mapped:
                    stacked[n] = np.concatenate(
                        [stacked[n]] + [stacked[n][-1:]] * pad)
            member_rows = list(range(B))
        else:
            # every member identical (a same-text storm): one execution,
            # every member unpacks row 0
            Bb = 1
            member_rows = [0] * B

        builds_sig = tuple(F.build_inputs_sig(bt) for bt in builds)
        base_key = F.fused_cache_key(plan, scan_cols, K, CAP,
                                     sb_valid_names, builds_sig, sort_spec,
                                     rank_assigns, tuple(names),
                                     lim_key=lim_key)
        key = ("batched", base_key, Bb, mapped)
        keep = tuple(dict.fromkeys(n for (n, _lbl) in plan.output))
        # observability levers cannot stale a program: they choose how
        # the identical trace is dispatched/recorded, not what it computes
        # lint: allow-cache-key(progstats/memledger/critpath observe only)
        cached = self._fused_cache.get(key)
        fresh_compile = cached is None
        if cached is not None:
            fn, layout_box, out_schema = cached
            progstats.record_hit(getattr(fn, "key_id", None))
        else:
            fn = layout_box = out_schema = None

        def _builder():
            bfn, box = F.build_fused_batched_fn(
                pipe, plan.final_program, scan_cols, K, CAP, sb_valid_names,
                join_metas, rank_assigns, sort_spec, plan.limit,
                plan.offset, keep, dict(axes), Bb, lift_limit=lift_limit,
                late_scan=late_scan)
            out_cols = [c for c in schema.columns if c.name in keep] \
                or list(schema.columns)
            return bfn, box, Schema(out_cols)

        dev_params = {k: (jnp.asarray(v) if isinstance(v, np.ndarray)
                          else v) for k, v in stacked.items()}
        build_inputs = [F.build_traced_inputs(bt) for bt in builds]
        try:
            with self._span("device-dispatch-batched", k=K, cap=CAP,
                            b=Bb) as dsp, \
                    _xla_scope("device-dispatch-batched"):
                import time as _time
                t_disp = _time.perf_counter()
                if fn is None:
                    # fill for the stacked program too: store consult →
                    # AOT capture, single-flight deduped (compile inside
                    # the dispatch span; a trace error re-raises at the
                    # call below and the lane falls back per-member
                    # exactly as before). cache=False — the entry parks
                    # only after the first successful dispatch.
                    (fn, layout_box, out_schema), fresh_compile = \
                        self._fused_fill(
                            "batched", key, _builder,
                            (arrays, valids, lengths, build_inputs,
                             dev_params), cache=False)
                # no compact in the batched lane (aux is always empty
                # — `_fused_plan_setup` never hands it a compact_prog)
                data_stacks, valid_stack, length, _aux = fn(
                    arrays, valids, lengths, build_inputs, dev_params)
                if fresh_compile:
                    dsp.attrs["compile_ms"] = round(
                        (_time.perf_counter() - t_disp) * 1000.0, 3)
        except Exception:                # noqa: BLE001 — lane, not law
            # a shape the vmapped trace can't batch (or a compile-side
            # failure): fall back to per-member execution rather than
            # failing B clients on an optimization
            GLOBAL.inc("batch/trace_errors")
            return None
        if cached is None:
            # cache only after the first successful dispatch, so a
            # trace-failing shape never parks a dead entry in the budget
            self._fused_cache[key] = (fn, layout_box, out_schema)
        # batch-lane padding: the power-of-two axis bucket materializes
        # Bb member slots of every stacked output for B live members
        # (same-text dedup maps all members to one row — min() keeps the
        # live share honest there)
        memledger.record_padded_buffers(
            "batch_lane", "result_buffers", min(B, Bb), Bb,
            (data_stacks, valid_stack))

        out_dicts = {n2: d for n2, d in dicts.items() if out_schema.has(n2)}
        out_dicts.update({n2: d for n2, d in plan.result_dicts.items()
                          if out_schema.has(n2)})
        with self._span("device-execute"), _xla_scope("device-execute"):
            import time as _time
            t_exec = _time.perf_counter()
            jax.block_until_ready((data_stacks, valid_stack, length))
            exec_ms = (_time.perf_counter() - t_exec) * 1000.0
        progstats.record_exec(getattr(fn, "key_id", None), exec_ms,
                              fresh=fresh_compile)
        with self._span("readout-transfer", b=len(members)):
            blocks = F.fetch_fused_batch(data_stacks, valid_stack, length,
                                         layout_box, out_schema, out_dicts,
                                         member_rows)
        out = []
        for (mp, _prms), blk in zip(members, blocks):
            blk = _apply_offset(blk, mp.offset or 0, mp.limit)
            out.append(self._project_output(blk, mp.output))
        return out

    def _bounded_groupby_rewrite(self, plan: QueryPlan, builds: list,
                                 join_metas: list):
        """The executor half of the bounds lattice — two rewrites of the
        partial (and matching merge) GroupBy, both from RUNTIME-VERIFIED
        join structure (a false bound drops groups, a false dependency
        merges them — only guaranteed sources qualify):

        * PROVEN `out_bound`: after an INNER probe against a unique-keyed
          build, surviving probe keys are a subset of the build's keys,
          so a group-by whose keys are all drawn from {probe key} ∪ build
          payload has ngroups ≤ build rows (semi joins bound the probe
          key the same way without payloads). Bucket-quantized so data
          growth recompiles at capacity-bucket granularity.

        * CARRY keys (`YDB_TPU_BOUNDS`): grouping columns functionally
          determined by a smaller determinant stop participating in the
          group-by sort identity — q10's 7-key (16-sort-operand) group-by
          collapses to its 1-key determinant, the keys materializing from
          group leaders like everything else late-materialized. The
          dependency is verified, never assumed: the determinant is the
          join's own key (unique ⇒ determines every payload column), or
          a payload column whose distinct count MEASURED on the
          materialized build equals the full key tuple's (`fd_block`
          retained by `ops/join.build` for exactly this check).

        Names reassigned AFTER the bounding join (later program Assigns,
        later join payloads/mark columns, partial-program Assigns) void
        the guarantee for that join and are excluded. Returns the
        (possibly rewritten) plan and its pipeline; the rewrite copies —
        cached plans are never mutated."""
        import dataclasses as _dc

        from ydb_tpu.query.bounds import bounds_enabled
        from ydb_tpu.utils.metrics import GLOBAL
        pipe = plan.pipeline
        if pipe.partial is None or not pipe.partial.commands:
            return plan, pipe
        gb = pipe.partial.commands[-1]
        if not isinstance(gb, ir.GroupBy) or not gb.keys:
            return plan, pipe
        keys = set(gb.keys)
        partial_assigned = {c.name for c in pipe.partial.commands[:-1]
                            if isinstance(c, ir.Assign)}
        best = None
        cands = []     # (step, bt, allowed, has_payload)
        bi = 0
        for si, (kind, step) in enumerate(pipe.steps):
            if kind != "join":
                continue
            bt = builds[bi]
            meta = join_metas[bi]
            bi += 1
            if step.not_in:
                continue
            if step.kind == "inner" and getattr(bt, "unique", False):
                allowed = {step.probe_key} | set(meta["payload_names"])
                has_payload = True
            elif step.kind == "left_semi":
                allowed = {step.probe_key}
                has_payload = False
            else:
                continue
            # names invalidated downstream of THIS join
            later = set(partial_assigned)
            bj = bi
            for sj in range(si + 1, len(pipe.steps)):
                k2, s2 = pipe.steps[sj]
                if k2 == "join":
                    later |= set(join_metas[bj]["payload_names"])
                    if s2.kind == "mark":
                        later.add(s2.mark_col or "__mark")
                    bj += 1
                else:
                    later |= {c.name for c in s2.commands
                              if isinstance(c, ir.Assign)}
            allowed -= later
            cands.append((step, bt, allowed, has_payload))
            if keys <= allowed:
                n = max(int(bt.n), 1)
                best = n if best is None else min(best, n)

        # -- carry reduction: per bounding join, find one determinant for
        # the keys it contributes and demote the rest to carried keys
        carry: list = []
        claimed: set = set()
        if bounds_enabled():
            for (step, bt, allowed, has_payload) in cands:
                if not has_payload:
                    continue
                gj = [k for k in gb.keys
                      if k in allowed and k not in claimed
                      and k not in carry]
                if len(gj) < 2:
                    continue
                det, measured = self._fd_determinant(step, bt, gj)
                if det is None:
                    continue
                claimed.add(det)
                for k in gj:
                    if k != det:
                        carry.append(k)
                if measured is not None and keys <= allowed:
                    # the measured distinct count of the FULL key tuple
                    # is an exact ngroups bound for this execution —
                    # tighter than build rows
                    best = measured if best is None \
                        else min(best, measured)

        bound = gb.out_bound
        if best is not None:
            cand = bucket_capacity(max(best, 1), minimum=128)
            rows = max(int(getattr(self.catalog.table(pipe.scan.table),
                                   "num_rows", 0)), 1)
            if cand < bucket_capacity(rows) \
                    and (not bound or int(bound) > cand):
                # a planner domain-product bound may be far looser than
                # the join bound (10^9-key-product vs an 8k-row build) —
                # keep the tighter of the two
                bound = cand
        if bound == gb.out_bound and not carry:
            return plan, pipe

        kept = tuple(k for k in gb.keys if k not in carry)
        domains = gb.key_domains
        if carry and domains and len(domains) == len(gb.keys):
            domains = tuple(d for k, d in zip(gb.keys, domains)
                            if k not in carry)
        elif carry:
            domains = ()
        new_carry = tuple(gb.carry_keys) + tuple(carry)
        gb2 = _dc.replace(gb, keys=kept, key_domains=domains,
                          out_bound=bound, carry_keys=new_carry)
        pipe = _dc.replace(pipe, partial=ir.Program(
            list(pipe.partial.commands[:-1]) + [gb2]))
        fp = plan.final_program
        if fp is not None and fp.commands \
                and isinstance(fp.commands[0], ir.GroupBy) \
                and fp.commands[0].keys == gb.keys:
            # the merge GroupBy sees the union of partials over the SAME
            # keys — the bound and the carry set transfer verbatim
            fgb0 = fp.commands[0]
            fgb = _dc.replace(
                fgb0, keys=kept, key_domains=domains,
                carry_keys=tuple(fgb0.carry_keys) + tuple(carry),
                out_bound=bound if (not fgb0.out_bound
                                    or (bound and int(fgb0.out_bound)
                                        > int(bound)))
                else fgb0.out_bound)
            fp = ir.Program([fgb] + list(fp.commands[1:]))
        plan = _dc.replace(plan, pipeline=pipe, final_program=fp)
        GLOBAL.inc("groupby/join_bounded_plans")
        if carry:
            GLOBAL.inc("bounds/carry_rewrites")
        return plan, pipe

    def _fd_determinant(self, step: JoinStep, bt, gj: list):
        """One grouping column that provably determines all of `gj`
        (keys drawn from this unique-keyed build's probe/payload).
        Returns (determinant | None, measured distinct count | None).

        Trivial case: the join key itself is among the keys — a unique
        build key determines every payload column by construction
        (probe == build key on surviving inner rows). Otherwise each
        candidate is VERIFIED on the materialized build block: det → gj
        holds on this dataset iff distinct(det) == distinct(gj-tuple)
        (det ⊆ gj, so equality forces a bijection)."""
        from ydb_tpu.query.bounds import dataset_distinct
        from ydb_tpu.utils.metrics import GLOBAL
        if step.probe_key in gj:
            return step.probe_key, None
        if step.build_key in gj:
            return step.build_key, None
        fdb = getattr(bt, "fd_block", None)
        if fdb is None:
            return None, None
        # map probe-side key names onto build-block columns (the probe
        # key reads the build key's values on surviving inner rows)
        mcols = [step.build_key if k == step.probe_key else k for k in gj]
        if any(c not in fdb.columns for c in mcols):
            return None, None
        memo = getattr(bt, "fd_memo", None)
        if memo is None:
            memo = bt.fd_memo = {}

        def distinct(cols: tuple) -> int:
            got = memo.get(cols)
            if got is None:
                got = memo[cols] = dataset_distinct(fdb, list(cols))
            return got

        GLOBAL.inc("bounds/fd_checks")
        total = distinct(tuple(sorted(mcols)))
        # candidates ordered smallest-encoding-first: a narrow int key
        # beats a wide string code as the surviving sort operand
        order = sorted(zip(gj, mcols),
                       key=lambda km: (fdb.columns[km[1]].data.itemsize,
                                       km[0]))
        for (k, m) in order:
            if distinct((m,)) == total:
                GLOBAL.inc("bounds/fd_verified")
                return k, total
        return None, None

    # -- tiled fused path (scan > HBM budget) ------------------------------

    def _execute_fused_tiled(self, plan: QueryPlan, params: dict, pipe,
                             sources: list, scan_cols: list, builds: list,
                             join_metas: list, build_dicts: dict,
                             partial_schema: Schema):
        """Stream a scan too large for HBM through fixed-size tiles: each
        tile is K_tile stacked sources run through ONE fused
        scan→filter→join→partial dispatch (`ops/fused.build_tile_fn`),
        with two tiles in flight (upload overlaps compute). Partials
        either stay device-resident for the normal finalize, spill to
        host-DRAM key-hash partitions for a per-partition merge
        (`ops/spill.py` — the WideCombiner InMemory→Spilling→
        ProcessSpilled analog, `mkql_wide_combine.cpp:338-600`), or, for
        non-aggregating plans, union host-side with per-tile top-k
        pre-cuts (DqCnMerge-style).

        Returns ("fused-tiled[...]" , HostBlock)."""
        import dataclasses

        from ydb_tpu.ops import fused as F
        from ydb_tpu.ops import spill as SP
        from ydb_tpu.ops.xla_exec import _SCATTER_MAX_BUCKETS
        from ydb_tpu.utils.metrics import GLOBAL

        CAP = max(bucket_capacity(max(b.length, 1)) for b in sources)
        row_bytes = 0
        sb_valid_names = set()
        tile_dicts = dict(build_dicts)
        for (s, internal) in pipe.scan.columns:
            cd0 = sources[0].columns[s]
            row_bytes += cd0.data.itemsize
            if any(b.columns[s].valid is not None for b in sources):
                sb_valid_names.add(internal)
                row_bytes += 1
            if cd0.dictionary is not None:
                tile_dicts[internal] = cd0.dictionary
        K_tile = max(1, int(self.tile_budget_bytes // (CAP * row_bytes)))
        K_tile = min(K_tile, len(sources))
        n_tiles = (len(sources) + K_tile - 1) // K_tile
        tile_cap = K_tile * CAP
        sb_valid_names = frozenset(sb_valid_names)

        # static tile-output capacity: bounded-domain partial group-bys
        # compact to their bucket count; everything else stays tile-sized
        tile_out_cap = tile_cap
        last = pipe.partial.commands[-1] \
            if pipe.partial is not None and pipe.partial.commands else None
        if isinstance(last, ir.GroupBy):
            if not last.keys:
                tile_out_cap = 1
            elif last.key_domains and all(d > 0 for d in last.key_domains):
                nb = 1
                for d in last.key_domains:
                    nb *= d + 1
                if nb + 1 <= _SCATTER_MAX_BUCKETS:
                    tile_out_cap = bucket_capacity(nb, minimum=128)
            if last.out_bound:
                # proven ngroups bound (join-derived or an out-of-scatter-
                # range domain product): the sorted lowering emits its
                # per-group outputs at this bucket, so the partials really
                # are this small — don't let a tile-cap-sized estimate
                # trigger a needless host-DRAM spill
                tile_out_cap = min(
                    tile_out_cap,
                    bucket_capacity(max(int(last.out_bound), 1),
                                    minimum=128))
        prow = sum(np.dtype(c.dtype.np).itemsize + 1
                   for c in partial_schema.columns)
        est_partial = n_tiles * min(tile_out_cap, tile_cap) * prow

        fp = plan.final_program
        merge_gb = fp.commands[0] if fp is not None and fp.commands \
            and isinstance(fp.commands[0], ir.GroupBy) else None
        spill = (merge_gb is not None and merge_gb.keys
                 and est_partial > self.merge_budget_bytes)
        union = merge_gb is None and est_partial > self.merge_budget_bytes

        builds_sig = tuple(F.build_inputs_sig(bt) for bt in builds)
        key = F.tile_cache_key(pipe, scan_cols, K_tile, CAP, sb_valid_names,
                               builds_sig, tuple(sorted(params)))
        fn = self._fused_cache.get(key)
        if fn is None:
            fn = F.build_tile_fn(pipe, scan_cols, K_tile, CAP,
                                 sb_valid_names, join_metas)
            self._fused_cache[key] = fn
        build_inputs = [F.build_traced_inputs(bt) for bt in builds]
        dev_params = {k: (jnp.asarray(v) if isinstance(v, np.ndarray) else v)
                      for k, v in params.items()}
        out_dicts = {n: d for n, d in tile_dicts.items()
                     if partial_schema.has(n)}

        GLOBAL.inc("executor/tiled_queries")
        # the tile stacks + resident partials live OUTSIDE the cache's
        # accounting: make room so warm cache + streaming don't OOM HBM
        self.device_cache.reserve(2 * self.tile_budget_bytes
                                  + self.merge_budget_bytes)
        store = None
        if spill:
            P = 1
            while est_partial / P > self.merge_budget_bytes and P < 256:
                P *= 2
            store = SP.PartitionStore(partial_schema, list(merge_gb.keys),
                                      P, out_dicts)

        # union mode: per-tile finalize plans (top-k pre-cut when the
        # query sort-limits, plain program application otherwise)
        lim = None if plan.limit is None else plan.limit + (plan.offset or 0)
        topk = bool(plan.sort) and lim is not None and lim <= (1 << 17)
        out_names = {n for (n, _lbl) in plan.output}
        extra = [(sk.name, sk.name) for sk in plan.sort
                 if sk.name not in out_names]
        if union:
            if topk:
                plan_tile = dataclasses.replace(
                    plan, offset=None, limit=lim, output=plan.output + extra)
            else:
                plan_tile = dataclasses.replace(
                    plan, sort=[], limit=None, offset=None,
                    output=plan.output + extra)

        partials, unions = [], []
        prev = None
        with self._span("tiled-scan", tiles=n_tiles, k_tile=K_tile,
                        spill=bool(spill), union=bool(union)):
            for t in range(n_tiles):
                tile_sources = sources[t * K_tile:(t + 1) * K_tile]
                sb, sbv, lengths = self._stack_tile(
                    tile_sources, pipe.scan.columns, K_tile, CAP,
                    sb_valid_names)
                out_d, out_v, length = fn(sb, sbv, lengths, build_inputs,
                                          dev_params)
                out_d = {n: out_d[n] for n in partial_schema.names}
                out_v = {n: v for n, v in out_v.items()
                         if partial_schema.has(n)}
                cap_t = (next(iter(out_d.values())).shape[0]
                         if out_d else tile_cap)
                dblock = DeviceBlock(partial_schema, out_d, out_v, length,
                                     cap_t, out_dicts)
                if spill:
                    store.feed(dblock)       # syncs → natural backpressure
                elif union:
                    unions.append(self._finalize(plan_tile, [dblock],
                                                 params))
                else:
                    partials.append(dblock)
                    if prev is not None:
                        jax.block_until_ready(prev)
                    prev = out_d

        if spill:
            GLOBAL.inc("executor/spilled_rows", store.spilled_rows)
            GLOBAL.inc("executor/spilled_bytes", store.spilled_bytes)
            return ("fused-tiled-spill",
                    self._merge_spilled(plan, store, params))
        if union:
            u = HostBlock.concat(unions) if len(unions) > 1 else unions[0]
            if topk:
                plan_merge = dataclasses.replace(
                    plan, final_program=None, output=plan.output + extra)
                block = self._finalize(plan_merge, [to_device(u)], params)
            else:
                block = SP.host_sort_limit(
                    u, plan.sort, plan.limit, plan.offset,
                    {**out_dicts, **plan.result_dicts})
            return ("fused-tiled-union", block)
        return ("fused-tiled", self._finalize(plan, partials, params))

    def _stack_tile(self, tile_sources: list, scan_columns: list,
                    K_tile: int, CAP: int, sb_valid_names: frozenset):
        """Host-stack one tile of sources into (K_tile, CAP) arrays and
        upload (async H2D). Short tiles pad with zero-length sources so
        every tile shares one compiled program."""
        lengths = np.zeros(K_tile, np.int32)
        for k, b in enumerate(tile_sources):
            lengths[k] = b.length
        arrays, valids = {}, {}
        for (s, internal) in scan_columns:
            dtype = tile_sources[0].columns[s].data.dtype
            stack = np.zeros((K_tile, CAP), dtype=dtype)
            vstack = np.zeros((K_tile, CAP), np.bool_) \
                if internal in sb_valid_names else None
            for k, b in enumerate(tile_sources):
                cd = b.columns[s]
                stack[k, :b.length] = cd.data
                if vstack is not None:
                    vstack[k, :b.length] = (cd.valid if cd.valid is not None
                                            else True)
            arrays[internal] = jnp.asarray(stack)
            if vstack is not None:
                valids[internal] = jnp.asarray(vstack)
        return arrays, valids, jnp.asarray(lengths)

    def _merge_spilled(self, plan: QueryPlan, store, params: dict):
        """ProcessSpilled: per key-hash partition, concat the spilled
        pieces, run the merge group-by + rest of the final program on
        device, then combine partitions host-side (disjoint key sets) and
        apply ORDER BY / LIMIT on the host."""
        import dataclasses

        from ydb_tpu.ops import spill as SP

        out_names = {n for (n, _lbl) in plan.output}
        extra = [(sk.name, sk.name) for sk in plan.sort
                 if sk.name not in out_names]
        plan_p = dataclasses.replace(plan, sort=[], limit=None, offset=None,
                                     output=plan.output + extra)
        outs = []
        with self._span("spill-merge", parts=store.nparts):
            for p in range(store.nparts):
                hb = store.partition(p)
                if hb.length == 0 and outs:
                    continue
                outs.append(self._finalize(plan_p, [to_device(hb)], params))
        union = HostBlock.concat(outs) if len(outs) > 1 else outs[0]
        return SP.host_sort_limit(
            union, plan.sort, plan.limit, plan.offset,
            {**store.dictionaries, **plan.result_dicts})

    # -- distributed (mesh) path -------------------------------------------

    def _can_distribute(self, plan: QueryPlan) -> bool:
        """Distributable = two-phase aggregation shape: the pipeline ends in
        a partial GroupBy and the final program starts with the merge
        GroupBy (hash-shuffle boundary sits between the two)."""
        pipe = plan.pipeline
        if pipe.partial is None or not pipe.partial.commands:
            return False
        if not isinstance(pipe.partial.commands[-1], ir.GroupBy):
            return False
        fp = plan.final_program
        return (fp is not None and fp.commands
                and isinstance(fp.commands[0], ir.GroupBy))

    def _can_distribute_map(self, plan: QueryPlan,
                            snapshot: Snapshot) -> bool:
        """Map-style distribution (the DqCnMap/UnionAll connection): the
        pipeline has no aggregation boundary — scan/filter/join work
        spreads across devices and per-device results union for the final
        stage. Needs >1 scan source to be worth a fan-out."""
        pipe = plan.pipeline
        if pipe.partial is not None and any(
                isinstance(c, ir.GroupBy) for c in pipe.partial.commands):
            return False
        if plan.final_program is not None and any(
                isinstance(c, ir.GroupBy)
                for c in plan.final_program.commands):
            return False
        return self._scan_source_count(plan, snapshot) > 1

    def _scan_source_count(self, plan: QueryPlan, snapshot: Snapshot) -> int:
        pipe = plan.pipeline
        table = self.catalog.table(pipe.scan.table)
        return sum(len(p) + len(e)
                   for (p, e) in (s.scan_sources(snapshot,
                                                 pipe.scan.prune or None)
                                  for s in table.shards))

    def _execute_distributed_map(self, plan: QueryPlan, params: dict,
                                 snapshot: Snapshot) -> HostBlock:
        """Per-device pipelines (scan → filter → joins), results unioned
        host-side, final stage (exprs/sort/limit) single-device — the
        UnionAll-connection analog for non-aggregating queries.

        Guarded by `_can_distribute_map` (>1 scan source), so at least two
        per-device results always arrive."""
        nsrc = self._scan_source_count(plan, snapshot)
        # no point replicating builds onto devices that get no blocks
        devs = list(self.mesh.devices.flat)[:max(2, min(
            self.mesh.devices.size, nsrc))]
        builds = self._prepare_builds(plan.pipeline, params, snapshot)
        builds_by_dev = [[J.place(b, d) for b in builds] for d in devs]
        # dispatch every device's pipeline first; transfers afterwards —
        # to_host blocks, and fetching inside the loop would serialize the
        # fan-out this path exists for
        pending = [self._run_block(plan.pipeline, dblock,
                                   builds_by_dev[di], params)
                   for di, dblock in self._scan_device_blocks(
                       plan.pipeline, snapshot, devices=devs)]
        lim = None if plan.limit is None else plan.limit + (plan.offset or 0)
        if plan.sort and lim is not None and lim <= (1 << 17):
            # sort-limit queries: per-device partial top-k BEFORE the
            # union, so only ≤lim rows per device cross the link — the
            # DqCnMerge (sorted-merge connection) analog. The offset
            # applies only at the merge (each device must keep its full
            # top-(limit+offset) prefix).
            import dataclasses
            # sort keys must survive the per-device projection or the
            # merge pass cannot re-sort (ORDER BY a column/expr outside
            # the SELECT list); execute()'s final _project_output trims
            # the extras
            out_names = {n for (n, _lbl) in plan.output}
            extra = [(sk.name, sk.name) for sk in plan.sort
                     if sk.name not in out_names]
            plan_local = dataclasses.replace(
                plan, offset=None, limit=lim, output=plan.output + extra)
            outs = [self._finalize(plan_local, [d], params)
                    for d in pending]
            union = HostBlock.concat(outs) if len(outs) > 1 else outs[0]
            plan_merge = dataclasses.replace(
                plan, final_program=None, output=plan.output + extra)
            return self._finalize(plan_merge, [to_device(union)], params)
        outs = [to_host(d) for d in pending]
        union = HostBlock.concat(outs) if len(outs) > 1 else outs[0]
        return self._finalize(plan, [to_device(union)], params)

    def _execute_distributed(self, plan: QueryPlan, params: dict,
                             snapshot: Snapshot,
                             prebuilt: Optional[dict] = None) -> HostBlock:
        """Scan partitions round-robin across mesh devices, run the full
        per-block pipeline (pushdown → joins → partial agg) on each
        device, hash-shuffle the partials over the mesh, merge, then run
        the remaining final program + sort/limit single-device (post-agg
        tails are small)."""
        pipe = plan.pipeline
        devs = list(self.mesh.devices.flat)
        ndev = len(devs)
        builds = self._prepare_builds(pipe, params, snapshot,
                                      prebuilt=prebuilt)
        builds_by_dev = [[J.place(b, d) for b in builds] for d in devs]

        per_dev = [[] for _ in range(ndev)]
        for di, dblock in self._scan_device_blocks(pipe, snapshot,
                                                   devices=devs):
            per_dev[di].append(
                self._run_block(pipe, dblock, builds_by_dev[di], params))
        for di in range(ndev):
            if not per_dev[di]:
                empty = to_device(self._empty_scan_block(pipe),
                                  device=devs[di])
                per_dev[di].append(
                    self._run_block(pipe, empty, builds_by_dev[di], params))

        # merge GroupBy runs twice (pre-shuffle local combine + post-shuffle
        # final merge) — merge aggregation is associative, so this is the
        # BlockCombineHashed → BlockMergeFinalizeHashed split
        return self._merge_distributed_partials(plan, per_dev, params)

    # -- distributed shuffle join ------------------------------------------

    def _try_execute_shuffle_join(self, plan: QueryPlan, params: dict,
                                  snapshot: Snapshot,
                                  prebuilt: Optional[dict] = None):
        """Shuffle join over the mesh (`dq_opt_join.cpp` ShuffleJoin): the
        LAST join's build side hash-partitions across devices — no device
        holds the full build — and probe rows route to their key's owner
        via one ICI all_to_all (`parallel/shuffle_join.py`). Triggers when
        the build's stats estimate exceeds the broadcast budget; declines
        (→ broadcast path) for shapes the exchange doesn't cover yet:
        float/string keys, composite hash keys, NOT IN, duplicate-key
        inner/left builds, joins followed by further joins."""
        from ydb_tpu.core.dtypes import DType, Kind as _K

        pipe = plan.pipeline
        join_pos = [i for i, (k, _s) in enumerate(pipe.steps)
                    if k == "join"]
        if not join_pos:
            return None
        j = join_pos[-1]
        step = pipe.steps[j][1]
        if step.kind not in ("inner", "left", "left_semi", "left_anti",
                             "mark"):
            return None
        if step.not_in:
            # NOT IN null semantics stay on the broadcast path
            return None

        # cheap stats gate: the build's driving-scan footprint
        bp = getattr(step.build, "pipeline", step.build)
        if not hasattr(bp, "scan"):
            return None
        from ydb_tpu.query.admission import estimate_plan_bytes
        bplan = step.build if isinstance(step.build, QueryPlan) else None
        est = estimate_plan_bytes(
            self.catalog,
            bplan if bplan is not None else QueryPlan(pipeline=step.build),
            snapshot)
        if est <= self.dist_broadcast_budget_bytes:
            return None

        # materialize the build side (host) and check key shape; every
        # decline below hands the block to the broadcast path via
        # `prebuilt` so it is never executed twice
        if isinstance(step.build, QueryPlan):
            built = self.execute(step.build, snapshot)
        else:
            built = HostBlock.concat(
                [to_host(d) for d in
                 self._run_pipeline(step.build, params, snapshot)])
        if prebuilt is not None:
            prebuilt[j] = built
        if step.build_hash_keys:
            # composite key: the probe side already computed its combined
            # 64-bit hash into `probe_key` (planner pre-program); hashing
            # the build columns the same way makes the exchange key a
            # plain int64 — per-key equality verification rides in the
            # post-join programs (`rest`), exactly like the broadcast path
            built = _add_hash_column(built, step.build_hash_keys,
                                     step.build_key)
            if prebuilt is not None:
                prebuilt[j] = built
        kcd = built.columns.get(step.build_key)
        if kcd is None or np.issubdtype(kcd.data.dtype, np.floating):
            return None
        if step.anti_null_check:
            # anti/mark semantics with an ACTUALLY-NULL build key: the
            # broadcast path owns the three-valued-logic handling (empty
            # probe rule, or the loud composite NOT IN refusal); the
            # exchange would silently drop the NULLs and change the
            # answer. NULL-free builds shuffle fine.
            cd0 = built.columns.get(step.anti_null_col or step.build_key)
            if cd0 is not None and cd0.valid is not None \
                    and not cd0.valid.all():
                return None
        if kcd.dictionary is not None:
            # dictionary-encoded key: remap build codes into the PROBE
            # side's dictionary (same discipline as `_prepare_join`), so
            # codes exchange as plain comparable ints
            table = self.catalog.table(pipe.scan.table)
            probe_dicts = dict(table.dictionaries)
            for (storage, internal) in pipe.scan.columns:
                if storage in probe_dicts:
                    probe_dicts[internal] = probe_dicts[storage]
            probe_dict = probe_dicts.get(step.probe_key)
            if probe_dict is None:
                return None          # probe dict not derivable here
            if kcd.dictionary is not probe_dict:
                built = _remap_build_codes(built, step.build_key,
                                           probe_dict)
                # build values ABSENT from the probe dictionary remap to
                # the shared -2 never-match code: drop them before the
                # exchange (they can't match anything, and a shared code
                # would trip the duplicate-key uniqueness gate below)
                codes2 = built.columns[step.build_key].data
                if (codes2 == -2).any():
                    built = built.take(np.nonzero(codes2 != -2)[0])
                if prebuilt is not None:
                    prebuilt[j] = built
        # duplicate keys: the exchange probe is first-match only
        if step.kind in ("inner", "left", "mark"):
            enc = built.columns[step.build_key].data
            if len(enc) > 1 and len(np.unique(enc)) != len(enc):
                return None
        from ydb_tpu.parallel import shuffle_join as SJ
        devs = list(self.mesh.devices.flat)
        ndev = len(devs)
        barrays, pschema, bdicts, bcap = SJ.partition_build(
            built, step.build_key, list(step.payload), ndev)
        if not pschema.names and step.payload:
            return None

        with self._span("shuffle-join", ndev=ndev, build_rows=built.length):
            # stage A: pipeline prefix per device (earlier joins broadcast)
            prefix_builds = self._prepare_builds(pipe, params, snapshot,
                                                 until=j)
            builds_by_dev = [[J.place(b, d) for b in prefix_builds]
                             for d in devs]
            per_dev = [[] for _ in range(ndev)]
            for di, dblock in self._scan_device_blocks(pipe, snapshot,
                                                       devices=devs):
                per_dev[di].extend(self._run_block_multi(
                    pipe, dblock, builds_by_dev[di], params, until=j))
            for di in range(ndev):
                if not per_dev[di]:
                    empty = to_device(self._empty_scan_block(pipe),
                                      device=devs[di])
                    per_dev[di].extend(self._run_block_multi(
                        pipe, empty, builds_by_dev[di], params, until=j))

            in_schema = per_dev[0][0].schema
            payload_cols = []
            for name in pschema.names:
                payload_cols.append(
                    Column(name, pschema.dtype(name).with_nullable(True)))
            if step.kind == "mark":
                payload_cols.append(Column(step.mark_col or "__mark",
                                           DType(_K.BOOL, False)))
            rest = [s for (k, s) in pipe.steps[j + 1:]]
            # groupby_tuning in the key: the ShuffleJoin traces `rest`
            # and `pipe.partial` (GroupBy lowerings read the tile/batch/
            # legacy levers at trace time) — a knob flip must build a
            # fresh join, not reuse a program tiled under old settings
            key = (tuple((c.name, c.dtype.kind.value, c.dtype.nullable)
                         for c in in_schema.columns),
                   step.probe_key, step.kind,
                   tuple((c.name, c.dtype.kind.value, c.dtype.nullable)
                         for c in payload_cols),
                   ndev,
                   tuple(p.fingerprint() for p in rest),
                   pipe.partial.fingerprint() if pipe.partial else "",
                   groupby_tuning())
            sj = self._shuffle_joins.get(key)
            if sj is None:
                sj = SJ.ShuffleJoin(self.mesh, in_schema, step.probe_key,
                                    step.kind, payload_cols,
                                    step.mark_col or "__mark", step.not_in,
                                    rest, pipe.partial)
                self._shuffle_joins[key] = sj
            dicts = {}
            for blks in per_dev:
                for b in blks:
                    dicts.update(b.dictionaries)
            dicts.update(bdicts)
            post_blocks = sj.run(per_dev, barrays, bcap, params, dicts)

        from ydb_tpu.utils.metrics import GLOBAL
        GLOBAL.inc("executor/shuffle_joins")
        return self._merge_distributed_partials(plan, [[b] for b in
                                                       post_blocks], params)

    def _merge_distributed_partials(self, plan: QueryPlan, per_dev: list,
                                    params: dict) -> HostBlock:
        """Shared tail of the mesh paths: hash-shuffle merge of per-device
        partial-agg blocks + the rest of the final program."""
        import dataclasses

        from ydb_tpu.parallel.shuffle import DistributedAgg

        ndev = self.mesh.devices.size
        gb = plan.final_program.commands[0]
        merge_prog = ir.Program([gb])
        in_schema = per_dev[0][0].schema
        # bounds lattice: a PROVEN merge group-count bound sizes the
        # shuffle's per-target segments — each producer's partial holds
        # ≤ out_bound groups, so a bound-bucket segment cannot overflow
        # (replacing the full-capacity pad; the 2112.01075 stance)
        seg_rows = 0
        if gb.out_bound:
            from ydb_tpu.utils.metrics import GLOBAL
            seg_rows = bucket_capacity(max(int(gb.out_bound), 1),
                                       minimum=128)
            GLOBAL.inc("bounds/seg_bounded_shuffles")
        key = (merge_prog.fingerprint(),
               tuple((c.name, c.dtype.kind.value, c.dtype.nullable)
                     for c in in_schema.columns), ndev, seg_rows,
               groupby_tuning())
        dag = self._dist_aggs.get(key)
        if dag is None:
            dag = DistributedAgg(merge_prog, merge_prog, in_schema,
                                 self.mesh, seg_rows=seg_rows)
            self._dist_aggs[key] = dag
        merged = dag.run_device_blocks(per_dev, params)
        rest = list(plan.final_program.commands[1:])
        plan2 = dataclasses.replace(
            plan, final_program=ir.Program(rest) if rest else None)
        return self._finalize(plan2, [to_device(merged)], params)

    # -- pipelines ---------------------------------------------------------

    def _run_pipeline(self, pipe: Pipeline, params: dict,
                      snapshot: Snapshot, builds=None) -> list:
        """Partial-result DeviceBlocks (≥1: an empty scan still runs the
        programs once so global aggregates emit their row). `builds`:
        BuildTables already prepared by a declined fused attempt."""
        if builds is None:
            builds = self._prepare_builds(pipe, params, snapshot)
        out = []
        for d in self._scan_device_blocks(pipe, snapshot):
            out.extend(self._run_block_multi(pipe, d, builds, params))
        if not out:
            out = self._run_block_multi(
                pipe, to_device(self._empty_scan_block(pipe)), builds,
                params)
        return out

    def _run_block(self, pipe: Pipeline, d: DeviceBlock, builds: list,
                   params: dict) -> DeviceBlock:
        """Single-stream block runner (mesh path — partitioned builds are
        not routed here)."""
        out = self._run_block_multi(pipe, d, builds, params)
        assert len(out) == 1, "partitioned join on the mesh path"
        return out[0]

    def _run_block_multi(self, pipe: Pipeline, d: DeviceBlock, builds: list,
                         params: dict, until: Optional[int] = None) -> list:
        """Run one scan block through the pipeline. A GraceJoin-partitioned
        build forks the stream: probe rows route to their key's partition
        (device-side splitmix64 matches the host partitioner) and each
        partition continues through the remaining steps independently —
        their partials merge like any other blocks.

        `until`: stop BEFORE step index `until` and skip the partial (the
        shuffle-join stage-A prefix)."""
        if pipe.pre_program is not None:
            d = run_on_device(pipe.pre_program, d, params)
        stop = len(pipe.steps) if until is None else until

        def run_steps(d: DeviceBlock, si: int, bi: int) -> list:
            while si < stop:
                kind, step = pipe.steps[si]
                if kind != "join":
                    d = run_on_device(step, d, params)
                    si += 1
                    continue
                table = builds[bi]
                if isinstance(table, J.PartitionedBuild):
                    out = []
                    for p, bt in enumerate(table.tables):
                        dp = self._partition_block(d, step.probe_key, p,
                                                   table.n_partitions)
                        out.extend(self._probe_one(dp, bt, step, pipe,
                                                   run_steps, si, bi))
                    return out
                return self._probe_one(d, table, step, pipe, run_steps,
                                       si, bi)
            if until is None and pipe.partial is not None:
                d = run_on_device(pipe.partial, d, params)
            return [d]

        return run_steps(d, 0, 0)

    def _probe_one(self, d: DeviceBlock, table, step, pipe, run_steps,
                   si: int, bi: int) -> list:
        if not table.unique and step.kind in ("inner", "left"):
            # duplicate build keys → expanding probe; output compact
            d = J.probe_expand(d, table, step.probe_key, step.kind)
            return run_steps(d, si + 1, bi + 1)
        d, sel = J.probe(d, table, step.probe_key, step.kind,
                         sel=None, mark_col=step.mark_col or None,
                         not_in=step.not_in)
        if step.kind != "mark":
            d = compress_block(d, sel)
        return run_steps(d, si + 1, bi + 1)

    @staticmethod
    def _partition_block(d: DeviceBlock, key: str, p: int,
                         nparts: int) -> DeviceBlock:
        """Rows whose key hashes to partition p, compacted."""
        import jax.numpy as jnp

        from ydb_tpu.utils.hashing import splitmix64
        enc = d.arrays[key].astype(jnp.int64)
        part = splitmix64(jnp, enc) % jnp.uint64(nparts)
        return compress_block(d, part == jnp.uint64(p))

    def _prepare_builds(self, pipe: Pipeline, params: dict,
                        snapshot: Snapshot,
                        until: Optional[int] = None,
                        prebuilt: Optional[dict] = None) -> list:
        """Prepare every join build of a pipeline in order, threading the
        probe side's string dictionaries so cross-dictionary string keys
        remap to probe codes (each table/temp owns its own dictionary —
        raw code equality across two of them is meaningless).

        `until`: only the joins among steps[:until] (shuffle-join prefix).
        `prebuilt`: {step index: HostBlock} already-materialized build
        sides (a declined shuffle-join attempt hands its block over)."""
        probe_dicts = dict(self.catalog.table(pipe.scan.table).dictionaries)
        # scan columns are renamed storage→internal in the env
        for (storage, internal) in pipe.scan.columns:
            if storage in probe_dicts:
                probe_dicts[internal] = probe_dicts[storage]
        # FD-verification blocks are only ever read when the consuming
        # pipeline ends in a multi-key group-by (the carry rewrite's
        # measured lane) — don't pin host copies for any other shape
        keep_fd = (pipe.partial is not None and pipe.partial.commands
                   and isinstance(pipe.partial.commands[-1], ir.GroupBy)
                   and len(pipe.partial.commands[-1].keys) >= 2)
        builds = []
        for si, (kind, step) in enumerate(pipe.steps):
            if kind != "join" or (until is not None and si >= until):
                continue
            bt = self._prepare_join(step, params, snapshot,
                                    probe_dict=probe_dicts.get(
                                        step.probe_key),
                                    prebuilt_block=(prebuilt or {}).get(si),
                                    keep_fd=keep_fd)
            builds.append(bt)
            # payload columns join the probe namespace for later steps
            probe_dicts.update(getattr(bt, "dictionaries", None) or {})
        return builds

    def _prepare_join(self, step: JoinStep, params: dict,
                      snapshot: Snapshot, probe_dict=None,
                      prebuilt_block: Optional[HostBlock] = None,
                      keep_fd: bool = False) -> J.BuildTable:
        from ydb_tpu.query.build_cache import build_plan_fingerprint
        cache_key = None
        if prebuilt_block is None:
            single_dev = self.mesh is None or self.mesh.devices.size <= 1
            # knobs that steer the PartitionedBuild-vs-BuildTable choice
            # are part of the key (tests flip grace_budget_bytes); keep_fd
            # rides it so a group-by consumer never cache-hits a lean
            # entry whose FD block was skipped for a join-only shape
            cache_key = build_plan_fingerprint(
                step, params, snapshot, self.catalog,
                extra=(single_dev, self.grace_budget_bytes, keep_fd))
            if cache_key is not None:
                hit = self.build_cache.lookup(cache_key, probe_dict)
                if hit is not None:
                    return hit
        bt = self._prepare_join_uncached(step, params, snapshot,
                                         probe_dict, prebuilt_block,
                                         keep_fd=keep_fd)
        if cache_key is not None:
            self.build_cache.insert(cache_key, bt, probe_dict)
        return bt

    def _prepare_join_uncached(self, step: JoinStep, params: dict,
                               snapshot: Snapshot, probe_dict=None,
                               prebuilt_block: Optional[HostBlock] = None,
                               keep_fd: bool = False) -> J.BuildTable:
        if prebuilt_block is not None:
            built = prebuilt_block
        elif isinstance(step.build, QueryPlan):
            built = self.execute(step.build, snapshot)
        else:
            # route the build PIPELINE through the fused machinery too:
            # its scan gets the single-dispatch path (and the superblock
            # cache) instead of a dispatch per portion — q2/q9-class
            # queries spend most of their time in builds. Empty output =
            # keep every column (composite-key builds carry internal
            # hash columns a projection would drop).
            bplan = QueryPlan(pipeline=step.build, params=dict(params))
            fused = self._try_execute_fused(bplan, params, snapshot) \
                if self.enable_fused else None
            if isinstance(fused, tuple):
                built = fused[1]
            elif isinstance(fused, HostBlock):
                built = fused
            else:
                built = HostBlock.concat(
                    [to_host(d) for d in
                     self._run_pipeline(step.build, params, snapshot,
                                        builds=fused)])
        kcd = built.columns.get(step.build_key)
        if kcd is not None and kcd.dictionary is not None \
                and probe_dict is not None \
                and kcd.dictionary is not probe_dict:
            built = _remap_build_codes(built, step.build_key, probe_dict)
        if step.build_hash_keys:
            built = _add_hash_column(built, step.build_hash_keys,
                                     step.build_key)
        anti_has_null = False
        if step.anti_null_check:
            cd = built.columns[step.anti_null_col or step.build_key]
            if cd.valid is not None and not cd.valid.all():
                if step.kind == "left_anti":
                    # x NOT IN (set with NULL) is never TRUE → the anti
                    # probe selects nothing (SQL three-valued logic)
                    anti_has_null = True
                else:
                    # composite correlated NOT IN: a NULL poisons only its
                    # per-correlation-key set — needs per-key tracking
                    raise NotImplementedError(
                        "correlated NOT IN over a subquery producing NULLs "
                        "is not supported yet")
        # GraceJoin spill: a build side above the device budget hash-
        # partitions into host DRAM (single-device path only — the mesh
        # path replicates builds per device and would need partition
        # placement instead)
        single_dev = self.mesh is None or self.mesh.devices.size <= 1
        if single_dev and not step.not_in and built.length:
            cols = list(dict.fromkeys([step.build_key] + list(step.payload)))
            row_bytes = sum(built.columns[n].data.itemsize for n in cols)
            if built.length * row_bytes > self.grace_budget_bytes:
                return J.build_partitioned(built, step.build_key,
                                           list(step.payload),
                                           self.grace_budget_bytes)
        bt = J.build(built, step.build_key, list(step.payload),
                     keep_fd=keep_fd)
        bt.anti_has_null = anti_has_null
        return bt

    def _scan_device_blocks(self, pipe: Pipeline, snapshot: Snapshot,
                            devices=None):
        """Per-portion device blocks via the HBM column cache; committed but
        unindexed inserts upload uncached (they are transient — indexation
        turns them into portions).

        With `devices`, sources are placed round-robin across the mesh and
        (device_index, block) pairs are yielded instead (partition
        parallelism — the DataShard/ColumnShard shard-spread analog)."""
        table = self.catalog.table(pipe.scan.table)
        storage_names = [s for (s, _i) in pipe.scan.columns]
        rename = {s: i for (s, i) in pipe.scan.columns}
        i = 0
        for shard in table.shards:
            portions, insert_entries = shard.scan_sources(
                snapshot, pipe.scan.prune or None)
            for p in portions:
                if p.deletes and p.delete_sig(snapshot):
                    # MVCC delete marks: scan the filtered view uncached
                    # (the view is snapshot-specific; the mark set is in
                    # the superblock cache key on the fused path)
                    hb = _rename_block(
                        p.visible_block(snapshot).select(storage_names),
                        rename)
                    if devices is None:
                        yield to_device(hb)
                    else:
                        di = i % len(devices)
                        i += 1
                        yield di, to_device(hb, device=devices[di])
                    continue
                if devices is None:
                    yield self.device_cache.device_block(p, storage_names,
                                                         rename)
                else:
                    di = i % len(devices)
                    i += 1
                    yield di, self.device_cache.device_block(
                        p, storage_names, rename, device=devices[di])
            for e in insert_entries:
                hb = _rename_block(e.block.select(storage_names), rename)
                if devices is None:
                    yield to_device(hb)
                else:
                    di = i % len(devices)
                    i += 1
                    yield di, to_device(hb, device=devices[di])

    def _empty_scan_block(self, pipe: Pipeline) -> HostBlock:
        """Zero-row block with the scan's schema and dictionaries."""
        table = self.catalog.table(pipe.scan.table)
        cols, schema_cols = {}, []
        for (storage, internal) in pipe.scan.columns:
            c = table.schema.col(storage)
            cols[internal] = ColumnData(
                np.zeros(0, dtype=c.dtype.np), None,
                table.dictionaries.get(storage))
            schema_cols.append(Column(internal, c.dtype))
        return HostBlock(Schema(schema_cols), cols, 0)

    # -- fused finalize ----------------------------------------------------

    def _finalize(self, plan: QueryPlan, dblocks: list, params: dict,
                  defer: bool = False) -> "HostBlock | DeviceResultFuture":
        """Concat partials + final program + sort + limit in ONE device
        call, then one batched transfer (`defer=True`: the transfer is
        wrapped in a `DeviceResultFuture` and runs at `result()` time —
        the pipeline readout phase). Partial-agg states too large to
        merge in one device concat (high-cardinality group-bys on the
        portioned path) route to the host-DRAM partitioned merge instead
        of compiling an HBM-sized program."""
        in_schema = dblocks[0].schema

        fp = plan.final_program
        merge_gb = fp.commands[0] if fp is not None and fp.commands \
            and isinstance(fp.commands[0], ir.GroupBy) else None
        if merge_gb is not None and merge_gb.keys and len(dblocks) > 1:
            prow = sum(np.dtype(c.dtype.np).itemsize + 1
                       for c in in_schema.columns)
            total = sum(d.capacity for d in dblocks) * prow
            if total > self.merge_budget_bytes:
                from ydb_tpu.ops import spill as SP
                from ydb_tpu.utils.metrics import GLOBAL
                P = 1
                while total / P > self.merge_budget_bytes and P < 256:
                    P *= 2
                dicts = {}
                for d in dblocks:
                    dicts.update(d.dictionaries)
                store = SP.PartitionStore(in_schema, list(merge_gb.keys),
                                          P, dicts)
                for d in dblocks:
                    store.feed(d)
                GLOBAL.inc("executor/spilled_rows", store.spilled_rows)
                GLOBAL.inc("executor/spilled_bytes", store.spilled_bytes)
                merged = self._merge_spilled(plan, store, params)
                return DeviceResultFuture.completed(merged) if defer \
                    else merged
        sort_params, sort_spec, rank_assigns = self._sort_setup(
            plan, in_schema, dblocks)
        all_params = {**params, **sort_params}

        blocks_sig = tuple(
            (tuple(sorted(d.arrays)), tuple(sorted(d.valids)), d.capacity)
            for d in dblocks)
        key = (plan.final_program.fingerprint() if plan.final_program else "",
               ir.Program(rank_assigns).fingerprint() if rank_assigns else "",
               sort_spec, plan.limit, plan.offset, blocks_sig,
               tuple((c.name, c.dtype.kind.value, c.dtype.nullable)
                     for c in in_schema.columns),
               tuple(sorted(all_params)),
               tuple(n for (n, _lbl) in plan.output), groupby_tuning())
        entry = self._finalize_cache.get(key)
        if entry is None:
            entry = self._build_finalize(plan, in_schema, blocks_sig,
                                         sort_spec, rank_assigns)
            self._finalize_cache[key] = entry
        fn, out_schema = entry

        blocks_in = tuple((d.arrays, d.valids, d.length) for d in dblocks)
        dev_params = {k: (jnp.asarray(v) if isinstance(v, np.ndarray) else v)
                      for k, v in all_params.items()}
        out_d, out_v, length = fn(blocks_in, dev_params)

        dicts = {}
        for d in dblocks:
            dicts.update(d.dictionaries)
        dicts.update(plan.result_dicts)
        dicts = {n: dc for n, dc in dicts.items() if out_schema.has(n)}
        out_cap = (next(iter(out_d.values())).shape[0] if out_d else 0)
        dblock = DeviceBlock(out_schema, out_d, out_v, length, out_cap, dicts)
        lo = plan.offset or 0
        limit = plan.limit
        fut = to_host_async(dblock).map(
            lambda block: _apply_offset(block, lo, limit))
        return fut if defer else fut.result()

    def _sort_setup(self, plan: QueryPlan, in_schema: Schema, dblocks: list):
        """Rank-LUT params for string sort keys (lexicographic order over
        dictionary codes) + the static sort spec."""
        from ydb_tpu.core import dtypes as dt
        sort_params = {}
        rank_assigns = []
        spec = []
        schema = in_schema
        if plan.final_program is not None:
            schema = ir.infer_schema(plan.final_program, in_schema)
        dicts = {}
        for d in dblocks:
            dicts.update(d.dictionaries)
        dicts.update(plan.result_dicts)
        for j, sk in enumerate(plan.sort):
            dtype = schema.dtype(sk.name)
            dic = dicts.get(sk.name)
            if dtype.is_string and dic is not None:
                ranks = dic.sort_ranks()
                pname = f"__rank{j}"
                sort_params[pname] = ranks
                rank_col = f"__sortrank{j}"
                rank_assigns.append(ir.Assign(rank_col, ir.call(
                    "take_lut", ir.Col(sk.name),
                    ir.Param(pname, dt.DType(dt.Kind.INT32, False),
                             is_array=True))))
                spec.append((rank_col, sk.ascending, sk.nulls_first))
            else:
                spec.append((sk.name, sk.ascending, sk.nulls_first))
        return sort_params, tuple(spec), rank_assigns

    def _build_finalize(self, plan: QueryPlan, in_schema: Schema,
                        blocks_sig: tuple, sort_spec: tuple,
                        rank_assigns: list):
        final_prog = plan.final_program
        in_cols = list(in_schema.columns)
        names = [c.name for c in in_cols]
        out_schema = ir.infer_schema(final_prog, in_schema) \
            if final_prog is not None else in_schema
        limit = plan.limit
        lim2 = None if limit is None else limit + (plan.offset or 0)
        keep = [n for (n, _lbl) in plan.output]
        keep = list(dict.fromkeys(keep))

        @jax.jit
        def fn(blocks, params):
            datas, valid_arrays, masks = {n: [] for n in names}, \
                {n: [] for n in names}, []
            total = 0
            for (arrays, valids, length), (_an, _vn, cap) in zip(blocks,
                                                                 blocks_sig):
                iota = jnp.arange(cap, dtype=jnp.int32)
                masks.append(iota < length)
                total += cap
                for n in names:
                    datas[n].append(arrays[n])
                    v = valids.get(n)
                    valid_arrays[n].append(
                        v if v is not None else jnp.ones((cap,), jnp.bool_))
            env = {n: (jnp.concatenate(datas[n]),
                       jnp.concatenate(valid_arrays[n])) for n in names}
            mask = jnp.concatenate(masks)
            env, length = compress(env, jnp.int32(total), mask, total)
            cap = total
            if final_prog is not None:
                env, length, sel, _schema = _trace_program(
                    final_prog, in_cols, cap, env, length, params)
                if env:
                    cap = next(iter(env.values()))[0].shape[0]
                if sel is not None:
                    env, length = compress(env, length, sel, cap)
            for a in rank_assigns:
                from ydb_tpu.ops.xla_exec import _eval
                env[a.name] = _eval(a.expr, env, params, cap)
            if sort_spec:
                arrays = {n: d for n, (d, _v) in env.items()}
                valids = {n: v for n, (d, v) in env.items() if v is not None}
                arrays2, valids2, length = sort_env(
                    arrays, valids, length, None, sort_spec,
                    tuple(arrays.keys()))
                env = {n: (arrays2[n], valids2.get(n)) for n in arrays2}
            if lim2 is not None:
                length = jnp.minimum(length, jnp.int32(lim2))
                out_cap = min(bucket_capacity(lim2, minimum=128), cap)
                env = {n: (d[:out_cap], v[:out_cap] if v is not None else None)
                       for n, (d, v) in env.items()}
            out_names = [n for n in keep if n in env] or list(env.keys())
            out_d = {n: env[n][0] for n in out_names}
            out_v = {n: env[n][1] for n in out_names
                     if env[n][1] is not None}
            return out_d, out_v, length

        out_cols = [c for c in out_schema.columns if c.name in keep] \
            or list(out_schema.columns)
        return fn, Schema(out_cols)


    # -- output ------------------------------------------------------------

    def _project_output(self, block: HostBlock, output: list) -> HostBlock:
        from ydb_tpu.ops.device import DeviceStageBlock
        if isinstance(block, DeviceStageBlock) and not block.materialized:
            # stage-spine path: rename device-side, references only —
            # touching `block.columns` here would force the readback the
            # capture exists to avoid
            return block.project(output)
        cols = {}
        schema_cols = []
        used = set()
        for (internal, label) in output:
            lbl = label
            k = 2
            while lbl in used:
                lbl = f"{label}_{k}"
                k += 1
            used.add(lbl)
            cd = block.columns[internal]
            cols[lbl] = ColumnData(cd.data, cd.valid, cd.dictionary)
            schema_cols.append(Column(lbl, block.schema.dtype(internal)))
        return HostBlock(Schema(schema_cols), cols, block.length)


def _param_values_equal(a, b) -> bool:
    """Batch-invariance test for one runtime param across two members
    (arrays compare by dtype/shape/contents; scalars by type + value)."""
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (isinstance(a, np.ndarray) and isinstance(b, np.ndarray)
                and a.dtype == b.dtype and a.shape == b.shape
                and np.array_equal(a, b))
    return type(a) is type(b) and bool(a == b)


def _apply_offset(block: HostBlock, lo: int, limit) -> HostBlock:
    """Shared OFFSET/LIMIT tail slice of every deferred-readout path
    (fused fetch + finalize) — one definition so the two lanes can't
    silently diverge."""
    if lo:
        hi = lo + limit if limit is not None else block.length
        block = block.slice(lo, min(hi, block.length))
    return block


def _remap_build_codes(built: HostBlock, key: str, probe_dict) -> HostBlock:
    """Translate a build block's dictionary-encoded key codes into the
    PROBE side's dictionary (host-side O(distinct) LUT; values absent
    from the probe dictionary → -2, the never-match code; negative codes
    — the -1 NULL slot — pass through untouched)."""
    kcd = built.columns[key]
    src = kcd.dictionary.values_array()
    lut = np.full(max(len(src), 1), -2, dtype=np.int32)
    for i, v in enumerate(src):
        lut[i] = probe_dict.encode_existing(v)
    codes = kcd.data
    remapped = np.where(codes >= 0, lut[np.clip(codes, 0, None)],
                        codes).astype(codes.dtype)
    return HostBlock(
        built.schema,
        {**built.columns, key: ColumnData(remapped, kcd.valid, probe_dict)},
        built.length)


def _add_hash_column(block: HostBlock, key_cols: list, out: str) -> HostBlock:
    """Host-side mirror of the device hash-key expression
    (`hash_combine(hash64(c0), hash64(c1), ...)`) — bit-identical by
    construction (`ydb_tpu/utils/hashing.py`). Idempotent: a block that
    already carries `out` (a declined shuffle attempt's prebuilt handoff)
    passes through, instead of appending a duplicate schema column."""
    from ydb_tpu.core.dtypes import DType, Kind
    from ydb_tpu.utils.hashing import hash_combine, splitmix64

    if out in block.columns:
        return block
    h = None
    valid = None
    for name in key_cols:
        cd = block.columns[name]
        x = splitmix64(np, cd.data.astype(np.int64))
        h = x if h is None else hash_combine(np, h, x)
        if cd.valid is not None:
            valid = cd.valid if valid is None else (valid & cd.valid)
    cols = dict(block.columns)
    cols[out] = ColumnData(h, valid, None)
    schema = block.schema.extend([Column(out, DType(Kind.UINT64,
                                                    valid is not None))])
    return HostBlock(schema, cols, block.length)


def _rename_block(block: HostBlock, rename: dict) -> HostBlock:
    cols = {}
    schema_cols = []
    for c in block.schema:
        new = rename.get(c.name, c.name)
        cols[new] = block.columns[c.name]
        schema_cols.append(Column(new, c.dtype))
    return HostBlock(Schema(schema_cols), cols, block.length)
