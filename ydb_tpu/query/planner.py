"""AST → physical plan.

Combines the reference's logical/physical optimization + stage building:
  * predicate classification & pushdown into scans — the
    `KqpPushOlapFilter` rule (`kqp_opt_phy_olap_filter.cpp:527`);
  * join-tree construction from equi-edges with the largest table as the
    streaming fact side and broadcast build fragments — the MapJoin
    strategy of `dq_opt_join.cpp` (CBO/DPhyp ordering comes later);
  * two-phase aggregation: per-block partial GroupBy on device, final
    merge GroupBy — the BlockCombineHashed → BlockMergeFinalizeHashed
    split (`mkql_block_agg.cpp`);
  * HAVING/output/ORDER BY expression binding over the aggregated schema.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ydb_tpu.core import dtypes as dt
from ydb_tpu.ops import ir
from ydb_tpu.query import binder as B
from ydb_tpu.query.plan import JoinStep, Pipeline, QueryPlan, ScanSpec, SortKey
from ydb_tpu.sql import ast


class PlanError(Exception):
    pass


def conjuncts(e: Optional[ast.Expr]) -> list:
    if e is None:
        return []
    if isinstance(e, ast.BinOp) and e.op == "and":
        return conjuncts(e.left) + conjuncts(e.right)
    return [e]


def disjuncts(e: ast.Expr) -> list:
    if isinstance(e, ast.BinOp) and e.op == "or":
        return disjuncts(e.left) + disjuncts(e.right)
    return [e]


def _and_fold(parts: list) -> Optional[ast.Expr]:
    out = None
    for p in parts:
        out = p if out is None else ast.BinOp("and", out, p)
    return out


def _or_fold(parts: list) -> Optional[ast.Expr]:
    out = None
    for p in parts:
        out = p if out is None else ast.BinOp("or", out, p)
    return out


def hoist_or_common(pred: ast.Expr) -> list:
    """(a AND x) OR (a AND y) → a AND (x OR y): lift conjuncts shared by
    every OR branch to the top (TPC-H Q19's join condition shape) — the
    role of the reference's common-opt OR factoring."""
    out: list = []
    for p in conjuncts(pred):
        if not (isinstance(p, ast.BinOp) and p.op == "or"):
            out.append(p)
            continue
        branches = [conjuncts(b) for b in disjuncts(p)]
        common = [c for c in branches[0]
                  if all(c in b for b in branches[1:])]
        if not common:
            out.append(p)
            continue
        out.extend(common)
        rests = []
        degenerate = False
        for b in branches:
            rest = [c for c in b if c not in common]
            if not rest:
                degenerate = True   # one branch had only common conjuncts
                break
            rests.append(_and_fold(rest))
        if not degenerate:
            out.append(_or_fold(rests))
    return out


def walk_names(e, out: set):
    """Collect ast.Name nodes (skipping into subqueries)."""
    if isinstance(e, ast.Name):
        out.add(e.parts)
    elif isinstance(e, ast.BinOp):
        walk_names(e.left, out)
        walk_names(e.right, out)
    elif isinstance(e, ast.UnaryOp):
        walk_names(e.arg, out)
    elif isinstance(e, ast.FuncCall):
        for a in e.args:
            walk_names(a, out)
    elif isinstance(e, ast.Case):
        if e.operand is not None:
            walk_names(e.operand, out)
        for c, r in e.whens:
            walk_names(c, out)
            walk_names(r, out)
        if e.default is not None:
            walk_names(e.default, out)
    elif isinstance(e, (ast.Cast,)):
        walk_names(e.arg, out)
    elif isinstance(e, ast.Between):
        walk_names(e.arg, out)
        walk_names(e.lo, out)
        walk_names(e.hi, out)
    elif isinstance(e, (ast.InList,)):
        walk_names(e.arg, out)
        for i in e.items:
            walk_names(i, out)
    elif isinstance(e, (ast.Like, ast.IsNull)):
        walk_names(e.arg, out)


def walk_aggs(e, out: list):
    """Collect aggregate FuncCalls (no nesting into their args)."""
    if isinstance(e, ast.FuncCall):
        if e.name in B.AGG_NAMES:
            out.append(e)
            return
        for a in e.args:
            walk_aggs(a, out)
    elif isinstance(e, ast.BinOp):
        walk_aggs(e.left, out)
        walk_aggs(e.right, out)
    elif isinstance(e, ast.UnaryOp):
        walk_aggs(e.arg, out)
    elif isinstance(e, ast.Case):
        if e.operand is not None:
            walk_aggs(e.operand, out)
        for c, r in e.whens:
            walk_aggs(c, out)
            walk_aggs(r, out)
        if e.default is not None:
            walk_aggs(e.default, out)
    elif isinstance(e, ast.Cast):
        walk_aggs(e.arg, out)
    elif isinstance(e, ast.Between):
        walk_aggs(e.arg, out)
        walk_aggs(e.lo, out)
        walk_aggs(e.hi, out)


@dataclass
class _Rel:
    alias: str
    table: object                 # ColumnTable
    local_preds: list = field(default_factory=list)


class Planner:
    def __init__(self, catalog):
        self.catalog = catalog

    # -- entry -------------------------------------------------------------

    def plan_select(self, sel: ast.Select) -> QueryPlan:
        if sel.relation is None:
            raise PlanError("SELECT without FROM is not supported yet")
        pool = B.ParamPool()

        rels, join_conds, left_joins = self._flatten_relations(sel.relation)
        if left_joins:
            raise PlanError("outer joins not supported yet")
        scope = B.Scope()
        for r in rels.values():
            for col in r.table.schema:
                internal = f"{r.alias}.{col.name}"
                scope.add(r.alias, col.name, B.ColumnBinding(
                    internal, col.dtype,
                    r.table.dictionaries.get(col.name)))
        self.scope = scope
        self.pool = pool
        binder = B.ExprBinder(scope, pool)

        # classify predicates ((a∧x)∨(a∧y) → a∧(x∨y) first: surfaces
        # join conditions buried in OR branches, e.g. TPC-H Q19)
        preds = []
        for p in conjuncts(sel.where) + join_conds:
            preds.extend(hoist_or_common(p))
        edges: list = []           # (alias_a, col_a, alias_b, col_b)
        residuals: list = []
        for p in preds:
            aliases = self._pred_aliases(p, rels, scope)
            if len(aliases) <= 1:
                alias = next(iter(aliases), None)
                if alias is None:
                    residuals.append(p)     # constant pred → keep at top
                else:
                    rels[alias].local_preds.append(p)
            elif (len(aliases) == 2 and isinstance(p, ast.BinOp)
                  and p.op == "=" and isinstance(p.left, ast.Name)
                  and isinstance(p.right, ast.Name)):
                la = self._name_alias(p.left, rels, scope)
                ra = self._name_alias(p.right, rels, scope)
                edges.append((la, p.left, ra, p.right))
            else:
                residuals.append(p)

        # column demand: everything referenced above the scans
        needed: set = set()        # internal names
        for item in sel.items:
            if isinstance(item.expr, ast.Star):
                for r in rels.values():
                    for col in r.table.schema:
                        needed.add(f"{r.alias}.{col.name}")
            else:
                self._demand(item.expr, needed)
        for e in sel.group_by:
            self._demand(e, needed)
        for o in sel.order_by:
            self._demand(o.expr, needed)
        if sel.having is not None:
            self._demand(sel.having, needed)
        for p in residuals:
            self._demand(p, needed)

        # fact table and join spanning tree (PK edges preferred: MapJoin
        # needs unique build keys; leftover edges become residual filters)
        fact = max(rels.values(), key=lambda r: r.table.num_rows).alias
        children, in_tree, leftovers = self._spanning_tree(fact, rels, edges)
        unreachable = set(rels) - in_tree
        if unreachable:
            raise PlanError(f"no join path to {sorted(unreachable)} "
                            "(cross joins not supported yet)")
        for (la, lname, ra, rname) in leftovers:
            residuals.append(ast.BinOp("=", lname, rname))
        for p in residuals:
            self._demand(p, needed)

        pipeline = self._build_pipeline(fact, rels, children, needed,
                                        binder, top=True)

        # residual predicates at top
        if residuals:
            prog = ir.Program()
            for p in residuals:
                prog.filter(binder.bind(p))
            pipeline.steps.append(("program", prog))

        plan = QueryPlan(pipeline=pipeline, params=pool.values)
        self._plan_projection_agg(sel, plan, binder)
        return plan

    # -- relations ---------------------------------------------------------

    def _flatten_relations(self, rel: ast.Relation):
        rels: dict[str, _Rel] = {}
        conds: list = []
        left_joins: list = []

        def add_table(t: ast.TableRef):
            alias = t.alias or t.name
            if alias in rels:
                raise PlanError(f"duplicate alias {alias}")
            rels[alias] = _Rel(alias, self.catalog.table(t.name))

        def walk(r):
            if isinstance(r, ast.TableRef):
                add_table(r)
            elif isinstance(r, ast.Join):
                if r.kind in ("inner", "cross"):
                    walk(r.left)
                    walk(r.right)
                    if r.on is not None:
                        conds.extend(conjuncts(r.on))
                elif r.kind == "left":
                    left_joins.append(r)
                    walk(r.left)
                    walk(r.right)
                else:
                    raise PlanError(f"{r.kind} join not supported yet")
            elif isinstance(r, ast.SubqueryRef):
                raise PlanError("FROM subqueries not supported yet")
            else:
                raise PlanError(f"bad relation {r!r}")

        walk(rel)
        return rels, conds, left_joins

    def _pred_aliases(self, p, rels, scope) -> set:
        names: set = set()
        walk_names(p, names)
        out = set()
        for parts in names:
            b = scope.try_resolve(parts)
            if b is None:
                raise PlanError(f"unknown column {'.'.join(parts)}")
            out.add(b.internal.split(".", 1)[0])
        return out

    def _name_alias(self, n: ast.Name, rels, scope) -> str:
        return scope.resolve(n.parts).internal.split(".", 1)[0]

    def _demand(self, e, needed: set):
        names: set = set()
        walk_names(e, names)
        for parts in names:
            b = self.scope.try_resolve(parts)
            if b is not None:
                needed.add(b.internal)

    # -- join tree ---------------------------------------------------------

    def _spanning_tree(self, fact: str, rels, edges):
        """Prim-style tree from the fact outward; prefer edges whose child
        column is the child table's (single-column) primary key so the
        broadcast-join build side has unique keys."""
        in_tree = {fact}
        children: dict[str, list] = {a: [] for a in rels}
        used = [False] * len(edges)
        while True:
            best = None
            for i, (la, lname, ra, rname) in enumerate(edges):
                if used[i]:
                    continue
                for (pa, pname, ca, cname) in ((la, lname, ra, rname),
                                               (ra, rname, la, lname)):
                    if pa in in_tree and ca not in in_tree:
                        col = self.scope.resolve(cname.parts).internal \
                            .split(".", 1)[1]
                        pk = rels[ca].table.key_columns
                        score = 2 if (len(pk) == 1 and pk[0] == col) \
                            else (1 if col in pk else 0)
                        cand = (score, -rels[ca].table.num_rows,
                                -i, pa, pname, ca, cname)
                        if best is None or cand[:3] > best[:3]:
                            best = cand
            if best is None:
                break
            _s, _r, neg_i, pa, pname, ca, cname = best
            used[-neg_i] = True
            in_tree.add(ca)
            children[pa].append((ca, pname, cname))
        # drop used edges; also edges between two in-tree tables stay residual
        leftovers = [e for i, e in enumerate(edges) if not used[i]]
        return children, in_tree, leftovers

    def _build_pipeline(self, alias: str, rels, children, needed,
                        binder, top: bool) -> Pipeline:
        r = rels[alias]
        # local predicate program
        pre = ir.Program()
        scan_cols: set = set()
        for p in r.local_preds:
            pre.filter(binder.bind(p))
            self._demand(p, scan_cols)

        # recurse into children first (they register join-key demand)
        join_steps = []
        for (child, my_name, child_name) in children[alias]:
            probe_b = self.scope.resolve(my_name.parts)
            build_b = self.scope.resolve(child_name.parts)
            scan_cols.add(probe_b.internal)
            child_needed = set(needed)
            child_needed.add(build_b.internal)
            sub = self._build_pipeline(child, rels, children,
                                       child_needed, binder, top=False)
            # keep the build key in the payload when referenced above
            # (e.g. it is a group key)
            payload = [c for c in sub.out_names
                       if c in needed
                       and (c != build_b.internal or build_b.internal in needed)]
            kind = "inner" if payload else "left_semi"
            join_steps.append(JoinStep(sub, build_b.internal,
                                       probe_b.internal, kind, payload))

        # own columns demanded from above
        own_cols = {n for n in needed
                    if n.split(".", 1)[0] == alias
                    and self.scope.by_alias[alias].get(n.split(".", 1)[1])}
        scan_cols |= own_cols

        storage_cols = []
        for internal in sorted(scan_cols):
            a, col = internal.split(".", 1)
            if a == alias:
                storage_cols.append((col, internal))
        scan = ScanSpec(r.table.name, storage_cols)
        self._extract_prune(pre, scan, r.table)

        out_names = sorted(own_cols)
        for js in join_steps:
            out_names.extend(js.payload)
        pipe = Pipeline(scan=scan,
                        pre_program=pre if pre.commands else None,
                        steps=[("join", js) for js in join_steps],
                        out_names=out_names)
        if not top:
            # build fragments materialize: project to outputs
            prog = ir.Program().project(out_names)
            pipe.partial = prog
        return pipe

    def _extract_prune(self, prog: ir.Program, scan: ScanSpec, table) -> None:
        from ydb_tpu.storage.pushdown import extract_prune_predicates
        internal_to_storage = {i: s for (s, i) in scan.columns}
        for (col, op, val) in extract_prune_predicates(prog):
            storage = internal_to_storage.get(col)
            if storage is None:
                continue
            dtype = table.schema.dtype(storage)
            if dtype.is_string and op != "eq":
                continue   # dictionary codes are unordered
            scan.prune.append((storage, op, val))

    # -- aggregation & projection ------------------------------------------

    def _plan_projection_agg(self, sel: ast.Select, plan: QueryPlan,
                             binder: B.ExprBinder) -> None:
        aggs: list = []
        for item in sel.items:
            if not isinstance(item.expr, ast.Star):
                walk_aggs(item.expr, aggs)
        if sel.having is not None:
            walk_aggs(sel.having, aggs)
        for o in sel.order_by:
            walk_aggs(o.expr, aggs)

        has_agg = bool(aggs) or bool(sel.group_by)

        # alias map for GROUP BY / ORDER BY references to select aliases
        alias_map = {item.alias: item.expr for item in sel.items if item.alias}

        def deref(e, positional=False):
            """Select-alias substitution; `positional` additionally resolves
            bare integers as 1-based select positions (ORDER BY 1 / GROUP
            BY 1) and must only be used at the top level of those clauses —
            never recursively, or nested literals would be rewritten."""
            if isinstance(e, ast.Name) and len(e.parts) == 1 \
                    and e.parts[0] in alias_map \
                    and self.scope.try_resolve(e.parts) is None:
                return alias_map[e.parts[0]]
            if positional and isinstance(e, ast.Literal) \
                    and isinstance(e.value, int) and e.type_hint is None \
                    and 1 <= e.value <= len(sel.items):
                return sel.items[e.value - 1].expr
            return e

        if has_agg:
            self._plan_aggregate(sel, plan, binder, aggs, deref)
        else:
            self._plan_simple(sel, plan, binder, deref)

    def _plan_simple(self, sel: ast.Select, plan: QueryPlan,
                     binder: B.ExprBinder, deref) -> None:
        """No aggregation: compute outputs per block; final sort/limit."""
        prog = ir.Program()
        output = []
        out_names = []
        for i, item in enumerate(sel.items):
            if isinstance(item.expr, ast.Star):
                for name in plan.pipeline.out_names:
                    output.append((name, name.split(".", 1)[1]))
                    out_names.append(name)
                continue
            e = binder.bind(item.expr)
            label = item.alias or (
                item.expr.parts[-1] if isinstance(item.expr, ast.Name)
                else f"column{i}")
            if isinstance(e, ir.Col):
                name = e.name
            else:
                name = f"expr{i}"
                prog.assign(name, e)
            output.append((name, label))
            out_names.append(name)

        uniq_outs = list(dict.fromkeys(out_names))
        if sel.distinct:
            # dedup per block, then globally; sort expressions are computed
            # after the final dedup (they would be dropped by the GroupBy)
            prog.group_by(uniq_outs, [])
            plan.pipeline.partial = prog
            final = ir.Program().group_by(uniq_outs, [])
            sort_keys, _extra = self._bind_sort(sel, binder.bind, out_names,
                                                final, alias_deref=deref)
            plan.final_program = final
        else:
            sort_keys, extra = self._bind_sort(sel, binder.bind, out_names,
                                               prog, alias_deref=deref)
            prog.project(list(dict.fromkeys(out_names + extra)))
            plan.pipeline.partial = prog
        plan.sort = sort_keys
        plan.limit, plan.offset = sel.limit, sel.offset
        plan.output = output

    def _plan_aggregate(self, sel: ast.Select, plan: QueryPlan,
                        binder: B.ExprBinder, agg_calls, deref) -> None:
        partial = ir.Program()
        # group keys
        key_specs = []     # (ast_expr, ir_expr, key_name)
        for i, ge in enumerate(sel.group_by):
            ge = deref(ge, positional=True)
            e = binder.bind(ge)
            if isinstance(e, ir.Col):
                name = e.name
            else:
                name = f"gk{i}"
                partial.assign(name, e)
            key_specs.append((ge, e, name))
        key_names = [k[2] for k in key_specs]

        # aggregate instances (deduped by bound signature)
        agg_map: dict = {}          # signature -> dict describing partial/final
        partial_aggs: list = []
        final_aggs: list = []
        n = 0

        sealed = [False]

        def register(call: ast.FuncCall) -> dict:
            nonlocal n
            if call.distinct:
                raise PlanError("DISTINCT aggregates not supported yet")
            # dedup on the AST (bound IR is not stable: LUT params get
            # fresh names per binding)
            if call.star or not call.args:
                sig = ("count_all",)
            else:
                sig = (call.name, repr(call.args[0]))
            inst = agg_map.get(sig)
            if inst is not None:
                return inst
            if sealed[0]:
                raise PlanError(
                    f"aggregate {call.name} appeared only after the partial "
                    "stage was sealed (planner bug)")
            inst = {"func": call.name}
            if call.star or not call.args:
                out = f"agg{n}"; n += 1
                partial_aggs.append(ir.Agg(out, "count_all"))
                final_aggs.append(ir.Agg(out, "sum", out))
                inst["col"] = out
            else:
                arg_ir = binder.bind(call.args[0])
                arg_name = arg_ir.name if isinstance(arg_ir, ir.Col) else None
                if arg_name is None:
                    arg_name = f"aggarg{n}"
                    partial.assign(arg_name, arg_ir)
                if call.name == "avg":
                    s, c = f"agg{n}s", f"agg{n}c"; n += 1
                    partial_aggs.append(ir.Agg(s, "sum", arg_name))
                    partial_aggs.append(ir.Agg(c, "count", arg_name))
                    final_aggs.append(ir.Agg(s, "sum", s))
                    final_aggs.append(ir.Agg(c, "sum", c))
                    inst["sum"], inst["count"] = s, c
                elif call.name == "count":
                    out = f"agg{n}"; n += 1
                    partial_aggs.append(ir.Agg(out, "count", arg_name))
                    final_aggs.append(ir.Agg(out, "sum", out))
                    inst["col"] = out
                elif call.name in ("sum", "min", "max", "some"):
                    out = f"agg{n}"; n += 1
                    f = call.name
                    partial_aggs.append(ir.Agg(out, f, arg_name))
                    final_aggs.append(ir.Agg(out, "sum" if f == "sum" else f, out))
                    inst["col"] = out
                else:
                    raise PlanError(f"aggregate {call.name} not supported")
            agg_map[sig] = inst
            return inst

        for call in agg_calls:
            register(call)

        partial.group_by(key_names, partial_aggs)
        sealed[0] = True
        plan.pipeline.partial = partial

        # -- final stage: merge aggs, having, outputs, sort ---------------
        final = ir.Program().group_by(key_names, final_aggs)

        planner = self

        class GroupBinder(B.ExprBinder):
            def bind(self, e):
                e = deref(e)
                # whole-expression match against a group key
                try:
                    be = binder.bind(e)
                except B.BindError:
                    be = None
                if be is not None:
                    for (_ge, ire, name) in key_specs:
                        if be == ire:
                            return ir.Col(name)
                if isinstance(e, ast.FuncCall) and e.name in B.AGG_NAMES:
                    inst = register(e)
                    if e.name == "avg":
                        return ir.call("div", ir.Col(inst["sum"]),
                                       ir.Col(inst["count"]))
                    return ir.Col(inst["col"])
                return super().bind(e)

        gbinder = GroupBinder(self.scope, self.pool)

        if sel.having is not None:
            final.filter(gbinder.bind(sel.having))

        output = []
        out_names = []
        for i, item in enumerate(sel.items):
            if isinstance(item.expr, ast.Star):
                raise PlanError("* with GROUP BY")
            e = gbinder.bind(item.expr)
            label = item.alias or (
                item.expr.parts[-1] if isinstance(item.expr, ast.Name)
                else f"column{i}")
            if isinstance(e, ir.Col):
                name = e.name
            else:
                name = f"out{i}"
                final.assign(name, e)
            output.append((name, label))
            out_names.append(name)

        sort_keys, extra = self._bind_sort(sel, gbinder.bind, out_names, final,
                                           alias_deref=deref)
        final.project(list(dict.fromkeys(out_names + extra)))
        plan.final_program = final
        plan.sort = sort_keys
        plan.limit, plan.offset = sel.limit, sel.offset
        plan.output = output

    def _bind_sort(self, sel, bind_fn, out_names: list, prog: ir.Program,
                   alias_deref) -> tuple[list, list]:
        sort_keys: list = []
        extra: list = []
        for j, o in enumerate(sel.order_by):
            e = bind_fn(alias_deref(o.expr, positional=True))
            if isinstance(e, ir.Col):
                name = e.name
            else:
                name = f"sort{j}"
                prog.assign(name, e)
                extra.append(name)
            nf = o.nulls_first
            if nf is None:
                nf = o.ascending       # YQL: NULL is smallest
            sort_keys.append(SortKey(name, o.ascending, nf))
        return sort_keys, extra
