"""AST → physical plan.

Combines the reference's logical/physical optimization + stage building:
  * predicate classification & pushdown into scans — the
    `KqpPushOlapFilter` rule (`kqp_opt_phy_olap_filter.cpp:527`);
  * join-tree construction from equi-edges with the largest table as the
    streaming fact side and broadcast build fragments — the MapJoin
    strategy of `dq_opt_join.cpp` (CBO/DPhyp ordering comes later);
  * two-phase aggregation: per-block partial GroupBy on device, final
    merge GroupBy — the BlockCombineHashed → BlockMergeFinalizeHashed
    split (`mkql_block_agg.cpp`);
  * HAVING/output/ORDER BY expression binding over the aggregated schema.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ydb_tpu.core import dtypes as dt
from ydb_tpu.ops import ir
from ydb_tpu.query import binder as B
from ydb_tpu.query.plan import JoinStep, Pipeline, QueryPlan, ScanSpec, SortKey
from ydb_tpu.sql import ast


class PlanError(Exception):
    pass


def conjuncts(e: Optional[ast.Expr]) -> list:
    if e is None:
        return []
    if isinstance(e, ast.BinOp) and e.op == "and":
        return conjuncts(e.left) + conjuncts(e.right)
    return [e]


def disjuncts(e: ast.Expr) -> list:
    if isinstance(e, ast.BinOp) and e.op == "or":
        return disjuncts(e.left) + disjuncts(e.right)
    return [e]


def _and_fold(parts: list) -> Optional[ast.Expr]:
    out = None
    for p in parts:
        out = p if out is None else ast.BinOp("and", out, p)
    return out


def _or_fold(parts: list) -> Optional[ast.Expr]:
    out = None
    for p in parts:
        out = p if out is None else ast.BinOp("or", out, p)
    return out


def hoist_or_common(pred: ast.Expr) -> list:
    """(a AND x) OR (a AND y) → a AND (x OR y): lift conjuncts shared by
    every OR branch to the top (TPC-H Q19's join condition shape) — the
    role of the reference's common-opt OR factoring."""
    out: list = []
    for p in conjuncts(pred):
        if not (isinstance(p, ast.BinOp) and p.op == "or"):
            out.append(p)
            continue
        branches = [conjuncts(b) for b in disjuncts(p)]
        common = [c for c in branches[0]
                  if all(c in b for b in branches[1:])]
        if not common:
            out.append(p)
            continue
        out.extend(common)
        rests = []
        degenerate = False
        for b in branches:
            rest = [c for c in b if c not in common]
            if not rest:
                degenerate = True   # one branch had only common conjuncts
                break
            rests.append(_and_fold(rest))
        if not degenerate:
            out.append(_or_fold(rests))
    return out


def walk_names(e, out: set):
    """Collect ast.Name nodes (skipping into subqueries)."""
    if isinstance(e, ast.Name):
        out.add(e.parts)
    elif isinstance(e, ast.BinOp):
        walk_names(e.left, out)
        walk_names(e.right, out)
    elif isinstance(e, ast.UnaryOp):
        walk_names(e.arg, out)
    elif isinstance(e, ast.FuncCall):
        for a in e.args:
            walk_names(a, out)
    elif isinstance(e, ast.Case):
        if e.operand is not None:
            walk_names(e.operand, out)
        for c, r in e.whens:
            walk_names(c, out)
            walk_names(r, out)
        if e.default is not None:
            walk_names(e.default, out)
    elif isinstance(e, (ast.Cast,)):
        walk_names(e.arg, out)
    elif isinstance(e, ast.Between):
        walk_names(e.arg, out)
        walk_names(e.lo, out)
        walk_names(e.hi, out)
    elif isinstance(e, (ast.InList,)):
        walk_names(e.arg, out)
        for i in e.items:
            walk_names(i, out)
    elif isinstance(e, (ast.Like, ast.IsNull)):
        walk_names(e.arg, out)


def walk_aggs(e, out: list):
    """Collect aggregate FuncCalls (no nesting into their args)."""
    if isinstance(e, ast.FuncCall):
        if e.name in B.AGG_NAMES:
            out.append(e)
            return
        for a in e.args:
            walk_aggs(a, out)
    elif isinstance(e, ast.BinOp):
        walk_aggs(e.left, out)
        walk_aggs(e.right, out)
    elif isinstance(e, ast.UnaryOp):
        walk_aggs(e.arg, out)
    elif isinstance(e, ast.Case):
        if e.operand is not None:
            walk_aggs(e.operand, out)
        for c, r in e.whens:
            walk_aggs(c, out)
            walk_aggs(r, out)
        if e.default is not None:
            walk_aggs(e.default, out)
    elif isinstance(e, ast.Cast):
        walk_aggs(e.arg, out)
    elif isinstance(e, ast.Between):
        walk_aggs(e.arg, out)
        walk_aggs(e.lo, out)
        walk_aggs(e.hi, out)


def _hash_key_expr(cols: list) -> ir.Expr:
    """Combined 64-bit hash over key columns (both join sides use this same
    expression, mirroring `ydb/core/formats/arrow/hash/calcer.cpp`)."""
    return _hash_key_expr_of([ir.Col(c) for c in cols])


def _hash_key_expr_of(exprs: list) -> ir.Expr:
    parts = [ir.call("hash64", e) for e in exprs]
    if len(parts) == 1:
        return parts[0]
    return ir.call("hash_combine", *parts)


@dataclass
class _Rel:
    alias: str
    table: object                 # ColumnTable
    local_preds: list = field(default_factory=list)


class Planner:
    def __init__(self, catalog):
        import threading
        self.catalog = catalog
        # planning keeps per-query working state on the instance (scope,
        # sub-spec lists, eff map); concurrent lock-free SELECTs must not
        # interleave plans. RLock: correlated subqueries re-enter via
        # _plan_inner. Planning is microseconds; execution runs outside.
        self._mu = threading.RLock()

    # -- entry -------------------------------------------------------------

    def plan_select(self, sel: ast.Select) -> QueryPlan:
        # literal lifting runs AFTER planning (paramlift.py): pruning,
        # selectivity, and dictionary folding all saw concrete values;
        # only the compiled artifact becomes value-free
        from ydb_tpu.query.bounds import annotate_plan
        from ydb_tpu.query.paramlift import lift_plan
        with self._mu:
            plan = lift_plan(self._plan_select_locked(sel))
            try:
                # bounds lattice (query/bounds.py): stamp every
                # pipeline's proven row bound — sizing only, must never
                # fail a query
                annotate_plan(plan, self.catalog)
            except Exception:          # noqa: BLE001 — sizing, not law
                pass
            try:
                # late-materialization sets (query/latemat.py): which
                # columns the fused path carries as row-ids — EXPLAIN
                # metadata; the executor recomputes against the actual
                # fused shape, so this too must never fail a query
                from ydb_tpu.query.latemat import annotate_plan as _lm
                _lm(plan)
            except Exception:          # noqa: BLE001 — sizing, not law
                pass
            return plan

    def plan_dq(self, sel: ast.Select, topology):
        """Lower a SELECT to a DQ stage graph (`ydb_tpu/dq/graph.py`) —
        the distributed counterpart of `plan_select`: stages own the
        programs (rendered stage SQL each worker engine compiles through
        plan_select locally), edges are UnionAll / HashShuffle /
        Broadcast / Merge channels. Column references resolve from THIS
        catalog's schemas; the cross-process router passes an RPC schema
        probe instead (`cluster/router.py`). `topology`: a
        `dq.lower.DqTopology`."""
        from ydb_tpu.dq.lower import lower_select

        def table_cols(table: str) -> list:
            return list(self.catalog.table(table).schema.names)
        return lower_select(sel, topology, table_cols)

    def _plan_select_locked(self, sel: ast.Select) -> QueryPlan:
        if sel.relation is None:
            raise PlanError("SELECT without FROM is not supported yet")
        pool = B.ParamPool()
        self._jk_counter = 0

        rels, join_conds, left_joins = self._flatten_relations(sel.relation)
        scope = B.Scope()
        for r in rels.values():
            for col in r.table.schema:
                internal = f"{r.alias}.{col.name}"
                scope.add(r.alias, col.name, B.ColumnBinding(
                    internal, col.dtype,
                    r.table.dictionaries.get(col.name)))
        # left-joined relations: columns visible (nullable — the join may
        # null-extend), but OUTSIDE the inner-join spanning tree
        self._left_specs = []
        self._left_post_preds: list = []
        for (tref, on) in left_joins:
            alias = tref.alias or tref.name
            if alias in rels or any(s["alias"] == alias
                                    for s in self._left_specs):
                raise PlanError(f"duplicate alias {alias}")
            try:
                table = self.catalog.table(tref.name)
            except KeyError as e:
                raise PlanError(str(e.args[0])) from e
            for col in table.schema:
                scope.add(alias, col.name, B.ColumnBinding(
                    f"{alias}.{col.name}", col.dtype.with_nullable(True),
                    table.dictionaries.get(col.name)))
            self._left_specs.append({"alias": alias, "table": table,
                                     "tref": tref, "on": on})
        self.scope = scope
        self.pool = pool
        binder = B.ExprBinder(scope, pool,
                              udfs=getattr(self.catalog, "udfs", None))
        left_aliases = {s["alias"] for s in self._left_specs}

        # classify each left join's ON conjuncts: equi pair vs build-local
        for spec in self._left_specs:
            alias = spec["alias"]
            pairs, local = [], []
            if spec["on"] is None:
                raise PlanError("LEFT JOIN requires an ON clause")
            for c in conjuncts(spec["on"]):
                aliases = self._pred_aliases(c, rels, scope)
                if aliases <= {alias}:
                    local.append(c)
                    continue
                ok = (isinstance(c, ast.BinOp) and c.op == "="
                      and isinstance(c.left, ast.Name)
                      and isinstance(c.right, ast.Name))
                if ok:
                    la = self._name_alias(c.left, rels, scope)
                    ra = self._name_alias(c.right, rels, scope)
                    if la == alias and ra not in left_aliases:
                        pairs.append((c.right, c.left))
                        continue
                    if ra == alias and la not in left_aliases:
                        pairs.append((c.left, c.right))
                        continue
                raise PlanError(f"unsupported LEFT JOIN condition {c!r}")
            if not pairs:
                raise PlanError("LEFT JOIN needs at least one equi-join "
                                "condition")
            spec["pairs"], spec["local"] = pairs, local

        # eager aggregation: a LEFT JOIN consumed only through aggregates
        # pre-aggregates its build by the join key — the expanding
        # duplicate-key probe (portioned-path cliff) stops existing
        sel = self._eager_agg_rewrite(sel, rels, scope)

        # classify predicates ((a∧x)∨(a∧y) → a∧(x∨y) first: surfaces
        # join conditions buried in OR branches, e.g. TPC-H Q19)
        preds = []
        for p in conjuncts(sel.where) + join_conds:
            preds.extend(hoist_or_common(p))

        # subquery extraction: IN/EXISTS → semi/anti join specs; scalar
        # subqueries → precompute params (uncorrelated) or decorrelated
        # aggregate joins (the KqpRewrite*-style flattening the reference
        # does in logical opt, `dq_opt_join.cpp` / kqp_opt_log)
        self._sub_specs: list = []
        self._init_subplans: list = []
        self._post_preds: list = []
        kept = []
        for p in preds:
            q = self._extract_subqueries(p, rels)
            if q is not None:
                kept.append(q)
        preds = kept
        if sel.having is not None or any(
                not isinstance(it.expr, ast.Star) and
                self._has_scalar_sub(it.expr) for it in sel.items):
            sel = ast.Select(**{**sel.__dict__})
            if sel.having is not None:
                sel.having = self._rewrite_scalar_subqueries(
                    sel.having, rels, allow_correlated=False)
            # scalar subqueries in the SELECT list precompute to params
            # (the KqpPhysicalTx TxResultBinding shape: q88-style reports)
            sel.items = [
                it if isinstance(it.expr, ast.Star) else ast.SelectItem(
                    self._rewrite_scalar_subqueries(
                        it.expr, rels, allow_correlated=False), it.alias)
                for it in sel.items]
        edges: list = []           # (alias_a, col_a, alias_b, col_b)
        residuals: list = []
        for p in preds:
            aliases = self._pred_aliases(p, rels, scope)
            if aliases & left_aliases:
                # WHERE over a null-extended side filters AFTER the left
                # join (standard SQL: ON extends, WHERE restricts)
                self._left_post_preds.append(p)
                continue
            if len(aliases) <= 1:
                alias = next(iter(aliases), None)
                if alias is None:
                    residuals.append(p)     # constant pred → keep at top
                else:
                    rels[alias].local_preds.append(p)
            elif (len(aliases) == 2 and isinstance(p, ast.BinOp)
                  and p.op == "=" and isinstance(p.left, ast.Name)
                  and isinstance(p.right, ast.Name)):
                la = self._name_alias(p.left, rels, scope)
                ra = self._name_alias(p.right, rels, scope)
                edges.append((la, p.left, ra, p.right))
            else:
                residuals.append(p)

        # column demand: everything referenced above the scans
        needed: set = set()        # internal names
        for item in sel.items:
            if isinstance(item.expr, ast.Star):
                if item.expr.table is not None:
                    if item.expr.table not in rels:
                        raise PlanError(
                            f"unknown table alias {item.expr.table!r} in "
                            f"{item.expr.table}.*")
                    star_rels = [rels[item.expr.table]]
                else:
                    star_rels = list(rels.values())
                for r in star_rels:
                    for col in r.table.schema:
                        needed.add(f"{r.alias}.{col.name}")
            else:
                self._demand(item.expr, needed)
        for e in sel.group_by:
            self._demand(e, needed)
        for o in sel.order_by:
            self._demand(o.expr, needed)
        if sel.having is not None:
            self._demand(sel.having, needed)
        for p in residuals:
            self._demand(p, needed)
        for spec in self._sub_specs:
            for (oexpr, _lbl) in spec["keys"]:
                self._demand(oexpr, needed)
            if spec.get("neq"):
                self._demand(spec["neq"][0], needed)
        for p in self._post_preds:
            self._demand(p, needed)

        # fact table and join spanning tree (PK edges preferred: MapJoin
        # needs unique build keys; leftover edges become residual filters).
        # Try every candidate fact and keep the tree with the fewest
        # non-PK build sides, ranked by ESTIMATED post-predicate
        # cardinality (query/stats.py — selectivity-aware effective rows,
        # the statistics-fed cost model of `dq_opt_join_cost_based.cpp`
        # over this executor's star-shaped plan space): the biggest
        # surviving row stream drives the scan, well-filtered relations
        # become broadcast builds however large their raw tables are.
        from ydb_tpu.query import stats as S
        eff = {a: S.effective_rows(a, r.table, r.local_preds)
               for a, r in rels.items()}
        # cost of a candidate tree: every relation is scanned whichever
        # orientation we pick, so orientations differ only in their BUILD
        # terms — each build side pays its EFFECTIVE rows (host transfer +
        # table construction), non-PK-unique builds penalized (expanding
        # probes, fused-path decline). Minimizing the build sum puts the
        # largest surviving row stream in the driving scan and strongly
        # filtered relations in tiny builds, whatever their raw sizes.
        # The non-unique penalty is steep: such builds force expanding
        # probes onto the portioned path, losing whole-query fusion — on
        # this platform a constant-factor cliff, not a linear cost.
        # And when such a build must also ATTACH PAYLOAD (columns of it
        # are demanded above the join — a payload-free join replans as a
        # fusable semi probe), the cliff is certain, so the DRIVER's
        # effective rows join the cost: the portioned fallback walks the
        # whole driving stream host-side. That term is what flips q12
        # onto the lineitem-driven orientation — a 32× penalty on a
        # well-filtered lineitem build still undercut scanning every
        # order through the host lane.
        _BAD_MULT = 32.0
        payload_alias = {a for a in rels
                         if any(n.split(".", 1)[0] == a for n in needed)}
        best = None
        for cand in rels:
            children_c, in_tree_c, leftovers_c, scores = \
                self._spanning_tree(cand, rels, edges, eff)
            unreachable = set(rels) - in_tree_c
            cost = 0.0
            defused = False
            for a in in_tree_c:
                if a != cand:
                    bad = scores.get(a, 0) < 2
                    cost += eff[a] * (_BAD_MULT if bad else 1.0)
                    defused = defused or (bad and a in payload_alias)
            if defused:
                cost += eff[cand]
            rank = (len(unreachable), cost)
            if best is None or rank < best[0]:
                best = (rank, cand, children_c, in_tree_c, leftovers_c)
        (rank, fact, children, in_tree, leftovers) = best
        self._eff_map = eff          # reused by _build_pipeline (EXPLAIN)
        unreachable = set(rels) - in_tree
        if unreachable:
            raise PlanError(f"no join path to {sorted(unreachable)} "
                            "(cross joins not supported yet)")
        for (la, lname, ra, rname) in leftovers:
            residuals.append(ast.BinOp("=", lname, rname))
        for p in residuals:
            self._demand(p, needed)
        for spec in self._left_specs:
            for (p_ast, _b) in spec["pairs"]:
                self._demand(p_ast, needed)
        for p in self._left_post_preds:
            self._demand(p, needed)

        pipeline = self._build_pipeline(fact, rels, children, needed,
                                        binder, top=True)

        # residual predicates at top
        if residuals:
            prog = ir.Program()
            for p in residuals:
                prog.filter(binder.bind(p))
            pipeline.steps.append(("program", prog))

        # null-extending (left outer) joins + their post-join filters
        self._attach_left_joins(pipeline, binder, needed)

        # semi/anti/scalar subquery joins + their filters
        self._attach_sub_specs(pipeline, binder)

        plan = QueryPlan(pipeline=pipeline, params=pool.values,
                         init_subplans=list(self._init_subplans))
        plan.star_order = [f"{r.alias}.{col.name}"
                           for r in rels.values() for col in r.table.schema]
        self._plan_projection_agg(sel, plan, binder)
        return plan

    # -- relations ---------------------------------------------------------

    def _flatten_relations(self, rel: ast.Relation):
        rels: dict[str, _Rel] = {}
        conds: list = []
        left_joins: list = []

        def add_table(t: ast.TableRef):
            alias = t.alias or t.name
            if alias in rels:
                raise PlanError(f"duplicate alias {alias}")
            try:
                rels[alias] = _Rel(alias, self.catalog.table(t.name))
            except KeyError as e:
                raise PlanError(str(e.args[0])) from e

        def walk(r):
            if isinstance(r, ast.TableRef):
                add_table(r)
            elif isinstance(r, ast.Join):
                if r.kind in ("inner", "cross"):
                    walk(r.left)
                    walk(r.right)
                    if r.on is not None:
                        conds.extend(conjuncts(r.on))
                elif r.kind == "left":
                    walk(r.left)
                    # the nullable side stays OUT of the inner-join tree; it
                    # becomes a null-extending build fragment attached after
                    # the inner pipeline (`CommonJoinCore` left semantics)
                    if not isinstance(r.right, ast.TableRef):
                        raise PlanError("LEFT JOIN right side must be a "
                                        "table (materialize subqueries "
                                        "first)")
                    left_joins.append((r.right, r.on))
                elif r.kind == "right":
                    # A RIGHT JOIN B == B LEFT JOIN A
                    walk(r.right)
                    if not isinstance(r.left, ast.TableRef):
                        raise PlanError("RIGHT JOIN left side must be a "
                                        "table")
                    left_joins.append((r.left, r.on))
                else:
                    raise PlanError(f"{r.kind} join not supported yet")
            elif isinstance(r, ast.SubqueryRef):
                raise PlanError("FROM subqueries must be materialized by "
                                "the engine before planning")
            else:
                raise PlanError(f"bad relation {r!r}")

        walk(rel)
        return rels, conds, left_joins

    def _pred_aliases(self, p, rels, scope) -> set:
        names: set = set()
        walk_names(p, names)
        out = set()
        for parts in names:
            b = scope.try_resolve(parts)
            if b is None:
                raise PlanError(f"unknown column {'.'.join(parts)}")
            out.add(b.internal.split(".", 1)[0])
        return out

    def _name_alias(self, n: ast.Name, rels, scope) -> str:
        return scope.resolve(n.parts).internal.split(".", 1)[0]

    def _demand(self, e, needed: set):
        names: set = set()
        walk_names(e, names)
        for parts in names:
            b = self.scope.try_resolve(parts)
            if b is not None:
                needed.add(b.internal)

    # -- join tree ---------------------------------------------------------

    def _spanning_tree(self, fact: str, rels, edges, eff=None):
        """Prim-style tree from the fact outward over alias-pair edge
        GROUPS (all equi-conditions between a pair join together — composite
        keys). Prefer groups whose child columns cover the child table's
        primary key, so the broadcast-join build side has unique keys;
        among candidates, attach the smallest ESTIMATED child first
        (`eff`: effective-cardinality map from query/stats.py)."""
        if eff is None:
            eff = {a: r.table.num_rows for a, r in rels.items()}
        groups: dict[tuple, list] = {}
        for (la, lname, ra, rname) in edges:
            key = (la, ra) if la <= ra else (ra, la)
            pair = (lname, rname) if la <= ra else (rname, lname)
            groups.setdefault(key, []).append(pair)
        group_list = list(groups.items())

        in_tree = {fact}
        children: dict[str, list] = {a: [] for a in rels}
        used = [False] * len(group_list)
        scores: dict = {}   # child alias -> PK-coverage score (2 = unique)
        while True:
            best = None
            for i, ((a1, a2), pairs) in enumerate(group_list):
                if used[i]:
                    continue
                for (pa, ca, flip) in ((a1, a2, False), (a2, a1, True)):
                    if pa in in_tree and ca not in in_tree:
                        child_cols = {
                            self.scope.resolve((p[1] if not flip else p[0]).parts)
                            .internal.split(".", 1)[1] for p in pairs}
                        pk = set(rels[ca].table.key_columns)
                        score = 2 if pk <= child_cols \
                            else (1 if child_cols & pk else 0)
                        cand = (score, -eff[ca], -i,
                                pa, ca, flip)
                        if best is None or cand[:3] > best[:3]:
                            best = cand
            if best is None:
                break
            _s, _r, neg_i, pa, ca, flip = best
            scores[ca] = _s
            used[-neg_i] = True
            in_tree.add(ca)
            pairs = group_list[-neg_i][1]
            oriented = [(cn, pn) if flip else (pn, cn) for (pn, cn) in pairs]
            children[pa].append((ca, oriented))   # [(parent_name, child_name)]
        leftovers = []
        for i, ((a1, a2), pairs) in enumerate(group_list):
            if not used[i]:
                for (lname, rname) in pairs:
                    leftovers.append((a1, lname, a2, rname))
        return children, in_tree, leftovers, scores

    def _build_pipeline(self, alias: str, rels, children, needed,
                        binder, top: bool) -> Pipeline:
        r = rels[alias]
        # local predicate program
        pre = ir.Program()
        scan_cols: set = set()
        for p in r.local_preds:
            pre.filter(binder.bind(p))
            self._demand(p, scan_cols)

        # recurse into children first (they register join-key demand)
        join_steps = []       # [(JoinStep, post_program | None)]
        for (child, pairs) in children[alias]:
            probe_bs = [self.scope.resolve(pn.parts) for (pn, _cn) in pairs]
            build_bs = [self.scope.resolve(cn.parts) for (_pn, cn) in pairs]
            for b in probe_bs:
                scan_cols.add(b.internal)
            child_needed = set(needed)
            for b in build_bs:
                child_needed.add(b.internal)
            sub = self._build_pipeline(child, rels, children,
                                       child_needed, binder, top=False)
            if len(pairs) == 1:
                build_key, probe_key = build_bs[0].internal, probe_bs[0].internal
                # keep the build key in the payload when referenced above
                payload = [c for c in sub.out_names
                           if c in needed
                           and (c != build_key or build_key in needed)]
                kind = "inner" if payload else "left_semi"
                join_steps.append((JoinStep(sub, build_key, probe_key,
                                            kind, payload), None))
            else:
                # composite key: join on a combined 64-bit hash of the key
                # columns on both sides, then verify each equality post-join
                # (collision guard) — the packed-key analog of GraceJoin's
                # multi-column keys (`mkql_grace_join.cpp`)
                jk = f"__jk{self._jk_counter}"
                self._jk_counter += 1
                pre.assign(jk, _hash_key_expr([b.internal for b in probe_bs]))
                bjk = f"{jk}b"
                sub_partial = ir.Program()
                # string key columns from a DIFFERENT dictionary than the
                # probe side's must remap codes before hashing/verifying —
                # raw code equality across dictionaries is meaningless
                hash_cols, remap_names = [], []
                verify = ir.Program()
                for i, (pb, bb) in enumerate(zip(probe_bs, build_bs)):
                    if pb.dtype.is_string and pb.dictionary is not None \
                            and bb.dictionary is not None \
                            and bb.dictionary is not pb.dictionary:
                        src = bb.dictionary.values_array()
                        lut = np.full(max(len(src), 1), -2, dtype=np.int32)
                        for ci, v in enumerate(src):
                            lut[ci] = pb.dictionary.encode_existing(v)
                        p = self.pool.add(
                            lut, dt.DType(dt.Kind.STRING, False),
                            is_array=True)
                        self.pool.param_dicts[p.name] = pb.dictionary
                        rname = f"{jk}r{i}"
                        sub_partial.assign(
                            rname, ir.call("take_lut",
                                           ir.Col(bb.internal), p))
                        remap_names.append(rname)
                        hash_cols.append(rname)
                        verify.filter(ir.call("eq", ir.Col(pb.internal),
                                              ir.Col(rname)))
                    else:
                        hash_cols.append(bb.internal)
                        verify.filter(ir.call("eq", ir.Col(pb.internal),
                                              ir.Col(bb.internal)))
                sub_partial.assign(bjk, _hash_key_expr(hash_cols))
                sub_partial.project(sub.out_names + remap_names + [bjk])
                sub.partial = sub_partial
                payload = list(dict.fromkeys(
                    [c for c in sub.out_names if c in needed]
                    + [b.internal for b in build_bs] + remap_names))
                join_steps.append(
                    (JoinStep(sub, bjk, jk, "inner", payload,
                              build_key_cols=[b.internal
                                              for b in build_bs]),
                     verify))

        # own columns demanded from above
        own_cols = {n for n in needed
                    if n.split(".", 1)[0] == alias
                    and self.scope.by_alias[alias].get(n.split(".", 1)[1])}
        scan_cols |= own_cols

        storage_cols = []
        for internal in sorted(scan_cols):
            a, col = internal.split(".", 1)
            if a == alias:
                storage_cols.append((col, internal))
        scan = ScanSpec(r.table.name, storage_cols)
        est = getattr(self, "_eff_map", {}).get(alias)
        if est is None:              # single-relation plans skip the tree
            from ydb_tpu.query import stats as S
            est = S.effective_rows(alias, r.table, r.local_preds)
        scan.est_rows = round(est, 1)
        self._extract_prune(pre, scan, r.table)

        out_names = sorted(own_cols)
        steps = []
        for (js, verify) in join_steps:
            out_names.extend(c for c in js.payload if c not in out_names)
            steps.append(("join", js))
            if verify is not None:
                steps.append(("program", verify))
        pipe = Pipeline(scan=scan,
                        pre_program=pre if pre.commands else None,
                        steps=steps,
                        out_names=out_names)
        if not top:
            # build fragments materialize: project to outputs
            prog = ir.Program().project(out_names)
            pipe.partial = prog
        return pipe

    def _extract_prune(self, prog: ir.Program, scan: ScanSpec, table) -> None:
        from ydb_tpu.storage.pushdown import extract_prune_predicates
        internal_to_storage = {i: s for (s, i) in scan.columns}
        for (col, op, val) in extract_prune_predicates(prog):
            storage = internal_to_storage.get(col)
            if storage is None:
                continue
            dtype = table.schema.dtype(storage)
            if dtype.is_string and op != "eq":
                continue   # dictionary codes are unordered
            scan.prune.append((storage, op, val))

    # -- left outer joins --------------------------------------------------

    # -- eager aggregation (LEFT JOIN build pre-aggregation) ---------------

    _EAGER_FNS = ("count", "sum", "min", "max")

    def _eager_agg_rewrite(self, sel: ast.Select, rels, scope):
        """Pre-aggregate a LEFT JOIN build below the join (classic eager
        aggregation) when the joined relation is consumed ONLY through
        count/sum/min/max aggregates over its columns:

            c LEFT JOIN o ON c.k = o.k [AND o-local] ... count(o.x)
        →   build' = SELECT o.k, count(o.x) FROM o [WHERE local] GROUP BY o.k
            c LEFT JOIN build' ON c.k = o.k ... sum(coalesce(o.cnt, 0))

        The payoff is the bounds lattice's: the pre-aggregated build is
        UNIQUE-keyed (grouped by the join key), so the expanding
        duplicate-key probe — the shape that declines whole-plan fusion
        and runs the portioned host lane (q13's measured 89.5% wall) —
        stops existing, and the join becomes row-preserving with a
        key-domain-bounded build. Exact per SQL semantics: per-key
        partial counts SUM over the probe stream (an unmatched probe row
        contributes coalesce(NULL, 0) = 0); sum/min/max merge with
        themselves, and their all-NULL-group result stays NULL through
        the null-extended payload. The rewrite only fires when every
        reference to the alias outside the ON clause sits in a
        qualifying aggregate — any other use (group key, scalar context,
        subquery, string min/max, DISTINCT) keeps the expanding join."""
        import dataclasses as _dc

        from ydb_tpu.query.bounds import bounds_enabled
        if not sel.group_by or not self._left_specs:
            return sel
        if not bounds_enabled():       # lever off: capacity-shaped plans
            return sel
        if any(isinstance(it.expr, ast.Star) for it in sel.items):
            return sel

        def alias_of(parts) -> Optional[str]:
            b = scope.try_resolve(parts)
            return b.internal.split(".", 1)[0] if b is not None else None

        def scan(e, alias, calls) -> bool:
            """True iff every reference to `alias` under `e` is the sole
            Name argument of a qualifying aggregate (collected into
            `calls`). Conservative: unknown node kinds fail."""
            if e is None or isinstance(e, (ast.Literal, ast.BoundParam)):
                return True
            if isinstance(e, ast.Name):
                return alias_of(e.parts) != alias
            if isinstance(e, ast.FuncCall):
                if e.name in B.AGG_NAMES:
                    refs: set = set()
                    walk_names(e, refs)
                    if not any(alias_of(p) == alias for p in refs):
                        # a probe-side aggregate sees k copies of each
                        # matched probe row in the EXPANDING join; the
                        # rewrite makes the probe row-preserving, so only
                        # multiplicity-insensitive aggregates (min/max,
                        # DISTINCT) keep their value — count(*)/sum/avg
                        # over the probe stream disqualify the spec
                        return bool(e.distinct) or e.name in ("min", "max")
                    if (e.name not in self._EAGER_FNS or e.distinct
                            or e.star or len(e.args) != 1
                            or not isinstance(e.args[0], ast.Name)):
                        return False
                    b = scope.try_resolve(e.args[0].parts)
                    if b is None or (b.dtype.is_string
                                     and e.name in ("min", "max")):
                        return False
                    calls.append(e)
                    return True
                return all(scan(a, alias, calls) for a in e.args)
            if isinstance(e, ast.BinOp):
                return scan(e.left, alias, calls) \
                    and scan(e.right, alias, calls)
            if isinstance(e, ast.UnaryOp):
                return scan(e.arg, alias, calls)
            if isinstance(e, ast.Case):
                parts = ([e.operand] if e.operand is not None else []) \
                    + [x for (c, r) in e.whens for x in (c, r)] \
                    + ([e.default] if e.default is not None else [])
                return all(scan(x, alias, calls) for x in parts)
            if isinstance(e, ast.Cast):
                return scan(e.arg, alias, calls)
            if isinstance(e, ast.Between):
                return all(scan(x, alias, calls)
                           for x in (e.arg, e.lo, e.hi))
            if isinstance(e, ast.InList):
                return all(scan(x, alias, calls)
                           for x in (e.arg,) + tuple(e.items))
            if isinstance(e, (ast.Like, ast.IsNull)):
                return scan(e.arg, alias, calls)
            return False               # subqueries / unknown nodes

        def agg_sig(e: ast.FuncCall):
            return (e.name, repr(e.args[0]))

        rewritten = False
        for spec in self._left_specs:
            if len(spec["pairs"]) != 1:
                continue
            alias = spec["alias"]
            # keys / filters must not touch the alias at all (WHERE over
            # the null-extended side restricts post-join — incompatible)
            no_ref: list = []
            if not all(scan(e, alias, no_ref) and not no_ref
                       for e in list(sel.group_by) + [sel.where]):
                continue
            calls: list = []
            agg_exprs = [it.expr for it in sel.items] \
                + [o.expr for o in sel.order_by] \
                + ([sel.having] if sel.having is not None else [])
            if not all(scan(e, alias, calls) for e in agg_exprs):
                continue
            if not calls:
                continue
            # one synthetic payload per distinct (fn, arg)
            insts: dict = {}           # sig -> (payload_col, sub_item_expr)
            repl: dict = {}            # sig -> outer replacement FuncCall
            for c in calls:
                sig = agg_sig(c)
                if sig in insts:
                    continue
                pname = f"__ea{len(insts)}"
                ref = ast.Name((alias, pname))
                if c.name == "count":
                    # int64-cast partial counts: coalesce/sum over a
                    # uint64 payload and an int literal would promote;
                    # the outer cast restores count's uint64 result type
                    # so the lever cannot flip the output schema
                    sub_e = ast.Cast(c, "int64")
                    out_dt = dt.DType(dt.Kind.INT64, True)
                    repl[sig] = ast.Cast(ast.FuncCall("sum", (ast.FuncCall(
                        "coalesce", (ref, ast.Literal(0))),)), "uint64")
                else:
                    sub_e = c
                    arg_dt = scope.resolve(c.args[0].parts).dtype
                    from ydb_tpu.ops.ir import agg_result_dtype
                    out_dt = agg_result_dtype(
                        c.name if c.name == "sum" else "some",
                        arg_dt).with_nullable(True)
                    repl[sig] = ast.FuncCall(c.name, (ref,))
                insts[sig] = (pname, sub_e)
                scope.add(alias, pname,
                          B.ColumnBinding(f"{alias}.{pname}", out_dt, None))
            spec["eager"] = list(insts.values())

            def walk(e):
                if isinstance(e, ast.FuncCall) and e.name in B.AGG_NAMES \
                        and not e.distinct and not e.star and e.args:
                    r = repl.get(agg_sig(e))
                    if r is not None:
                        return r

                def rw(v):
                    if isinstance(v, tuple):
                        return tuple(rw(x) for x in v)
                    if hasattr(v, "__dataclass_fields__"):
                        return walk(v)
                    return v

                kw = {f: rw(getattr(e, f))
                      for f in e.__dataclass_fields__}
                return _dc.replace(e, **kw)

            sel = ast.Select(**{**sel.__dict__})
            sel.items = [ast.SelectItem(walk(it.expr), it.alias)
                         for it in sel.items]
            sel.order_by = [ast.OrderItem(walk(o.expr), o.ascending,
                                          o.nulls_first)
                            for o in sel.order_by]
            if sel.having is not None:
                sel.having = walk(sel.having)
            rewritten = True
        if rewritten:
            from ydb_tpu.utils.metrics import GLOBAL
            GLOBAL.inc("bounds/eager_agg_rewrites")
        return sel

    def _attach_left_joins(self, pipeline, binder: B.ExprBinder,
                           needed: set) -> None:
        """Append a null-extending build fragment per LEFT JOIN: the right
        side plans as its own (filtered) subquery whose output labels are
        the internal `alias.col` names, so payload columns land in the
        outer scope's namespace. Duplicate build keys take the expanding
        probe automatically."""
        for spec in self._left_specs:
            alias = spec["alias"]
            pairs = spec["pairs"]
            build_cols = [bn.parts[-1] for (_p, bn) in pairs]
            if spec.get("eager"):
                # eager aggregation (`_eager_agg_rewrite`): the build
                # GROUPS by its join key — unique-keyed by construction,
                # so the probe is row-preserving and fusion survives
                bk = build_cols[0]
                items = [ast.SelectItem(ast.Name((alias, bk)),
                                        f"{alias}.{bk}")]
                items += [ast.SelectItem(sub_e, f"{alias}.{pname}")
                          for (pname, sub_e) in spec["eager"]]
                sub = ast.Select(items=items,
                                 relation=ast.TableRef(spec["tref"].name,
                                                       alias),
                                 where=_and_fold(spec["local"]),
                                 group_by=[ast.Name((alias, bk))])
                right_cols = [bk] + [p for (p, _e) in spec["eager"]]
            else:
                right_cols = sorted({n.split(".", 1)[1] for n in needed
                                     if n.startswith(alias + ".")}
                                    | set(build_cols))
                items = [ast.SelectItem(ast.Name((alias, col)),
                                        f"{alias}.{col}")
                         for col in right_cols]
                sub = ast.Select(items=items,
                                 relation=ast.TableRef(spec["tref"].name,
                                                       alias),
                                 where=_and_fold(spec["local"]))
            jplan = self._plan_inner(sub)
            payload = [f"{alias}.{c}" for c in right_cols]

            if len(pairs) == 1:
                e = binder.bind(pairs[0][0])
                if isinstance(e, ir.Col):
                    probe_key = e.name
                else:
                    probe_key = f"__lj{self._jk_counter}"
                    self._jk_counter += 1
                    pre = ir.Program().assign(probe_key, e)
                    pipeline.steps.append(("program", pre))
                js = JoinStep(jplan, f"{alias}.{build_cols[0]}", probe_key,
                              "left", payload)
                pipeline.steps.append(("join", js))
            else:
                # composite key: hash-combine both sides (host-side for
                # the build via build_hash_keys, in-program for the
                # probe), then verify each equality POST-join — a hash
                # collision cannot filter the row (LEFT keeps it), so
                # mismatched payloads are NULLed instead
                bound = [binder.bind(p_ast) for (p_ast, _b) in pairs]
                for (p_ast, _b), e in zip(pairs, bound):
                    b = self.scope.try_resolve(p_ast.parts) \
                        if isinstance(p_ast, ast.Name) else None
                    if b is not None and b.dtype.is_string:
                        raise PlanError(
                            "composite LEFT JOIN over string keys is "
                            "not supported yet")
                probe_key = f"__lj{self._jk_counter}"
                self._jk_counter += 1
                pre = ir.Program().assign(
                    probe_key,
                    _hash_key_expr_of(bound))
                pipeline.steps.append(("program", pre))
                bh = f"{alias}.__ljbh"
                js = JoinStep(jplan, bh, probe_key, "left", payload,
                              build_hash_keys=[f"{alias}.{c}"
                                               for c in build_cols])
                pipeline.steps.append(("join", js))
                ver = ir.Program()
                ok = None
                for e, bc in zip(bound, build_cols):
                    t = ir.call("eq", e, ir.Col(f"{alias}.{bc}"))
                    ok = t if ok is None else ir.call("and", ok, t)
                okname = f"__ljok{self._jk_counter}"
                self._jk_counter += 1
                ver.assign(okname, ok)
                for pcol in payload:
                    ver.assign(pcol, ir.call(
                        "if", ir.Col(okname), ir.Col(pcol),
                        ir.call("typed_null", ir.Col(pcol))))
                pipeline.steps.append(("program", ver))
            pipeline.out_names.extend(
                c for c in payload if c not in pipeline.out_names)

        if self._left_post_preds:
            prog = ir.Program()
            for p in self._left_post_preds:
                prog.filter(binder.bind(p))
            pipeline.steps.append(("program", prog))

    # -- subqueries --------------------------------------------------------

    def _inner_scope(self, inner_sel: ast.Select):
        """Scope + relation map for a subquery's own tables."""
        inner_rels, _conds, _lj = self._flatten_relations(inner_sel.relation)
        scope = B.Scope()
        for r in inner_rels.values():
            for col in r.table.schema:
                scope.add(r.alias, col.name, B.ColumnBinding(
                    f"{r.alias}.{col.name}", col.dtype,
                    r.table.dictionaries.get(col.name)))
        return scope

    def _split_correlations(self, inner_sel: ast.Select,
                            with_neq: bool = False):
        """Pull `inner_col = outer_col` conjuncts out of the subquery's
        WHERE (the equality-decorrelation the reference performs in logical
        optimization). Returns (inner select w/o them, [(inner_name_ast,
        outer_name_ast)]) — plus, when `with_neq`, the list of
        `inner_col <> outer_col` conjuncts as a third element (decorrelated
        via the min/max trick in `_add_semi_spec`)."""
        inner_scope = self._inner_scope(inner_sel)
        rest, pairs, neqs = [], [], []
        for c in conjuncts(inner_sel.where):
            names: set = set()
            walk_names(c, names)
            outer = [p for p in names if inner_scope.try_resolve(p) is None]
            if not outer:
                rest.append(c)
                continue
            ok = (isinstance(c, ast.BinOp) and c.op in ("=", "<>")
                  and isinstance(c.left, ast.Name)
                  and isinstance(c.right, ast.Name))
            if not ok or (c.op == "<>" and not with_neq):
                raise PlanError(
                    f"unsupported correlated predicate {c!r} (only "
                    "inner_col = outer_col correlation is decorrelated)")
            dest = pairs if c.op == "=" else neqs
            if inner_scope.try_resolve(c.left.parts) is not None:
                dest.append((c.left, c.right))
            elif inner_scope.try_resolve(c.right.parts) is not None:
                dest.append((c.right, c.left))
            else:
                raise PlanError(f"correlated predicate {c!r} references no "
                                "subquery column")
        new_sel = ast.Select(**{**inner_sel.__dict__})
        new_sel.where = _and_fold(rest)
        if with_neq:
            return new_sel, pairs, neqs
        return new_sel, pairs

    def _expr_dtype(self, e: ast.Expr, scope: B.Scope):
        """Static result dtype of a (possibly aggregate) expression."""
        from ydb_tpu.core import dtypes as dt
        from ydb_tpu.ops.ir import agg_result_dtype
        if isinstance(e, ast.FuncCall) and e.name in B.AGG_NAMES:
            if e.star or not e.args:
                return dt.DType(dt.Kind.UINT64, False)
            if e.name == "avg":
                return dt.DType(dt.Kind.FLOAT64, True)
            arg = self._expr_dtype(e.args[0], scope)
            return agg_result_dtype("sum" if e.name == "sum" else "some",
                                    arg).with_nullable(True)
        if isinstance(e, ast.BinOp):
            if e.op in ("and", "or", "=", "<>", "<", "<=", ">", ">="):
                return dt.DType(dt.Kind.BOOL, True)
            lt = self._expr_dtype(e.left, scope)
            rt = self._expr_dtype(e.right, scope)
            if e.op == "/":
                return dt.DType(dt.Kind.FLOAT64, lt.nullable or rt.nullable)
            return dt.common_numeric(lt, rt)
        if isinstance(e, ast.UnaryOp):
            return self._expr_dtype(e.arg, scope)
        if isinstance(e, ast.Name):
            return scope.resolve(e.parts).dtype
        f = B._try_fold(e)
        if f is not None:
            return f.dtype
        raise PlanError(f"cannot type subquery expression {e!r}")

    def _plan_inner(self, inner_sel: ast.Select) -> "QueryPlan":
        return Planner(self.catalog).plan_select(inner_sel)

    def _extract_subqueries(self, p: ast.Expr, rels):
        """Consume IN/EXISTS predicates into semi/anti-join specs; rewrite
        scalar subqueries. Returns the remaining predicate (or None if the
        conjunct was fully consumed)."""
        if isinstance(p, ast.UnaryOp) and p.op == "not":
            a = p.arg
            if isinstance(a, ast.Exists):
                p = ast.Exists(a.query, not a.negated)
            elif isinstance(a, ast.InSubquery):
                p = ast.InSubquery(a.arg, a.query, not a.negated)
        if isinstance(p, ast.InSubquery):
            self._add_semi_spec([p.arg], p.query, p.negated,
                                first_item_key=True)
            return None
        if isinstance(p, ast.Exists):
            self._add_semi_spec([], p.query, p.negated, first_item_key=False)
            return None
        n_before = len(self._sub_specs)
        p = self._rewrite_embedded_membership(p)
        if len(self._sub_specs) > n_before:
            # the predicate now references mark columns that only exist
            # AFTER the mark joins attach — apply it post-join. Scalar
            # subqueries sharing the predicate still need their rewrite
            # (they would otherwise reach the binder raw).
            rewritten, _corr = self._rewrite_scalars(p)
            self._post_preds.append(p if rewritten is None else rewritten)
            return None
        rewritten, correlated = self._rewrite_scalars(p)
        if rewritten is None:
            return p
        if correlated:
            self._post_preds.append(rewritten)
            return None
        return rewritten

    def _rewrite_embedded_membership(self, p):
        """IN/EXISTS sitting INSIDE a larger predicate (an OR arm, a CASE
        condition): each becomes a MARK join whose bool bit substitutes
        for the membership test — `a IN s1 OR a IN s2` runs as two mark
        joins + one filter (ds35-family demographics queries)."""
        import dataclasses as _dc

        def walk(e):
            # normalize NOT (x IN (...)) / NOT EXISTS the way the
            # top-level extraction does, so the negated-inside-OR guard
            # actually fires instead of silently planning a plain mark
            if isinstance(e, ast.UnaryOp) and e.op == "not":
                a = e.arg
                if isinstance(a, ast.Exists):
                    e = ast.Exists(a.query, not a.negated)
                elif isinstance(a, ast.InSubquery):
                    e = ast.InSubquery(a.arg, a.query, not a.negated)
            if isinstance(e, (ast.InSubquery, ast.Exists)):
                n = len(self._sub_specs) + len(self._init_subplans)
                mark = f"__s{n}m"
                if isinstance(e, ast.InSubquery):
                    self._add_semi_spec([e.arg], e.query, e.negated,
                                        first_item_key=True,
                                        mark_pred=mark)
                else:
                    self._add_semi_spec([], e.query, e.negated,
                                        first_item_key=False,
                                        mark_pred=mark)
                from ydb_tpu.core import dtypes as dt
                self.scope.add("__sub", mark, B.ColumnBinding(
                    mark, dt.DType(dt.Kind.BOOL, False)))
                return ast.Name((mark,))
            if not hasattr(e, "__dataclass_fields__") \
                    or isinstance(e, (ast.ScalarSubquery, ast.Select)):
                return e

            def rw(v):
                if isinstance(v, tuple):
                    return tuple(rw(x) for x in v)
                if hasattr(v, "__dataclass_fields__"):
                    return walk(v)
                return v
            out = {f: rw(getattr(e, f)) for f in e.__dataclass_fields__}
            try:
                return _dc.replace(e, **out)
            except TypeError:
                return e
        return walk(p)

    def _has_scalar_sub(self, e) -> bool:
        """Generic dataclass-field walk (matches the shapes the rewriter's
        walk at `_rewrite_scalars` can reach). Exists/InSubquery bodies
        are handled by the semi-join machinery, not the scalar rewrite —
        don't descend into them."""
        if isinstance(e, ast.ScalarSubquery):
            return True
        if isinstance(e, (ast.Exists, ast.InSubquery)) \
                or not hasattr(e, "__dataclass_fields__"):
            return False

        def any_sub(v) -> bool:
            if isinstance(v, tuple):
                return any(any_sub(x) for x in v)
            return hasattr(v, "__dataclass_fields__") \
                and self._has_scalar_sub(v)
        return any(any_sub(getattr(e, f)) for f in e.__dataclass_fields__)

    def _rewrite_scalar_subqueries(self, p, rels, allow_correlated):
        rewritten, correlated = self._rewrite_scalars(
            p, allow_correlated=allow_correlated)
        return p if rewritten is None else rewritten

    def _rewrite_scalars(self, p, allow_correlated=True):
        """Replace every ScalarSubquery in `p`: uncorrelated → BoundParam
        (precomputed), correlated → reference to a decorrelated aggregate
        join column. Returns (rewritten or None-if-unchanged, any_correlated)."""
        state = {"changed": False, "correlated": False}

        def walk(e):
            if isinstance(e, ast.ScalarSubquery):
                state["changed"] = True
                inner, pairs = self._split_correlations(e.query)
                if len(inner.items) != 1:
                    raise PlanError("scalar subquery must select one column")
                inner_scope = self._inner_scope(inner)
                dtype = self._expr_dtype(inner.items[0].expr, inner_scope) \
                    .with_nullable(True)
                n = len(self._sub_specs) + len(self._init_subplans)
                if not pairs:
                    pname = f"__sp{n}"
                    self._init_subplans.append(
                        (pname, self._plan_inner(inner)))
                    return ast.BoundParam(pname, dtype)
                if not allow_correlated:
                    raise PlanError("correlated scalar subquery not "
                                    "supported in this clause")
                state["correlated"] = True
                agg_label = f"__s{n}agg"
                items = [ast.SelectItem(inner.items[0].expr, agg_label)]
                key_labels = []
                for i, (iname, _oname) in enumerate(pairs):
                    lbl = f"__s{n}k{i}"
                    items.append(ast.SelectItem(iname, lbl))
                    key_labels.append(lbl)
                sub_sel = ast.Select(
                    items=items, relation=inner.relation, where=inner.where,
                    group_by=[iname for (iname, _o) in pairs])
                spec = {
                    "kind": "scalar", "n": n,
                    "plan": self._plan_inner(sub_sel),
                    "keys": [(oname, lbl) for (_i, oname), lbl
                             in zip(pairs, key_labels)],
                    "payload": [agg_label],
                }
                self._sub_specs.append(spec)
                self.scope.add("__sub", agg_label,
                               B.ColumnBinding(agg_label, dtype))
                return ast.Name((agg_label,))
            # structural rebuild
            if isinstance(e, ast.BinOp):
                return ast.BinOp(e.op, walk(e.left), walk(e.right))
            if isinstance(e, ast.UnaryOp):
                return ast.UnaryOp(e.op, walk(e.arg))
            if isinstance(e, ast.Between):
                return ast.Between(walk(e.arg), walk(e.lo), walk(e.hi),
                                   e.negated)
            if isinstance(e, ast.InList):
                return ast.InList(walk(e.arg),
                                  tuple(walk(x) for x in e.items),
                                  e.negated)
            if isinstance(e, ast.IsNull):
                return ast.IsNull(walk(e.arg), e.negated)
            if isinstance(e, ast.Like):
                return ast.Like(walk(e.arg), e.pattern, e.negated)
            if isinstance(e, ast.FuncCall):
                return ast.FuncCall(e.name, tuple(walk(a) for a in e.args),
                                    e.distinct, e.star)
            if isinstance(e, ast.Case):
                return ast.Case(
                    walk(e.operand) if e.operand is not None else None,
                    tuple((walk(c), walk(r)) for (c, r) in e.whens),
                    walk(e.default) if e.default is not None else None)
            if isinstance(e, ast.Cast):
                return ast.Cast(walk(e.arg), e.to)
            return e

        out = walk(p)
        if not state["changed"]:
            return None, False
        return out, state["correlated"]

    def _add_semi_spec(self, outer_exprs, inner_sel: ast.Select,
                       negated: bool, first_item_key: bool,
                       mark_pred: str = ""):
        """`mark_pred`: non-empty = the membership test sits INSIDE a
        larger predicate (an OR arm) — plan a MARK join exposing the
        bit under that name instead of a filtering semi join."""
        inner, pairs, neqs = self._split_correlations(inner_sel,
                                                      with_neq=True)
        n = len(self._sub_specs) + len(self._init_subplans)
        if neqs:
            if first_item_key or not pairs:
                raise PlanError("inner <> outer correlation needs an "
                                "EXISTS with an equality correlation too")
            if len(neqs) > 1:
                raise PlanError("at most one <> correlation is supported")
            return self._add_neq_semi_spec(inner, pairs, neqs[0], negated, n)
        items = []
        keys = []        # [(outer_ast_expr, build_label)]
        i = 0
        if first_item_key:
            if len(inner.items) != 1:
                raise PlanError("IN subquery must select one column")
            lbl = f"__s{n}k{i}"; i += 1
            items.append(ast.SelectItem(inner.items[0].expr, lbl))
            keys.append((outer_exprs[0], lbl))
        for (iname, oname) in pairs:
            lbl = f"__s{n}k{i}"; i += 1
            items.append(ast.SelectItem(iname, lbl))
            keys.append((oname, lbl))
        if not keys:
            raise PlanError("uncorrelated EXISTS is not supported yet")
        has_aggs: list = []
        for it in inner.items:
            walk_aggs(it.expr, has_aggs)
        grouped = bool(inner.group_by) or bool(has_aggs) \
            or inner.having is not None
        sub_sel = ast.Select(
            items=items, relation=inner.relation, where=inner.where,
            group_by=list(inner.group_by), having=inner.having,
            distinct=not grouped)
        if grouped and pairs:
            # correlated grouped subquery: correlation keys join the groups
            sub_sel.group_by = list(inner.group_by) + \
                [iname for (iname, _o) in pairs]
        spec = {
            "kind": "anti" if negated else "semi", "n": n,
            "plan": self._plan_inner(sub_sel),
            "keys": keys, "payload": [],
            # NOT IN (vs NOT EXISTS): NULL probe keys must be excluded when
            # the build set is non-empty — x NOT IN S is NULL, not TRUE
            "not_in": negated and first_item_key,
        }
        if mark_pred:
            if negated:
                raise PlanError("negated IN/EXISTS inside OR is not "
                                "supported yet")
            if len(keys) > 1:
                raise PlanError("composite-key IN/EXISTS inside OR is "
                                "not supported yet")
            spec["kind"] = "markpred"
            spec["mark"] = mark_pred
        if spec["not_in"] and pairs:
            # correlated NOT IN additionally needs a per-correlation-key
            # set-emptiness probe (x NOT IN {} is TRUE even for NULL x):
            # a distinct projection of the correlation keys alone
            if grouped:
                raise PlanError(
                    "correlated NOT IN over a grouped subquery is not "
                    "supported yet")
            corr_items = [ast.SelectItem(iname, f"__s{n}c{i}")
                          for i, (iname, _o) in enumerate(pairs)]
            sub2 = ast.Select(
                items=corr_items, relation=inner.relation, where=inner.where,
                group_by=[iname for (iname, _o) in pairs])
            spec["plan2"] = self._plan_inner(sub2)
            spec["keys2"] = [(oname, f"__s{n}c{i}")
                             for i, (_i, oname) in enumerate(pairs)]
        self._sub_specs.append(spec)

    def _add_neq_semi_spec(self, inner: ast.Select, pairs, neq,
                           negated: bool, n: int):
        """EXISTS(... WHERE k = outer.k AND col <> outer.col): a row with a
        different `col` exists in group k iff min(col) != outer.col OR
        max(col) != outer.col (all-equal collapses min=max=outer). The
        subquery groups by the equi keys with min/max aggregates; the
        existence test becomes a mark join + verification filter."""
        (neq_inner, neq_outer) = neq
        items, keys = [], []
        for i, (iname, oname) in enumerate(pairs):
            lbl = f"__s{n}k{i}"
            items.append(ast.SelectItem(iname, lbl))
            keys.append((oname, lbl))
        mn, mx = f"__s{n}mn", f"__s{n}mx"
        items.append(ast.SelectItem(
            ast.FuncCall("min", (neq_inner,)), mn))
        items.append(ast.SelectItem(
            ast.FuncCall("max", (neq_inner,)), mx))
        sub_sel = ast.Select(
            items=items, relation=inner.relation, where=inner.where,
            group_by=[iname for (iname, _o) in pairs])
        self._sub_specs.append({
            "kind": "anti" if negated else "semi", "n": n,
            "plan": self._plan_inner(sub_sel),
            "keys": keys, "payload": [mn, mx],
            "not_in": False,
            "neq": (neq_outer, mn, mx),
        })

    def _attach_sub_specs(self, pipeline, binder: B.ExprBinder):
        for spec in self._sub_specs:
            n = spec["n"]
            bound = []
            pre = ir.Program()
            for (oexpr, _lbl) in spec["keys"]:
                e = binder.bind(oexpr)
                bound.append(e)
            if spec.get("neq"):
                self._attach_neq_spec(pipeline, spec, bound, binder, pre)
                continue
            if len(spec["keys"]) == 1:
                e = bound[0]
                if isinstance(e, ir.Col):
                    probe_key = e.name
                else:
                    probe_key = f"__s{n}p"
                    pre.assign(probe_key, e)
                if pre.commands:
                    pipeline.steps.append(("program", pre))
                build_key = spec["keys"][0][1]
                if spec["kind"] == "markpred":
                    # membership bit for a disjunctive predicate: a MARK
                    # join attaches `mark` = matched without filtering
                    # (the reference lowers ORed existence tests the
                    # same way before peephole, `dq_opt_join.cpp`)
                    js = JoinStep(spec["plan"], build_key, probe_key,
                                  "mark", [], mark_col=spec["mark"])
                elif spec["kind"] == "scalar":
                    js = JoinStep(spec["plan"], build_key, probe_key,
                                  "inner", list(spec["payload"]))
                else:
                    kind = "left_semi" if spec["kind"] == "semi" \
                        else "left_anti"
                    js = JoinStep(spec["plan"], build_key, probe_key, kind,
                                  [], anti_null_check=(kind == "left_anti"),
                                  not_in=(kind == "left_anti"
                                          and spec.get("not_in", False)))
                pipeline.steps.append(("join", js))
            else:
                # composite: hash-key mark join + per-key verification
                self._guard_composite_string_keys(
                    [o for (o, _lbl) in spec["keys"]])
                probe_key = f"__s{n}p"
                hashed = [ir.call("hash64", e) for e in bound]
                pre.assign(probe_key,
                           hashed[0] if len(hashed) == 1
                           else ir.call("hash_combine", *hashed))
                pipeline.steps.append(("program", pre))
                mark = f"__s{n}m"
                key_labels = [lbl for (_o, lbl) in spec["keys"]]
                not_in = spec["kind"] == "anti" and spec.get("not_in", False)
                js = JoinStep(spec["plan"], f"__s{n}bh", probe_key, "mark",
                              key_labels + list(spec["payload"]),
                              mark_col=mark,
                              build_hash_keys=key_labels,
                              # correlated NOT IN: a NULL build value poisons
                              # its whole per-key set — raise like the
                              # single-key path does
                              anti_null_check=not_in,
                              anti_null_col=key_labels[0] if not_in else "")
                pipeline.steps.append(("join", js))
                matched = ir.Col(mark)
                for e, lbl in zip(bound, key_labels):
                    matched = ir.call("and", matched,
                                      ir.call("eq", e, ir.Col(lbl)))
                if not_in:
                    self._attach_not_in_verify(pipeline, spec, bound,
                                               matched, n)
                    continue
                verify = ir.Program()
                if spec["kind"] == "anti":
                    verify.filter(ir.call("not", matched))
                else:          # semi or scalar
                    verify.filter(matched)
                pipeline.steps.append(("program", verify))

        if self._post_preds:
            prog = ir.Program()
            for p in self._post_preds:
                prog.filter(binder.bind(p))
            pipeline.steps.append(("program", prog))

    def _guard_composite_string_keys(self, outer_exprs) -> None:
        """Composite correlated keys hash raw per-table dictionary codes;
        a string key from another dictionary would silently mismatch —
        refuse loudly until remapping reaches these join shapes (the
        single-key and edge-join paths DO remap)."""
        for e in outer_exprs:
            if isinstance(e, ast.Name):
                b = self.scope.try_resolve(e.parts)
                if b is not None and b.dtype.is_string:
                    raise PlanError(
                        "multi-key correlated subqueries with string key "
                        "columns are not supported yet")

    def _attach_neq_spec(self, pipeline, spec, bound, binder, pre):
        """EXISTS / NOT EXISTS with a `col <> outer.col` correlation: mark
        join against the per-key min/max aggregate, then verify
        `min != outer OR max != outer` (coalesced to FALSE so NULL min/max
        — empty or all-NULL groups — read as 'no differing row')."""
        n = spec["n"]
        key_labels = [lbl for (_o, lbl) in spec["keys"]]
        mark = f"__s{n}m"
        if len(bound) == 1:
            e = bound[0]
            if isinstance(e, ir.Col):
                probe_key = e.name
            else:
                probe_key = f"__s{n}p"
                pre.assign(probe_key, e)
            if pre.commands:
                pipeline.steps.append(("program", pre))
            js = JoinStep(spec["plan"], key_labels[0], probe_key, "mark",
                          list(spec["payload"]), mark_col=mark)
            pipeline.steps.append(("join", js))
            matched = ir.Col(mark)
        else:
            self._guard_composite_string_keys(
                [o for (o, _lbl) in spec["keys"]])
            probe_key = f"__s{n}p"
            hashed = [ir.call("hash64", e) for e in bound]
            pre.assign(probe_key, hashed[0] if len(hashed) == 1
                       else ir.call("hash_combine", *hashed))
            pipeline.steps.append(("program", pre))
            js = JoinStep(spec["plan"], f"__s{n}bh", probe_key, "mark",
                          key_labels + list(spec["payload"]),
                          mark_col=mark, build_hash_keys=key_labels)
            pipeline.steps.append(("join", js))
            matched = ir.Col(mark)
            for e, lbl in zip(bound, key_labels):
                matched = ir.call("and", matched,
                                  ir.call("eq", e, ir.Col(lbl)))
        (neq_outer, mn, mx) = spec["neq"]
        o = binder.bind(neq_outer)
        differs = ir.call("or", ir.call("ne", ir.Col(mn), o),
                          ir.call("ne", ir.Col(mx), o))
        exists_true = ir.call(
            "coalesce", ir.call("and", matched, differs),
            ir.Const(False, dt.DType(dt.Kind.BOOL, False)))
        verify = ir.Program()
        verify.filter(exists_true if spec["kind"] == "semi"
                      else ir.call("not", exists_true))
        pipeline.steps.append(("program", verify))

    def _attach_not_in_verify(self, pipeline, spec, bound, matched, n):
        """Correlated NOT IN (composite-key mark join): `x NOT IN S_k` is
        NULL — row excluded — when x is NULL and the per-correlation-key
        set S_k is non-empty, but TRUE when S_k is empty. Emptiness is
        probed with a second mark join on the correlation keys alone;
        Kleene AND/OR then give keep = NOT matched AND
        (x IS NOT NULL OR NOT any_corr)."""
        # snapshot `matched` before the second join clobbers columns
        mcol = f"__s{n}mt"
        snap = ir.Program()
        snap.assign(mcol, matched)
        pipeline.steps.append(("program", snap))

        corr_bound = bound[1:]
        self._guard_composite_string_keys(
            [o for (o, _lbl) in spec["keys2"]])
        corr_labels = [lbl for (_o, lbl) in spec["keys2"]]
        probe2 = f"__s{n}p2"
        h2 = [ir.call("hash64", e) for e in corr_bound]
        pre2 = ir.Program()
        pre2.assign(probe2, h2[0] if len(h2) == 1
                    else ir.call("hash_combine", *h2))
        pipeline.steps.append(("program", pre2))
        mark2 = f"__s{n}m2"
        js2 = JoinStep(spec["plan2"], f"__s{n}bh2", probe2, "mark",
                       list(corr_labels), mark_col=mark2,
                       build_hash_keys=list(corr_labels))
        pipeline.steps.append(("join", js2))
        any_corr = ir.Col(mark2)
        for e, lbl in zip(corr_bound, corr_labels):
            any_corr = ir.call("and", any_corr,
                               ir.call("eq", e, ir.Col(lbl)))
        verify = ir.Program()
        verify.filter(ir.call(
            "and", ir.call("not", ir.Col(mcol)),
            ir.call("or", ir.call("is_not_null", bound[0]),
                    ir.call("not", any_corr))))
        pipeline.steps.append(("program", verify))

    # -- aggregation & projection ------------------------------------------

    def _plan_projection_agg(self, sel: ast.Select, plan: QueryPlan,
                             binder: B.ExprBinder) -> None:
        aggs: list = []
        for item in sel.items:
            if not isinstance(item.expr, ast.Star):
                walk_aggs(item.expr, aggs)
        if sel.having is not None:
            walk_aggs(sel.having, aggs)
        for o in sel.order_by:
            walk_aggs(o.expr, aggs)

        has_agg = bool(aggs) or bool(sel.group_by)

        # alias map for GROUP BY / ORDER BY references to select aliases
        alias_map = {item.alias: item.expr for item in sel.items if item.alias}

        def deref(e, positional=False, prefer_alias=False):
            """Select-alias substitution; `positional` additionally resolves
            bare integers as 1-based select positions (ORDER BY 1 / GROUP
            BY 1) and must only be used at the top level of those clauses —
            never recursively, or nested literals would be rewritten.
            `prefer_alias` (ORDER BY): a select alias shadows a source
            column of the same name (PostgreSQL rule: `sum(x) as x ...
            order by x` sorts the aggregate); GROUP BY keeps the source
            column."""
            if isinstance(e, ast.Name) and len(e.parts) == 1 \
                    and e.parts[0] in alias_map \
                    and (prefer_alias
                         or self.scope.try_resolve(e.parts) is None):
                return alias_map[e.parts[0]]
            if positional and isinstance(e, ast.Literal) \
                    and isinstance(e.value, int) and e.type_hint is None \
                    and 1 <= e.value <= len(sel.items):
                return sel.items[e.value - 1].expr
            return e

        if has_agg:
            self._plan_aggregate(sel, plan, binder, aggs, deref)
        else:
            self._plan_simple(sel, plan, binder, deref)

    def _plan_simple(self, sel: ast.Select, plan: QueryPlan,
                     binder: B.ExprBinder, deref) -> None:
        """No aggregation: compute outputs per block; final sort/limit."""
        prog = ir.Program()
        output = []
        out_names = []
        for i, item in enumerate(sel.items):
            if isinstance(item.expr, ast.Star):
                # pipeline out_names are demand-set derived (unordered);
                # emit * in schema-declaration order
                avail = set(plan.pipeline.out_names)
                names = [n for n in plan.star_order if n in avail] \
                    or plan.pipeline.out_names
                if item.expr.table is not None:
                    prefix = item.expr.table + "."
                    names = [n for n in names if n.startswith(prefix)]
                    if not names:
                        raise PlanError(
                            f"unknown table alias {item.expr.table!r} in "
                            f"{item.expr.table}.*")
                for name in names:
                    output.append((name, name.split(".", 1)[1]))
                    out_names.append(name)
                continue
            e = binder.bind(item.expr)
            label = item.alias or (
                item.expr.parts[-1] if isinstance(item.expr, ast.Name)
                else f"column{i}")
            if isinstance(e, ir.Col):
                name = e.name
            else:
                name = f"expr{i}"
                prog.assign(name, e)
                d = self._maybe_result_dict(e)
                if d is not None:
                    plan.result_dicts[name] = d
            output.append((name, label))
            out_names.append(name)

        uniq_outs = list(dict.fromkeys(out_names))
        if sel.distinct:
            # dedup per block, then globally; sort expressions are computed
            # after the final dedup (they would be dropped by the GroupBy)
            domains = self._key_domains(uniq_outs)
            dbound = self._groups_bound(domains)
            prog.group_by(uniq_outs, [], domains, out_bound=dbound)
            plan.pipeline.partial = prog
            final = ir.Program().group_by(uniq_outs, [], domains,
                                          out_bound=dbound)
            sort_keys, _extra = self._bind_sort(sel, binder.bind, out_names,
                                                final, alias_deref=deref)
            plan.final_program = final
        else:
            sort_keys, extra = self._bind_sort(sel, binder.bind, out_names,
                                               prog, alias_deref=deref)
            prog.project(list(dict.fromkeys(out_names + extra)))
            plan.pipeline.partial = prog
        plan.sort = sort_keys
        plan.limit, plan.offset = sel.limit, sel.offset
        plan.output = output

    def _plan_aggregate(self, sel: ast.Select, plan: QueryPlan,
                        binder: B.ExprBinder, agg_calls, deref) -> None:
        partial = ir.Program()
        # group keys
        key_specs = []     # (ast_expr, ir_expr, key_name)
        for i, ge in enumerate(sel.group_by):
            ge = deref(ge, positional=True)
            e = binder.bind(ge)
            if isinstance(e, ir.Col):
                name = e.name
            else:
                name = f"gk{i}"
                partial.assign(name, e)
                d = self._maybe_result_dict(e)
                if d is not None:
                    plan.result_dicts[name] = d
            key_specs.append((ge, e, name))
        key_names = [k[2] for k in key_specs]

        # DISTINCT aggregates: dedup by (group keys + arg) in the partial
        # and first-final GroupBys, then aggregate the arg in a second
        # final GroupBy over the group keys alone. All distinct aggs must
        # share one argument (one dedup dimension).
        distinct_calls = [c for c in agg_calls if c.distinct]
        dcol = None
        final2_aggs: list = []
        if distinct_calls:
            if any(c.star or not c.args for c in distinct_calls):
                raise PlanError("COUNT(DISTINCT *) is meaningless")
            args = {repr(c.args[0]) for c in distinct_calls}
            if len(args) != 1:
                raise PlanError("DISTINCT aggregates over different "
                                "arguments are not supported yet")
            d_ir = binder.bind(distinct_calls[0].args[0])
            if isinstance(d_ir, ir.Col):
                dcol = d_ir.name
            else:
                dcol = "__dx"
                partial.assign(dcol, d_ir)

        # aggregate instances (deduped by bound signature)
        agg_map: dict = {}          # signature -> dict describing partial/final
        partial_aggs: list = []
        final_aggs: list = []
        n = 0

        sealed = [False]

        string_agg_decodes: list = []
        string_rank_luts: dict = {}   # column name -> (rank col, inv param)

        def register(call: ast.FuncCall) -> dict:
            nonlocal n
            if call.distinct:
                sig = (call.name, "distinct", repr(call.args[0]))
                inst = agg_map.get(sig)
                if inst is not None:
                    return inst
                if sealed[0]:
                    raise PlanError(
                        f"aggregate {call.name} appeared only after the "
                        "partial stage was sealed (planner bug)")
                inst = {"func": call.name}
                if call.name == "avg":
                    s, c = f"agg{n}s", f"agg{n}c"; n += 1
                    final2_aggs.append(ir.Agg(s, "sum", dcol))
                    final2_aggs.append(ir.Agg(c, "count", dcol))
                    inst["sum"], inst["count"] = s, c
                else:
                    out = f"agg{n}"; n += 1
                    f = {"count": "count", "sum": "sum", "min": "min",
                         "max": "max"}.get(call.name)
                    if f is None:
                        raise PlanError(
                            f"DISTINCT {call.name} not supported")
                    final2_aggs.append(ir.Agg(out, f, dcol))
                    inst["col"] = out
                agg_map[sig] = inst
                return inst
            # dedup on the AST (bound IR is not stable: LUT params get
            # fresh names per binding)
            if call.star or not call.args:
                sig = ("count_all",)
            else:
                sig = (call.name, repr(call.args[0]))
            inst = agg_map.get(sig)
            if inst is not None:
                return inst
            if sealed[0]:
                raise PlanError(
                    f"aggregate {call.name} appeared only after the partial "
                    "stage was sealed (planner bug)")
            inst = {"func": call.name}
            if call.star or not call.args:
                out = f"agg{n}"; n += 1
                partial_aggs.append(ir.Agg(out, "count_all"))
                final_aggs.append(ir.Agg(out, "sum", out))
                inst["col"] = out
            else:
                arg_ir = binder.bind(call.args[0])
                arg_name = arg_ir.name if isinstance(arg_ir, ir.Col) else None
                if arg_name is None:
                    arg_name = f"aggarg{n}"
                    partial.assign(arg_name, arg_ir)
                if call.name == "avg":
                    s, c = f"agg{n}s", f"agg{n}c"; n += 1
                    partial_aggs.append(ir.Agg(s, "sum", arg_name))
                    partial_aggs.append(ir.Agg(c, "count", arg_name))
                    final_aggs.append(ir.Agg(s, "sum", s))
                    final_aggs.append(ir.Agg(c, "sum", c))
                    inst["sum"], inst["count"] = s, c
                elif call.name == "count":
                    out = f"agg{n}"; n += 1
                    partial_aggs.append(ir.Agg(out, "count", arg_name))
                    final_aggs.append(ir.Agg(out, "sum", out))
                    inst["col"] = out
                elif call.name in ("min", "max") and isinstance(
                        arg_ir, ir.Col) and self._string_dict(arg_ir.name):
                    # lexicographic MIN/MAX over a dictionary-coded string:
                    # aggregate the code's lexicographic RANK (plan-time
                    # LUT — the plan cache keys on data_version, so the
                    # dictionary snapshot stays valid), then map the
                    # winning rank back to a code in the final stage
                    dic = self._string_dict(arg_ir.name)
                    i32 = dt.DType(dt.Kind.INT32, False)
                    # the inverse LUT holds string CODES — typing it as
                    # STRING makes the decoded column a real string
                    # (codes + dictionary) through schema inference
                    sstr = dt.DType(dt.Kind.STRING, False)
                    cached = string_rank_luts.get(arg_ir.name)
                    if cached is None:
                        vals = dic.values_array()
                        order = np.argsort(vals) if len(vals) else None
                        ranks = (np.argsort(order).astype(np.int32)
                                 if order is not None
                                 else np.zeros(1, np.int32))
                        inv = (order.astype(np.int32) if order is not None
                               else np.zeros(1, np.int32))
                        rp, ip = f"__aggrank{n}", f"__agginv{n}"
                        plan.params[rp] = ranks
                        plan.params[ip] = inv
                        rank_col = f"aggarg{n}"
                        partial.assign(rank_col, ir.call(
                            "take_lut", arg_ir, ir.Param(rp, i32,
                                                         is_array=True)))
                        cached = (rank_col, ip)
                        string_rank_luts[arg_ir.name] = cached
                    rank_col, ip = cached
                    out = f"agg{n}"; n += 1
                    partial_aggs.append(ir.Agg(out, call.name, rank_col))
                    final_aggs.append(ir.Agg(out, call.name, out))
                    dec = f"{out}dec"
                    string_agg_decodes.append(
                        (dec, ir.call("take_lut", ir.Col(out),
                                      ir.Param(ip, sstr, is_array=True))))
                    plan.result_dicts[dec] = dic
                    inst["col"] = dec
                elif call.name in ("sum", "min", "max", "some"):
                    out = f"agg{n}"; n += 1
                    f = call.name
                    partial_aggs.append(ir.Agg(out, f, arg_name))
                    final_aggs.append(ir.Agg(out, "sum" if f == "sum" else f, out))
                    inst["col"] = out
                else:
                    raise PlanError(f"aggregate {call.name} not supported")
            agg_map[sig] = inst
            return inst

        for call in agg_calls:
            register(call)

        domains = self._key_domains(key_names)
        gbound = self._groups_bound(domains)
        sealed[0] = True
        if dcol is None:
            partial.group_by(key_names, partial_aggs, domains,
                             out_bound=gbound)
            plan.pipeline.partial = partial
            # -- final stage: merge aggs, having, outputs, sort -----------
            final = ir.Program().group_by(key_names, final_aggs, domains,
                                          out_bound=gbound)
            for (dec, expr) in string_agg_decodes:
                final.assign(dec, expr)
        else:
            ddom = self._key_domains([dcol])
            dbound = self._groups_bound(domains + ddom)
            partial.group_by(key_names + [dcol], partial_aggs,
                             domains + ddom, out_bound=dbound)
            plan.pipeline.partial = partial
            # first final GroupBy completes the global dedup by
            # (keys + arg); the second collapses to the group keys, counting
            # the deduplicated arg and re-merging the regular aggregates
            # (associative, so the double merge is exact)
            final = ir.Program().group_by(key_names + [dcol], final_aggs,
                                          domains + ddom, out_bound=dbound)
            final.group_by(
                key_names,
                [ir.Agg(a.out, a.func, a.out) for a in final_aggs]
                + final2_aggs, domains, out_bound=gbound)
            for (dec, expr) in string_agg_decodes:
                final.assign(dec, expr)

        planner = self

        class GroupBinder(B.ExprBinder):
            def bind(self, e):
                e = deref(e)
                # whole-expression match against a group key
                try:
                    be = binder.bind(e)
                except B.BindError:
                    be = None
                if be is not None:
                    for (_ge, ire, name) in key_specs:
                        if be == ire:
                            return ir.Col(name)
                if isinstance(e, ast.FuncCall) and e.name in B.AGG_NAMES:
                    inst = register(e)
                    if e.name == "avg":
                        return ir.call("div", ir.Col(inst["sum"]),
                                       ir.Col(inst["count"]))
                    return ir.Col(inst["col"])
                return super().bind(e)

        gbinder = GroupBinder(self.scope, self.pool)

        if sel.having is not None:
            final.filter(gbinder.bind(sel.having))

        output = []
        out_names = []
        for i, item in enumerate(sel.items):
            if isinstance(item.expr, ast.Star):
                raise PlanError("* with GROUP BY")
            e = gbinder.bind(item.expr)
            label = item.alias or (
                item.expr.parts[-1] if isinstance(item.expr, ast.Name)
                else f"column{i}")
            if isinstance(e, ir.Col):
                name = e.name
            else:
                name = f"out{i}"
                final.assign(name, e)
                d = self._maybe_result_dict(e)
                if d is not None:
                    plan.result_dicts[name] = d
            output.append((name, label))
            out_names.append(name)

        sort_keys, extra = self._bind_sort(sel, gbinder.bind, out_names, final,
                                           alias_deref=deref)
        final.project(list(dict.fromkeys(out_names + extra)))
        plan.final_program = final
        plan.sort = sort_keys
        plan.limit, plan.offset = sel.limit, sel.offset
        plan.output = output

    def _maybe_result_dict(self, e) -> object:
        """Dictionary of a derived string expression (take_lut through a
        pool param), or the source column's dictionary for plain columns."""
        d = getattr(self.pool, "expr_dicts", None)
        if d is not None and id(e) in d:
            return d[id(e)]
        if isinstance(e, ir.Call) and e.op == "take_lut" \
                and len(e.args) == 2 and isinstance(e.args[1], ir.Param):
            return self.pool.param_dicts.get(e.args[1].name)
        if isinstance(e, ir.Call) and e.op in ("if", "coalesce"):
            # string CASE: every string branch encodes into one shared
            # derived dictionary (binder._maybe_string_case); branches from
            # DIFFERENT dictionaries would decode through the wrong one
            found = {id(x): x for x in
                     (self._maybe_result_dict(a) for a in e.args)
                     if x is not None}
            if len(found) > 1:
                raise PlanError("string branches of if/coalesce come from "
                                "different dictionaries")
            if found:
                return next(iter(found.values()))
        return None

    def _string_dict(self, name: str):
        """The dictionary of a string scan column (None otherwise)."""
        b = self.scope.by_internal(name)
        if b is not None and b.dtype.is_string and b.dictionary is not None:
            return b.dictionary
        return None

    @staticmethod
    def _groups_bound(domains: tuple) -> int:
        """Guaranteed ngroups upper bound from bounded key domains: the
        mixed-radix bucket count prod(domain+1) (each key contributes its
        domain plus the NULL slot). Feeds `ir.GroupBy.out_bound` so the
        sorted lowering late-materializes per-group gathers at output
        cardinality when the bounded product overflows the scatter paths
        (multi-string-key group-bys, q16-class). 0 = no guarantee (any
        unbounded key, or a product too large to ever matter)."""
        bound = 1
        for d in domains:
            if d <= 0:
                return 0
            bound *= d + 1
            if bound > (1 << 40):
                return 0
        return bound

    def _key_domains(self, key_names: list) -> tuple:
        """Static key-domain sizes for the scatter aggregation path:
        dictionary-coded strings (len(dict)) and bools (2); 0 = unbounded.
        Domains snapshot the dictionary size at plan time — plans are built
        per query, so codes cannot exceed them during execution."""
        from ydb_tpu.core.dtypes import Kind
        domains = []
        for name in key_names:
            b = self.scope.by_internal(name)
            if b is None:
                domains.append(0)
            elif b.dtype.is_string and b.dictionary is not None:
                domains.append(max(len(b.dictionary), 1))
            elif b.dtype.kind is Kind.BOOL:
                domains.append(2)
            else:
                domains.append(0)
        return tuple(domains)

    def _bind_sort(self, sel, bind_fn, out_names: list, prog: ir.Program,
                   alias_deref) -> tuple[list, list]:
        sort_keys: list = []
        extra: list = []
        for j, o in enumerate(sel.order_by):
            e = bind_fn(alias_deref(o.expr, positional=True,
                                    prefer_alias=True))
            if isinstance(e, ir.Col):
                name = e.name
                extra.append(name)     # keep through the output projection
            else:
                name = f"sort{j}"
                prog.assign(name, e)
                extra.append(name)
            nf = o.nulls_first
            if nf is None:
                nf = o.ascending       # YQL: NULL is smallest
            sort_keys.append(SortKey(name, o.ascending, nf))
        return sort_keys, extra
