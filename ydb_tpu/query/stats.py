"""Table/column statistics + predicate selectivity — the CBO's inputs.

The reference feeds its cost-based optimizer from a statistics service
(base statistics + column statistics aggregated from DataShards,
`ydb/core/statistics/`, consumed by `dq_opt_join_cost_based.cpp`). Here
the same inputs come from what storage already maintains: per-portion
min/max/null stats (`storage/portion.py`), table row counts, and string
dictionary cardinalities (exact NDV for dictionary-encoded columns).

Selectivity heuristics are the classic System-R family: equality 1/NDV,
ranges by min-max span fraction, LIKE 0.1, default 1/3 — enough to rank
join orders by effective (post-local-predicate) cardinality instead of
raw table size.
"""

from __future__ import annotations

import numpy as np

from ydb_tpu.sql import ast

DEFAULT_SEL = 1.0 / 3.0
LIKE_SEL = 0.1


def table_rows(table) -> int:
    return max(int(getattr(table, "num_rows", 0)), 1)


def column_minmax(table, col: str):
    """(min, max) over the table's portions, or (None, None)."""
    lo = hi = None
    for shard in getattr(table, "shards", []):
        for p in getattr(shard, "portions", []):
            st = p.stats.get(col)
            if st is None or st.min is None:
                continue
            lo = st.min if lo is None else min(lo, st.min)
            hi = st.max if hi is None else max(hi, st.max)
    return lo, hi


def column_ndv(table, col: str) -> float:
    """Distinct-value estimate: exact for dictionary columns, span- and
    row-bounded for integers, sqrt(rows) fallback otherwise."""
    rows = table_rows(table)
    dic = getattr(table, "dictionaries", {}).get(col)
    if dic is not None and len(dic):
        return float(len(dic))
    if col in getattr(table, "key_columns", []):
        return float(rows)
    lo, hi = column_minmax(table, col)
    if lo is not None and hi is not None \
            and isinstance(lo, (int, np.integer)):
        return float(min(int(hi) - int(lo) + 1, rows))
    return float(max(rows ** 0.5, 1.0))


def _col_of(e, alias: str):
    """Column name if `e` is a bare/qualified reference to this alias."""
    if isinstance(e, ast.Name):
        if len(e.parts) == 1:
            return e.parts[0]
        if len(e.parts) == 2 and e.parts[0] == alias:
            return e.parts[1]
    return None


def _range_sel(table, col: str, op: str, v) -> float:
    lo, hi = column_minmax(table, col)
    try:
        if lo is None or hi is None or float(hi) <= float(lo):
            return DEFAULT_SEL
        span = float(hi) - float(lo)
        f = (float(v) - float(lo)) / span
        f = min(max(f, 0.0), 1.0)
        return f if op in ("<", "<=") else 1.0 - f
    except (TypeError, ValueError):
        return DEFAULT_SEL


def predicate_selectivity(pred, alias: str, table) -> float:
    """Estimated fraction of rows surviving one local predicate."""
    if isinstance(pred, ast.BinOp):
        col = _col_of(pred.left, alias)
        lit = pred.right if isinstance(pred.right, ast.Literal) else None
        if col is None:                          # literal <op> col
            col = _col_of(pred.right, alias)
            lit = pred.left if isinstance(pred.left, ast.Literal) else None
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
            op = flip.get(pred.op, pred.op)
        else:
            op = pred.op
        if col is None or not table.schema.has(col):
            return DEFAULT_SEL
        if op == "=":
            return 1.0 / column_ndv(table, col)
        if op == "<>":
            return 1.0 - 1.0 / column_ndv(table, col)
        if op in ("<", "<=", ">", ">=") and lit is not None \
                and lit.type_hint is None:
            return _range_sel(table, col, op, lit.value)
        return DEFAULT_SEL
    if isinstance(pred, ast.Between):
        col = _col_of(pred.arg, alias)
        if col is None or not table.schema.has(col):
            return DEFAULT_SEL
        if isinstance(pred.lo, ast.Literal) and isinstance(pred.hi,
                                                          ast.Literal) \
                and pred.lo.type_hint is None:
            a = _range_sel(table, col, ">=", pred.lo.value)
            b = _range_sel(table, col, "<=", pred.hi.value)
            s = max(a + b - 1.0, 1.0 / table_rows(table))
            return 1.0 - s if pred.negated else s
        return DEFAULT_SEL
    if isinstance(pred, ast.InList):
        col = _col_of(pred.arg, alias)
        if col is None or not table.schema.has(col):
            return DEFAULT_SEL
        s = min(len(pred.items) / column_ndv(table, col), 1.0)
        return 1.0 - s if pred.negated else s
    if isinstance(pred, ast.Like):
        return 1.0 - LIKE_SEL if pred.negated else LIKE_SEL
    if isinstance(pred, ast.IsNull):
        return DEFAULT_SEL
    return DEFAULT_SEL


def effective_rows(alias: str, table, local_preds: list) -> float:
    """Post-local-predicate cardinality estimate — the quantity join
    ordering ranks by (raw num_rows ranked r3's plans; a date_dim
    filtered to one month must become a build side, whatever its raw
    size relative to the probe)."""
    rows = float(table_rows(table))
    for p in local_preds:
        rows *= predicate_selectivity(p, alias, table)
    return max(rows, 1.0)
