"""Late-materialization planning: which columns can ride as row-ids.

The fused path hauls every payload byte through the middle of a plan —
probe gathers at scan capacity, compress/sort over full payload widths
(PERF round-16: "the gathered payload WIDTH is the remaining tax").
This pass marks, statically per pipeline, the columns whose VALUES are
not needed until late:

  * scan columns never referenced by the pre-program's compute or any
    join's probe key — a single int32 row-position column stands in for
    all of them (`ops/fused.LM_POS`);
  * inner/left join payload columns — the probe threads a
    (build row-id, match) pair per side instead of gathering widths
    (`ops/join.probe_lut_traced` late mode).

Deferred columns materialize at their first compute reference (group-by
keys/agg args, filters, sort keys) — which, once the executor's
`ir.Compact` has shrunk the pipeline to its ladder-quantized bound, runs
at the bound instead of scan capacity — or at the post-LIMIT tail, where
a LIMIT-K plan gathers K-bucket rows. The analysis here is purely
structural (the same walk the trace performs), so EXPLAIN's
`-- latemat:` lines and the executed deferral agree by construction.

Lever: `YDB_TPU_LATE_MAT` (`ops/xla_exec.late_mat_enabled`, a
tuning-provider riding every fused cache key via `groupby_tuning`).
"""

from __future__ import annotations

from ydb_tpu.ops import ir
from ydb_tpu.ops.fused import _prog_refs


def deferrable_scan(pipe, scan_names) -> frozenset:
    """Scan columns (internal names) the fused body may defer: not part
    of the pre-program's compute set, not any join step's probe key, and
    only when the pre-program cannot drop the row-position helper (a
    GroupBy or Projection in the PRE-program would — those plans keep
    eager scan loads)."""
    if pipe.pre_program is not None and any(
            isinstance(c, (ir.GroupBy, ir.Projection))
            for c in pipe.pre_program.commands):
        return frozenset()
    refs = set()
    if pipe.pre_program is not None:
        refs |= _prog_refs(pipe.pre_program)
        # projected names in the PRE-program would be dropped from env
        # before the scan helper exists; excluded above
    for kind, step in pipe.steps:
        if kind == "join":
            refs.add(step.probe_key)
    return frozenset(n for n in scan_names if n not in refs)


def deferrable_joins(pipe) -> list:
    """Per join step (in order), True when its payload gathers defer:
    inner/left joins with payload columns (semi/anti carry none; mark
    keeps the eager gather — its mark column is the probe's product)."""
    out = []
    for kind, step in pipe.steps:
        if kind != "join":
            continue
        out.append(step.kind in ("inner", "left") and bool(step.payload))
    return out


def annotate_plan(plan) -> None:
    """Stamp the pipeline with its late-materialization sets (sizing/
    observability metadata — EXPLAIN's `-- latemat:` lines; the executor
    recomputes the same sets against the actual fused shape). Mirrors
    `bounds.annotate_plan`'s role for the bounds lattice."""
    from ydb_tpu.ops.xla_exec import late_mat_enabled
    pipe = plan.pipeline
    if not late_mat_enabled():
        pipe.late_names = ()
        return
    scan_names = [i for (_s, i) in pipe.scan.columns]
    late = sorted(deferrable_scan(pipe, scan_names))
    for (kind, step), d in zip(
            [(k, s) for (k, s) in pipe.steps if k == "join"],
            deferrable_joins(pipe)):
        if d:
            late += [f"{n}(row-id)" for n in step.payload]
    pipe.late_names = tuple(late)
