"""Memory admission for concurrent queries (the KQP resource-manager seat).

The reference admits queries against per-node memory pools
(`ydb/core/kqp/rm_service/kqp_rm_service.h:68` — TxMemory limits with
queueing at the session/executer boundary). Here: a byte-budget gate over
the device working set — each query's scan + build estimate reserves
budget before dispatch, waits (bounded) when the chip is oversubscribed,
and sheds with an admission error past the deadline. Estimates above the
whole budget clamp to it, so giant (tiled/spilled) queries serialize
against everything rather than deadlock.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class AdmissionTimeout(Exception):
    pass


class MemoryAdmission:
    def __init__(self, budget_bytes: int, timeout_s: float = 60.0):
        self.budget = int(budget_bytes)
        self.timeout_s = timeout_s
        self.in_flight = 0
        self.active = 0
        self._cv = threading.Condition()

    @contextmanager
    def admit(self, est_bytes: int):
        from ydb_tpu.utils.metrics import GLOBAL, GLOBAL_HIST
        est = max(0, min(int(est_bytes), self.budget))
        with self._cv:
            t_enter = time.monotonic()
            deadline = t_enter + self.timeout_s
            waited = False
            while self.in_flight + est > self.budget:
                waited = True
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(remaining):
                    GLOBAL.inc("admission/timeouts")
                    # the LONGEST waits are the timed-out ones — omitting
                    # them would bias p99/max low exactly when admission
                    # is saturated
                    GLOBAL_HIST.observe(
                        "admission/wait_ms",
                        (time.monotonic() - t_enter) * 1000.0)
                    raise AdmissionTimeout(
                        f"memory admission timed out: need {est} bytes, "
                        f"{self.budget - self.in_flight} free of "
                        f"{self.budget} (queries queue while the device "
                        f"is oversubscribed)")
            if waited:
                GLOBAL.inc("admission/waits")
            # queue-time distribution: non-waiters record ~0, so the
            # quantiles honestly show what fraction of queries queued
            GLOBAL_HIST.observe("admission/wait_ms",
                                (time.monotonic() - t_enter) * 1000.0)
            self.in_flight += est
            self.active += 1
            GLOBAL.set("admission/in_flight_bytes", self.in_flight)
            GLOBAL.set("admission/active_queries", self.active)
        try:
            yield
        finally:
            with self._cv:
                self.in_flight -= est
                self.active -= 1
                GLOBAL.set("admission/in_flight_bytes", self.in_flight)
                GLOBAL.set("admission/active_queries", self.active)
                self._cv.notify_all()

    def backlog(self) -> dict:
        """Queue snapshot for the compile-ahead observability surfaces
        (`.sys/progstore`, ProgStoreStats): active reservations,
        reserved bytes, free bytes — the wait a background compile
        overlaps with."""
        with self._cv:
            return {"active": self.active,
                    "in_flight_bytes": self.in_flight,
                    "free_bytes": max(0, self.budget - self.in_flight)}


def batch_reservation_bytes(est_bytes: int, n_members: int,
                            member_floor: int = 1 << 20) -> int:
    """ONE reservation for a coalesced batch (`query/batch_lane.py`).

    Charging each member its full scan+build estimate as an independent
    nominal-slot reservation would both multiply-count the shared
    superblock AND risk deadlocking the pipeline window against
    admission; charging only the leader's estimate would under-count the
    vmapped execution, which materializes one cap-sized copy of every
    intermediate PER MEMBER. The honest size of the stacked execution is
    therefore one reservation of ~N x the per-member estimate (floored
    for tiny scans); estimates above the whole budget clamp there and
    serialize against everything, like any giant query."""
    return int(est_bytes) + max(0, n_members - 1) * \
        max(int(member_floor), int(est_bytes))


def estimate_plan_bytes(catalog, plan, snapshot) -> int:
    """Device-byte estimate for a SELECT plan: the driving scan's columns
    at the table's row count, plus each join build's scan (one level deep
    — build subplans estimate their own driving scan).

    Deliberately stats-only (row counts × column widths; the bounds
    lattice adds portion-STATS prune previews and build output bounds,
    never block data): the executor enumerates the actual scan sources
    right after admission — re-walking blocks here would do it twice.

    Bounds-lattice tightening (`query/bounds.py`, YDB_TPU_BOUNDS):
      * the driving scan honors the plan's prune predicates against
        portion min/max stats — the q12/q20 prune-blind outlier class
        (a scan pruned to one month estimated at the full table);
      * a join build reserves min(scan, proven output bound × width) —
        builds MATERIALIZE at output cardinality, so a grouped/limited/
        bounded-multiplicity build stops double-charging its driving
        scan (the q21 class).

    Join-payload copy term: the fused probe materializes each build
    payload column at PROBE capacity — on q7/q9 that padded copy was the
    difference between the 229/313 MB admitted and the 354/402 MB
    measured peak. With late materialization (`YDB_TPU_LATE_MAT`) the
    probe threads a 5-byte (row-id, match) pair instead, and payload
    widths materialize once at build cardinality (bound-sized tail) —
    the estimate charges whichever execution the lever selects."""
    import numpy as np

    from ydb_tpu.ops.xla_exec import late_mat_enabled
    from ydb_tpu.query.bounds import (bounds_enabled, build_bytes_bound,
                                      scan_rows_bound)
    from ydb_tpu.utils.metrics import GLOBAL
    lattice = bounds_enabled()
    late = late_mat_enabled()
    memo: dict = {}                    # one stats walk per plan node

    def pipe_rows(pipe) -> int:
        try:
            table = catalog.table(pipe.scan.table)
        except KeyError:
            return 0
        rows = getattr(table, "num_rows", 0)
        if rows and lattice and pipe.scan.prune:
            rows = min(rows, scan_rows_bound(catalog, pipe.scan, snapshot)
                       or rows)
        return int(rows)

    def pipe_bytes(pipe) -> int:
        rows = pipe_rows(pipe)
        if not rows:
            return 0
        table = catalog.table(pipe.scan.table)
        per_row = 0
        for (s, _i) in pipe.scan.columns:
            if not table.schema.has(s):
                continue
            dt = table.schema.dtype(s)
            per_row += np.dtype(dt.np).itemsize + (1 if dt.nullable else 0)
        return rows * per_row

    def payload_width(bp, step) -> int:
        """Per-row bytes of the payload columns a probe attaches
        (data + validity; unresolvable names assume a wide 9-byte
        column — overcharging beats under-admitting)."""
        try:
            table = catalog.table(bp.scan.table)
        except KeyError:
            return 9 * len(step.payload)
        w = 0
        for name in step.payload:
            if table.schema.has(name):
                dt = table.schema.dtype(name)
                w += np.dtype(dt.np).itemsize + 1   # probe payloads
                #                                     are nullable-tagged
            else:
                w += 9                 # derived/renamed build column
        return w

    total = pipe_bytes(plan.pipeline)
    probe_rows = pipe_rows(plan.pipeline)
    for kind, step in plan.pipeline.steps:
        if kind != "join":
            continue
        build = step.build
        bp = getattr(build, "pipeline", build)   # QueryPlan | Pipeline
        if not hasattr(bp, "scan"):
            continue
        scan_est = pipe_bytes(bp)
        if lattice:
            bb = build_bytes_bound(catalog, step, snapshot, memo)
            if bb and bb < scan_est:
                GLOBAL.inc("bounds/admission_capped_bytes",
                           scan_est - bb)
                scan_est = bb
        total += scan_est
        # the probe-time copy of this join's output columns
        if step.kind in ("inner", "left") and step.payload:
            width = payload_width(bp, step)
            if late:
                # (int32 row-id + bool match) per probe row; widths
                # materialize once at build cardinality
                total += probe_rows * 5 + pipe_rows(bp) * width
            else:
                total += probe_rows * width
        elif step.kind == "mark":
            total += probe_rows       # 1-byte match-flag column
    return total
