"""Planner-wide bounded-cardinality lattice.

Generalizes the proven-cardinality machinery that retired the group-by
gathers (`ir.GroupBy.out_bound`, planner `_groups_bound`, the executor's
join-derived bound rewrite) into a bound that rides the WHOLE plan: every
pipeline carries a row-count upper bound derived bottom-up — scans bound
by table row counts (prune-aware where portion stats eliminate portions),
filters by selectivity-1 pass-through, inner/semi joins by build-side key
multiplicity, group-bys by key-domain products / proven out_bounds,
LIMIT by its K — and consumers size data movement from the proven bound
instead of worst-case capacity (the stance of arxiv 2112.01075: size
redistribution from static bounds, not padding).

Two trust tiers, deliberately distinct:

  * ``ir.GroupBy.out_bound`` / ``carry_keys`` are CORRECTNESS-BEARING —
    an understatement silently drops/merges groups. Only runtime-verified
    sources set them (the executor rewrite over materialized builds).
  * ``Pipeline.out_bound`` / ``QueryPlan.out_bound`` (stamped here) are
    SIZING-QUALITY — consumed by admission estimates, segment sizing with
    overflow reruns, EXPLAIN, and counters. They trust declared PKs for
    join-multiplicity the same way the join planner ranks with them.

`YDB_TPU_BOUNDS=0` disables the lattice end-to-end (plan stamping, the
executor carry/bound rewrite, admission capping, segment shrinking) —
byte-equal execution at capacity sizing, and part of the plan-cache
fingerprint plus every compiled-program cache key via `groupby_tuning`.
"""

from __future__ import annotations

import os

from ydb_tpu.ops import ir

_BIG = 1 << 62


def bounds_enabled() -> bool:  # lint: tuning-provider
    """`YDB_TPU_BOUNDS` lever: unset/1 = on; 0 = capacity sizing."""
    return os.environ.get("YDB_TPU_BOUNDS", "1").strip() != "0"


def groupby_bound(gb: ir.GroupBy) -> int:
    """Static group-count bound of one GroupBy: a stamped proven
    out_bound, else the mixed-radix key-domain product (0 = unbounded)."""
    if not gb.keys:
        return 1
    if gb.out_bound:
        return int(gb.out_bound)
    if gb.key_domains and all(d > 0 for d in gb.key_domains) \
            and len(gb.key_domains) == len(gb.keys):
        nb = 1
        for d in gb.key_domains:
            nb *= d + 1
            if nb > (1 << 40):
                return 0
        return nb
    return 0


def program_bound(prog, rows: int) -> int:
    """Row bound after a program: Filters/Assigns/Projections pass
    through (selectivity ≤ 1); each GroupBy caps rows at its group
    bound. `rows` 0 = unknown in, unknown out unless a GroupBy bounds."""
    out = rows
    if prog is None:
        return out
    for cmd in prog.commands:
        if isinstance(cmd, ir.GroupBy):
            gb = groupby_bound(cmd)
            if gb and out:
                out = min(out, gb)
            elif gb:
                out = gb
            # unbounded group-by: ngroups ≤ input rows — pass-through
    return out


def scan_rows_bound(catalog, scan, snapshot=None) -> int:
    """Driving-scan row bound: the table row count, tightened by a
    portion-stats prune preview when the plan carries prune predicates
    (the same `prune_by_range` elimination the executor performs at
    source enumeration — stats reads only, no block data touched)."""
    try:
        table = catalog.table(scan.table)
    except KeyError:
        return 0
    rows = int(getattr(table, "num_rows", 0))
    if not rows:
        return 0
    if not scan.prune:
        return rows
    try:
        from ydb_tpu.storage.mvcc import MAX_SNAPSHOT
        from ydb_tpu.storage.portion import prune_by_range
        if snapshot is None:
            snapshot = MAX_SNAPSHOT
        kept = 0
        for shard in table.shards:
            for p in shard.portions:
                if not snapshot.includes(p.version):
                    continue
                if any(prune_by_range(p, c, op, v)
                       for (c, op, v) in scan.prune):
                    continue
                kept += p.length
            for e in shard.inserts:
                kept += e.block.length
        return min(rows, kept) if kept else min(rows, 1)
    except Exception:                  # noqa: BLE001 — sizing, not law
        return rows


def _build_key_unique_declared(step, catalog) -> bool:
    """Does the build side's key provably (by DECLARED PK) hold unique
    values? True when the build is a plain pipeline whose key column is
    exactly its scan table's primary key, with no expanding steps of its
    own, or a subquery plan whose output is grouped by the key."""
    from ydb_tpu.query.plan import QueryPlan
    build = step.build
    if isinstance(build, QueryPlan):
        # subquery build: grouped/distinct output keyed on the build key.
        # The build key is the plan's OUTPUT label (`__s0k0`) — resolve
        # it back to the projected internal name first, or a grouped
        # q18-class build (group l_orderkey having sum > K) reads as
        # non-unique just because of the rename.
        bk = step.build_key
        for (iname, label) in build.output:
            if label == bk:
                bk = iname
                break
        progs = [build.pipeline.partial, build.final_program]
        for prog in progs:
            if prog is None:
                continue
            for cmd in prog.commands:
                if isinstance(cmd, ir.GroupBy) and cmd.keys \
                        and len(cmd.keys) + len(cmd.carry_keys) >= 1 \
                        and bk in cmd.keys \
                        and len(cmd.keys) == 1:
                    return True
        return False
    if step.build_hash_keys:
        keys = list(step.build_hash_keys)
    elif step.build_key_cols:
        # in-program composite hash: the synthesized `__jkNb` isn't a
        # storage column, but the columns it was derived from are — a
        # 64-bit hash of a unique tuple stays unique for sizing purposes
        # (collisions are post-join-verified and overflow-rerun-guarded)
        keys = list(step.build_key_cols)
    else:
        keys = [step.build_key]
    storage = {i: s for (s, i) in build.scan.columns}
    cols = {storage.get(k) for k in keys}
    if None in cols:
        return False
    try:
        table = catalog.table(build.scan.table)
    except KeyError:
        return False
    if set(table.key_columns) != cols:
        return False
    # the build's own joins must not expand it (unique-keyed probes keep
    # row count; any inner/left join is conservatively treated as
    # potentially expanding unless ITS build is PK-unique too)
    for kind, s2 in build.steps:
        if kind == "join" and s2.kind in ("inner", "left") \
                and not _build_key_unique_declared(s2, catalog):
            return False
    return True


def pipeline_bound(pipe, catalog, snapshot=None, _memo=None) -> int:
    """Bottom-up row bound of one pipeline (0 = unknown). `_memo`
    (id(node) → bound) dedups the walk within one derivation — nested
    builds would otherwise re-run the portion-stats scan preview once
    per enclosing level (2^depth walks on the q8 join-chain class)."""
    if _memo is not None and id(pipe) in _memo:
        return _memo[id(pipe)]
    rows = scan_rows_bound(catalog, pipe.scan, snapshot)
    bound = rows
    for kind, step in pipe.steps:
        if kind != "join":
            bound = program_bound(step, bound)
            continue
        if step.kind in ("left_semi", "left_anti", "mark"):
            continue                   # never expands the probe stream
        if _build_key_unique_declared(step, catalog):
            continue                   # unique build: row-preserving
        b = step.build
        from ydb_tpu.query.plan import QueryPlan
        bb = plan_bound(b, catalog, snapshot, _memo) \
            if isinstance(b, QueryPlan) \
            else pipeline_bound(b, catalog, snapshot, _memo)
        if bound and bb:
            bound = min(bound * bb, _BIG)
        else:
            bound = 0                  # unknown multiplicity
    bound = program_bound(pipe.partial, bound)
    if _memo is not None:
        _memo[id(pipe)] = bound
    return bound


def plan_bound(plan, catalog, snapshot=None, _memo=None) -> int:
    """Row bound of a whole plan's result (0 = unknown)."""
    key = ("plan", id(plan))
    if _memo is not None and key in _memo:
        return _memo[key]
    bound = pipeline_bound(plan.pipeline, catalog, snapshot, _memo)
    bound = program_bound(plan.final_program, bound)
    if plan.limit is not None:
        k = int(plan.limit) + int(plan.offset or 0)
        bound = min(bound, k) if bound else k
    if _memo is not None:
        _memo[key] = bound
    return bound


def annotate_plan(plan, catalog, snapshot=None):
    """Stamp the lattice onto a freshly planned SELECT: every pipeline's
    `out_bound` (driving + build fragments, recursively) and the plan's
    result bound. No-op with the lever off. Mutates the plan in place
    (plans are per-query objects at this point; the plan cache stores
    the annotated plan, and the fingerprint carries the lever)."""
    if not bounds_enabled():
        return plan
    from ydb_tpu.query.plan import QueryPlan
    from ydb_tpu.utils.metrics import GLOBAL
    memo: dict = {}                    # one stats walk per node

    def walk_pipe(pipe):
        for kind, step in pipe.steps:
            if kind != "join":
                continue
            if isinstance(step.build, QueryPlan):
                walk_plan(step.build)
            else:
                walk_pipe(step.build)
                step.build.out_bound = pipeline_bound(
                    step.build, catalog, snapshot, memo)
        pipe.out_bound = pipeline_bound(pipe, catalog, snapshot, memo)

    def walk_plan(p):
        walk_pipe(p.pipeline)
        for (_n, sub) in p.init_subplans:
            walk_plan(sub)
        p.out_bound = plan_bound(p, catalog, snapshot, memo)

    walk_plan(plan)
    # lint: allow-counters(bounds/* registered)
    GLOBAL.inc("bounds/plans")
    if plan.out_bound:
        GLOBAL.inc("bounds/finite_plans")
    return plan


def dataset_distinct(block, cols: list) -> int:
    """Distinct (validity-aware) tuple count of `cols` over a HostBlock —
    the measured side of the carry rewrite's functional-dependency
    verification. Counts under THE grouping equality itself
    (`ops/numpy_exec.canonical_key_pair`, shared with the group-by
    oracle): NULLs form one value per column, -0.0 == 0.0, all NaNs
    equal."""
    import numpy as np

    from ydb_tpu.ops.numpy_exec import canonical_key_pair
    if block.length == 0:
        return 0
    mats = []
    for name in cols:
        cd = block.columns[name]
        phys, valid = canonical_key_pair(cd.data, cd.valid)
        mats.append(phys)
        mats.append(valid)
    mat = np.stack(mats, axis=1)
    return int(len(np.unique(mat, axis=0)))


def build_bytes_bound(catalog, step, snapshot=None, _memo=None) -> int:
    """Admission-sizing bound for one join build's MATERIALIZED bytes:
    the build executes and lands host-side at its OUTPUT cardinality, so
    a bounded build (grouped subquery, LIMIT, bounded multiplicity
    chain) reserves bound × row-width instead of its driving scan's full
    table footprint (the q21 build double-charge class)."""
    import numpy as np
    from ydb_tpu.query.plan import QueryPlan
    build = step.build
    bp = getattr(build, "pipeline", build)
    if not hasattr(bp, "scan"):
        return 0
    bound = plan_bound(build, catalog, snapshot, _memo) \
        if isinstance(build, QueryPlan) \
        else pipeline_bound(build, catalog, snapshot, _memo)
    if not bound:
        return 0
    try:
        table = catalog.table(bp.scan.table)
    except KeyError:
        return 0
    per_row = 0
    for (s, _i) in bp.scan.columns:
        if table.schema.has(s):
            dt = table.schema.dtype(s)
            per_row += np.dtype(dt.np).itemsize + (1 if dt.nullable else 0)
    return bound * max(per_row, 1)
