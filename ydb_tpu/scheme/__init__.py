from ydb_tpu.scheme.catalog import Catalog  # noqa: F401
