"""System views: virtual `.sys/...` tables served through the scan path.

The reference exposes cluster/runtime state as virtual tables under
`.sys` (`ydb/core/sys_view/common/schema.h`: partition_stats,
query_metrics_one_minute, top_queries_by_duration_*, …), deliberately
served through the SAME scan protocol as user tables
(`sys_view/scan.cpp`) so every SQL feature composes with them. Same
stance here: a sysview materializes to a transient column table at plan
time and the normal engine executes the query over it — joins, filters,
aggregates and EXPLAIN all work on `.sys` views for free.
"""

from __future__ import annotations

import pandas as pd

from ydb_tpu.core.block import HostBlock

PREFIX = ".sys/"

VIEWS = ("tables", "partition_stats", "counters", "query_metrics",
         "top_queries_by_duration")


def is_sysview(name: str) -> bool:
    return name.startswith(PREFIX)


def sysview_block(engine, name: str) -> HostBlock:
    view = name[len(PREFIX):]
    if view == "tables":
        rows = [{
            "table_name": n,
            "store": getattr(t, "store_kind", "column"),
            "shards": len(getattr(t, "shards", [])) or 1,
            "rows": int(t.num_rows),
            "data_version": int(getattr(t, "data_version", 0)),
        } for n, t in sorted(engine.catalog.tables.items())
            if not getattr(t, "transient", False)]
        return _block(rows, [("table_name", str), ("store", str),
                             ("shards", "int64"), ("rows", "int64"),
                             ("data_version", "int64")])
    if view == "partition_stats":
        rows = []
        for n, t in sorted(engine.catalog.tables.items()):
            if getattr(t, "transient", False) \
                    or getattr(t, "store_kind", "column") == "row":
                continue
            for s in t.shards:
                rows.append({
                    "table_name": n, "shard_id": s.shard_id,
                    "portions": len(s.portions),
                    "rows": int(sum(p.num_rows for p in s.portions)),
                    "staged_inserts": len(s.inserts),
                })
        return _block(rows, [("table_name", str), ("shard_id", "int64"),
                             ("portions", "int64"), ("rows", "int64"),
                             ("staged_inserts", "int64")])
    if view == "counters":
        snap = engine.counters()
        rows = [{"counter": k, "value": float(v)}
                for k, v in snap.items()]
        return _block(rows, [("counter", str), ("value", "float64")])
    if view in ("query_metrics", "top_queries_by_duration"):
        hist = list(engine.query_history)
        if view == "top_queries_by_duration":
            hist = sorted(hist, key=lambda s: -s.total_ms)[:32]
        rows = [{
            "sql": st.sql, "kind": st.kind,
            "total_ms": st.total_ms, "parse_ms": st.parse_ms,
            "plan_ms": st.plan_ms, "execute_ms": st.execute_ms,
            "rows_out": int(st.rows_out),
            "path": ("distributed" if st.distributed
                     else "fused" if st.fused else "portioned"),
            "cache_hit": bool(st.plan_cache_hit),
        } for st in hist]
        return _block(rows, [("sql", str), ("kind", str),
                             ("total_ms", "float64"),
                             ("parse_ms", "float64"),
                             ("plan_ms", "float64"),
                             ("execute_ms", "float64"),
                             ("rows_out", "int64"), ("path", str),
                             ("cache_hit", "bool")])
    raise KeyError(f"unknown system view {name!r} "
                   f"(have: {', '.join(PREFIX + v for v in VIEWS)})")


def _block(rows: list, spec: list) -> HostBlock:
    """Typed block even when empty (object-dtype inference would fail)."""
    df = pd.DataFrame(rows, columns=[n for (n, _) in spec])
    for n, dtype in spec:
        if dtype is str:
            df[n] = df[n].astype(object).where(df[n].notna(), "")
        else:
            df[n] = df[n].fillna(0).astype(dtype)
    return HostBlock.from_pandas(df)
