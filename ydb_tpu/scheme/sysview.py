"""System views: virtual `.sys/...` tables served through the scan path.

The reference exposes cluster/runtime state as virtual tables under
`.sys` (`ydb/core/sys_view/common/schema.h`: partition_stats,
query_metrics_one_minute, top_queries_by_duration_*, …), deliberately
served through the SAME scan protocol as user tables
(`sys_view/scan.cpp`) so every SQL feature composes with them. Same
stance here: a sysview materializes to a transient column table at plan
time and the normal engine executes the query over it — joins, filters,
aggregates and EXPLAIN all work on `.sys` views for free.
"""

from __future__ import annotations

import pandas as pd

from ydb_tpu.core.block import HostBlock

PREFIX = ".sys/"

VIEWS = ("tables", "partition_stats", "counters", "query_metrics",
         "top_queries_by_duration", "dq_stage_stats", "query_profiles",
         "cluster_nodes", "query_memory", "device_transfers",
         "query_critical_path", "compiled_programs", "progstore",
         "materialized_views")


def is_sysview(name: str) -> bool:
    return name.startswith(PREFIX)


def sysview_block(engine, name: str) -> HostBlock:
    view = name[len(PREFIX):]
    if view == "tables":
        rows = [{
            "table_name": n,
            "store": getattr(t, "store_kind", "column"),
            "shards": len(getattr(t, "shards", [])) or 1,
            "rows": int(t.num_rows),
            "data_version": int(getattr(t, "data_version", 0)),
        } for n, t in sorted(engine.catalog.tables.items())
            if not getattr(t, "transient", False)]
        return _block(rows, [("table_name", str), ("store", str),
                             ("shards", "int64"), ("rows", "int64"),
                             ("data_version", "int64")])
    if view == "partition_stats":
        rows = []
        for n, t in sorted(engine.catalog.tables.items()):
            if getattr(t, "transient", False) \
                    or getattr(t, "store_kind", "column") == "row":
                continue
            for s in t.shards:
                rows.append({
                    "table_name": n, "shard_id": s.shard_id,
                    "portions": len(s.portions),
                    "rows": int(sum(p.num_rows for p in s.portions)),
                    "staged_inserts": len(s.inserts),
                })
        return _block(rows, [("table_name", str), ("shard_id", "int64"),
                             ("portions", "int64"), ("rows", "int64"),
                             ("staged_inserts", "int64")])
    if view == "counters":
        snap = engine.counters()
        rows = [{"counter": k, "value": float(v)}
                for k, v in snap.items()]
        return _block(rows, [("counter", str), ("value", "float64")])
    if view in ("query_metrics", "top_queries_by_duration"):
        hist = list(engine.query_history)
        if view == "top_queries_by_duration":
            hist = sorted(hist, key=lambda s: -s.total_ms)[:32]
        rows = [{
            "sql": st.sql, "kind": st.kind,
            "total_ms": st.total_ms, "parse_ms": st.parse_ms,
            "plan_ms": st.plan_ms, "execute_ms": st.execute_ms,
            "rows_out": int(st.rows_out),
            "path": ("distributed" if st.distributed
                     else "fused" if st.fused else "portioned"),
            "cache_hit": bool(st.plan_cache_hit),
        } for st in hist]
        return _block(rows, [("sql", str), ("kind", str),
                             ("total_ms", "float64"),
                             ("parse_ms", "float64"),
                             ("plan_ms", "float64"),
                             ("execute_ms", "float64"),
                             ("rows_out", "int64"), ("path", str),
                             ("cache_hit", "bool")])
    if view == "dq_stage_stats":
        # per-(stage, worker) task stats of recent DQ graph runs — the
        # TDqTaskRunnerStatsView seat (filled by dq/runner.py)
        rows = [{
            "trace_id": int(r.get("trace_id", 0)),
            "graph": r.get("graph", ""), "stage": r.get("stage", ""),
            "worker": r.get("worker", ""), "state": r.get("state", ""),
            "attempts": int(r.get("attempts", 0)),
            "channel": str(r.get("channel", "")),
            "rows": int(r.get("rows", 0)),
            "bytes": int(r.get("bytes", 0)),
            "frames": int(r.get("frames", 0)),
            "plane": str(r.get("plane", "host")),
            "ici_bytes": int(r.get("ici_bytes", 0)),
            "pad_live_bytes": int(r.get("pad_live_bytes", 0)),
            "pad_padded_bytes": int(r.get("pad_padded_bytes", 0)),
            "pad_efficiency": float(r.get("pad_efficiency", 0.0) or 0.0),
            "exec_ms": float(r.get("exec_ms", 0.0)),
            "flush_ms": float(r.get("flush_ms", 0.0)),
            "input_wait_ms": float(r.get("input_wait_ms", 0.0)),
            "backpressure_wait_ms": float(
                r.get("backpressure_wait_ms", 0.0)),
        } for r in list(getattr(engine, "dq_stage_stats", []))]
        return _block(rows, [("trace_id", "int64"), ("graph", str),
                             ("stage", str), ("worker", str),
                             ("state", str), ("attempts", "int64"),
                             ("channel", str),
                             ("rows", "int64"), ("bytes", "int64"),
                             ("frames", "int64"), ("plane", str),
                             ("ici_bytes", "int64"),
                             ("pad_live_bytes", "int64"),
                             ("pad_padded_bytes", "int64"),
                             ("pad_efficiency", "float64"),
                             ("exec_ms", "float64"),
                             ("flush_ms", "float64"),
                             ("input_wait_ms", "float64"),
                             ("backpressure_wait_ms", "float64")])
    if view == "query_profiles":
        # the last-N assembled profiles (sampled statements + DQ runs):
        # wall, span count, and the device-timeline phase rollup
        rows = []
        for p in list(getattr(engine, "profiles", [])):
            ph = p.get("phases") or {}
            rows.append({
                "trace_id": int(p.get("trace_id", 0)),
                "sql": p.get("sql", ""), "kind": p.get("kind", ""),
                "total_ms": float(p.get("total_ms", 0.0)),
                "rows_out": int(p.get("rows_out", 0)),
                "n_spans": int(p.get("n_spans", 0)),
                "n_stages": len(p.get("stages") or []),
                "compile_ms": float(ph.get("compile_ms", 0.0)),
                "build_ms": float(ph.get("build_ms", 0.0)),
                "upload_ms": float(ph.get("upload_ms", 0.0)),
                "dispatch_ms": float(ph.get("dispatch_ms", 0.0)),
                "device_ms": float(ph.get("device_ms", 0.0)),
                "readout_ms": float(ph.get("readout_ms", 0.0)),
            })
        return _block(rows, [("trace_id", "int64"), ("sql", str),
                             ("kind", str), ("total_ms", "float64"),
                             ("rows_out", "int64"), ("n_spans", "int64"),
                             ("n_stages", "int64"),
                             ("compile_ms", "float64"),
                             ("build_ms", "float64"),
                             ("upload_ms", "float64"),
                             ("dispatch_ms", "float64"),
                             ("device_ms", "float64"),
                             ("readout_ms", "float64")])
    if view == "cluster_nodes":
        # Hive membership/placement (the `ds_clusters`/nodes sysview
        # seat): one row per registered worker, lease liveness included.
        # Empty when no Hive is attached to this engine — the view
        # exists on every node, the CONTROL PLANE lives on one.
        hive = getattr(engine, "hive", None)
        if hive is not None:
            # membership-level sweep only: the view must not show
            # expired leases as alive, but a monitoring SELECT must
            # never trigger re-placement DATA MOVEMENT (hive.sweep()
            # replays shard images; the query path owns that)
            hive.membership.sweep()
        rows = [{
            "node_id": r["node_id"], "endpoint": r["endpoint"],
            "state": r["state"],
            "lease_ms_left": float(r["lease_ms_left"]),
            "heartbeats": int(r["heartbeats"]),
            "capacity": float(r["capacity"]),
            "load": float(r["load"]), "shards": r["shards"],
            "stale": bool(r["stale"]),
        } for r in (hive.rows() if hive is not None else [])]
        return _block(rows, [("node_id", str), ("endpoint", str),
                             ("state", str),
                             ("lease_ms_left", "float64"),
                             ("heartbeats", "int64"),
                             ("capacity", "float64"),
                             ("load", "float64"), ("shards", str),
                             ("stale", "bool")])
    if view == "query_memory":
        # per-statement resource-ledger rollups (engine.memory_stats,
        # filled when a statement's ledger closes — utils/memledger.py):
        # the bytes companion of `query_metrics`
        rows = [{
            "sql": r.get("sql", ""), "kind": r.get("kind", ""),
            "peak_bytes": int(r.get("peak_bytes", 0)),
            "alloc_bytes": int(r.get("alloc_bytes", 0)),
            "live_bytes": int(r.get("live_bytes", 0)),
            "padded_bytes": int(r.get("padded_bytes", 0)),
            "waste_bytes": int(r.get("waste_bytes", 0)),
            "pad_efficiency": float(r.get("pad_efficiency") or 0.0),
            "transfers": int(r.get("transfers", 0)),
            "transfer_bytes": int(r.get("transfer_bytes", 0)),
            "to_pandas_in_plan": int(r.get("to_pandas_in_plan", 0)),
            "admission_est_bytes":
                int(r.get("admission_est_bytes") or 0),
            "est_error_pct": float(r.get("est_error_pct") or 0.0),
        } for r in list(getattr(engine, "memory_stats", []))]
        return _block(rows, [("sql", str), ("kind", str),
                             ("peak_bytes", "int64"),
                             ("alloc_bytes", "int64"),
                             ("live_bytes", "int64"),
                             ("padded_bytes", "int64"),
                             ("waste_bytes", "int64"),
                             ("pad_efficiency", "float64"),
                             ("transfers", "int64"),
                             ("transfer_bytes", "int64"),
                             ("to_pandas_in_plan", "int64"),
                             ("admission_est_bytes", "int64"),
                             ("est_error_pct", "float64")])
    if view == "query_critical_path":
        # per-statement critical-path rollups (engine.critpath_stats,
        # utils/critpath.py): the blocking-chain class decomposition —
        # which chain of spans bounded each query's wall, by class.
        # Empty under YDB_TPU_CRITPATH=0.
        rows = [{
            "trace_id": int(r.get("trace_id", 0)),
            "sql": r.get("sql", ""), "kind": r.get("kind", ""),
            "wall_ms": float(r.get("wall_ms", 0.0)),
            "coverage": float(r.get("coverage", 0.0)),
            "connected": bool(r.get("connected", False)),
            "non_device_ms": float(r.get("non_device_ms", 0.0)),
            "device_execute_ms": float(r.get("device_execute_ms", 0.0)),
            "compile_ms": float(r.get("compile_ms", 0.0)),
            "host_transfer_ms": float(r.get("host_transfer_ms", 0.0)),
            "host_lane_ms": float(r.get("host_lane_ms", 0.0)),
            "channel_wait_ms": float(r.get("channel_wait_ms", 0.0)),
            "admission_wait_ms": float(r.get("admission_wait_ms", 0.0)),
            "scheduler_gap_ms": float(r.get("scheduler_gap_ms", 0.0)),
            "dominant_span": r.get("dominant_span", ""),
            "dominant_class": r.get("dominant_class", ""),
            "dominant_ms": float(r.get("dominant_ms", 0.0)),
        } for r in list(getattr(engine, "critpath_stats", []))]
        return _block(rows, [("trace_id", "int64"), ("sql", str),
                             ("kind", str), ("wall_ms", "float64"),
                             ("coverage", "float64"),
                             ("connected", "bool"),
                             ("non_device_ms", "float64"),
                             ("device_execute_ms", "float64"),
                             ("compile_ms", "float64"),
                             ("host_transfer_ms", "float64"),
                             ("host_lane_ms", "float64"),
                             ("channel_wait_ms", "float64"),
                             ("admission_wait_ms", "float64"),
                             ("scheduler_gap_ms", "float64"),
                             ("dominant_span", str),
                             ("dominant_class", str),
                             ("dominant_ms", "float64")])
    if view == "compiled_programs":
        # the compiled-program inventory (utils/progstats.py, process-
        # wide like device_transfers): one row per captured executable —
        # cache hit/miss/eviction counts, compile wall, the XLA cost +
        # memory analysis, cumulative measured device ms and the
        # roofline verdict. Evicted entries persist marked `evicted`;
        # `cost` is an explicit 'unavailable' where the backend
        # withholds analysis (never fabricated zeros). Empty under
        # YDB_TPU_PROGSTATS=0.
        from ydb_tpu.utils.progstats import inventory_rows
        rows = [{
            "program": r["program"], "kind": r["kind"],
            "state": r["state"], "source": r["source"],
            "hits": int(r["hits"]),
            "misses": int(r["misses"]),
            "evictions": int(r["evictions"]),
            "compiles": int(r["compiles"]),
            "compile_ms": float(r["compile_ms"]),
            "cost": r["cost"],
            "flops": float(r["flops"]),
            "transcendentals": float(r["transcendentals"]),
            "bytes_accessed": float(r["bytes_accessed"]),
            "output_bytes": float(r["output_bytes"]),
            "hlo_ops": int(r["hlo_ops"]),
            "arg_bytes": int(r["arg_bytes"]),
            "out_bytes": int(r["out_bytes"]),
            "temp_bytes": int(r["temp_bytes"]),
            "code_bytes": int(r["code_bytes"]),
            "execs": int(r["execs"]),
            "device_ms": float(r["device_ms"]),
            "device_ms_max": float(r["device_ms_max"]),
            "achieved_gflops": float(r["achieved_gflops"]),
            "achieved_gbps": float(r["achieved_gbps"]),
            "intensity": float(r["intensity"]),
            "utilization_pct": float(r["utilization_pct"]),
            "bound_class": r["bound_class"],
        } for r in inventory_rows()]
        return _block(rows, [("program", str), ("kind", str),
                             ("state", str), ("source", str),
                             ("hits", "int64"),
                             ("misses", "int64"),
                             ("evictions", "int64"),
                             ("compiles", "int64"),
                             ("compile_ms", "float64"), ("cost", str),
                             ("flops", "float64"),
                             ("transcendentals", "float64"),
                             ("bytes_accessed", "float64"),
                             ("output_bytes", "float64"),
                             ("hlo_ops", "int64"),
                             ("arg_bytes", "int64"),
                             ("out_bytes", "int64"),
                             ("temp_bytes", "int64"),
                             ("code_bytes", "int64"),
                             ("execs", "int64"),
                             ("device_ms", "float64"),
                             ("device_ms_max", "float64"),
                             ("achieved_gflops", "float64"),
                             ("achieved_gbps", "float64"),
                             ("intensity", "float64"),
                             ("utilization_pct", "float64"),
                             ("bound_class", str)])
    if view == "progstore":
        # the persistent compiled-program store (ydb_tpu/progstore):
        # one row — index size, on-disk footprint, per-kind entry
        # counts, this process's load/save activity, the cumulative
        # store counters, and the admission backlog the compile-ahead
        # lane overlaps with. A disabled store reports root='' with
        # zero entries (never a fabricated store).
        from ydb_tpu.progstore import store as _pstore
        st = _pstore.stats()
        bl = engine.admission.backlog() \
            if hasattr(engine, "admission") else {}
        rows = [{
            "root": st["root"], "entries": int(st["entries"]),
            "objects": int(st["objects"]),
            "object_bytes": int(st["object_bytes"]),
            "fused": int(st["kinds"].get("fused", 0)),
            "batched": int(st["kinds"].get("batched", 0)),
            "program": int(st["kinds"].get("program", 0)),
            "loads": int(st["loads"]), "saves": int(st["saves"]),
            "hits": int(st["hits"]), "misses": int(st["misses"]),
            "writes": int(st["writes"]), "corrupt": int(st["corrupt"]),
            "refused": int(st["refused"]), "errors": int(st["errors"]),
            "env": st["env"], "device": st["device"],
            "admission_active": int(bl.get("active", 0)),
            "admission_in_flight_bytes":
                int(bl.get("in_flight_bytes", 0)),
        }]
        return _block(rows, [("root", str), ("entries", "int64"),
                             ("objects", "int64"),
                             ("object_bytes", "int64"),
                             ("fused", "int64"), ("batched", "int64"),
                             ("program", "int64"), ("loads", "int64"),
                             ("saves", "int64"), ("hits", "int64"),
                             ("misses", "int64"), ("writes", "int64"),
                             ("corrupt", "int64"),
                             ("refused", "int64"), ("errors", "int64"),
                             ("env", str), ("device", str),
                             ("admission_active", "int64"),
                             ("admission_in_flight_bytes", "int64")])
    if view == "materialized_views":
        # the continuous-query registry (ydb_tpu/views/): one row per
        # view — source, CDC topic, the watermark plan_step its state is
        # exact at, current lag in coordinator steps, state size, fold/
        # rebuild activity, and the degraded flag (permanent base-query
        # fallback after the bounds escape)
        rows = [{
            "name": r["name"], "source": r["source"],
            "kind": r["kind"], "topic": r["topic"],
            "watermark_step": int(r["watermark_step"]),
            "lag_versions": int(r["lag_versions"]),
            "state_rows": int(r["state_rows"]),
            "state_bytes": int(r["state_bytes"]),
            "folds": int(r["folds"]), "rebuilds": int(r["rebuilds"]),
            "degraded": bool(r["degraded"]),
        } for r in engine.views.sysview_rows()]
        return _block(rows, [("name", str), ("source", str),
                             ("kind", str), ("topic", str),
                             ("watermark_step", "int64"),
                             ("lag_versions", "int64"),
                             ("state_rows", "int64"),
                             ("state_bytes", "int64"),
                             ("folds", "int64"), ("rebuilds", "int64"),
                             ("degraded", "bool")])
    if view == "device_transfers":
        # the host-transfer flight recorder's recent-transfer ring
        # (utils/memledger.py, process-wide): one row per recorded
        # device→host readback — plus device→device stage handoffs
        # (`device_to_device` true), which never cross the link —
        # newest last
        from ydb_tpu.utils.memledger import transfer_ring
        rows = [{
            "seq": int(r["seq"]), "site": r["site"],
            "bytes": int(r["bytes"]), "count": int(r["count"]),
            "boundary": bool(r["boundary"]),
            "to_pandas_in_plan": bool(r["to_pandas_in_plan"]),
            "device_to_device": bool(r.get("device_to_device", False)),
        } for r in transfer_ring()]
        return _block(rows, [("seq", "int64"), ("site", str),
                             ("bytes", "int64"), ("count", "int64"),
                             ("boundary", "bool"),
                             ("to_pandas_in_plan", "bool"),
                             ("device_to_device", "bool")])
    raise KeyError(f"unknown system view {name!r} "
                   f"(have: {', '.join(PREFIX + v for v in VIEWS)})")


def _block(rows: list, spec: list) -> HostBlock:
    """Typed block even when empty (object-dtype inference would fail)."""
    df = pd.DataFrame(rows, columns=[n for (n, _) in spec])
    for n, dtype in spec:
        if dtype is str:
            df[n] = df[n].astype(object).where(df[n].notna(), "")
        else:
            df[n] = df[n].fillna(0).astype(dtype)
    return HostBlock.from_pandas(df)
