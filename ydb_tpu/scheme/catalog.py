"""Table catalog — the SchemeShard/SchemeCache analog (embedded, v0).

The reference keeps a path tree in the SchemeShard tablet
(`ydb/core/tx/schemeshard/schemeshard_impl.h:69`) replicated to per-node
SchemeCaches (`ydb/core/tx/scheme_cache/scheme_cache.h:102`). Here the
catalog is an in-process registry of tables; DDL versioning, path tree, and
replication arrive with the distributed control plane.
"""

from __future__ import annotations

from typing import Optional

from ydb_tpu.core.schema import Schema
from ydb_tpu.storage.table import ColumnTable


class Catalog:
    def __init__(self, store=None):
        """`store`: a `ydb_tpu.storage.persist.Store` for durability; None
        keeps the catalog purely in-memory (tests, transient engines)."""
        self.tables: dict[str, ColumnTable] = {}
        self.store = store
        self._next_version = 1
        # scalar UDF registry (query/udf.py) with the standard string/
        # url/re2/json/ip library preinstalled; engine.register_udf adds
        from ydb_tpu.query.udf import UdfRegistry
        self.udfs = UdfRegistry()

    def create_table(self, name: str, schema: Schema, key_columns: list[str],
                     shards: int = 1, portion_rows: int = 1 << 20,
                     partition_by: Optional[list[str]] = None,
                     transient: bool = False,
                     store_kind: str = "column"):
        """`transient`: never persisted (materialized CTE/derived-table
        temps). `store_kind`: "column" (ColumnShard analog) or "row"
        (DataShard analog, `storage/rowtable.py`)."""
        if name in self.tables:
            raise ValueError(f"table {name!r} already exists")
        if store_kind == "row":
            from ydb_tpu.storage.rowtable import RowTable
            t = RowTable(name, schema, key_columns, shards, portion_rows,
                         partition_by)
        else:
            t = ColumnTable(name, schema, key_columns, shards, portion_rows,
                            partition_by)
        t.transient = transient
        t.catalog = self            # back-ref: split/merge re-save metadata
        self.tables[name] = t
        if self.store is not None and not transient:
            t.store = self.store
            self.store.create_table(t)
            self.store.save_catalog(self)
        return t

    def drop_table(self, name: str) -> None:
        t = self.tables.pop(name)
        if self.store is not None and t.store is not None:
            self.store.drop_table(name)
            self.store.save_catalog(self)

    def table(self, name: str) -> ColumnTable:
        t = self.tables.get(name)
        if t is None:
            raise KeyError(f"unknown table {name!r}")
        return t

    def has(self, name: str) -> bool:
        return name in self.tables
