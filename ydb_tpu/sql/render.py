"""AST → SQL text renderer.

The inverse of `ydb_tpu/sql/parser.py` for the expression/SELECT subset —
what the reference's `yql/sql` layer does when distributed stages ship
rewritten query fragments to other nodes. The cluster router
(`ydb_tpu/cluster/router.py`) renders per-shard partial queries and the
merge query from rewritten ASTs; round-tripping through our own parser is
the compatibility contract (tested in tests/test_cluster.py).
"""

from __future__ import annotations

from ydb_tpu.sql import ast


def _lit(v, hint=None) -> str:
    if v is None:
        return "NULL"
    if hint == "date":
        return f"date '{v}'"                 # parser keeps the ISO string
    if hint and hint.startswith("interval_"):
        return f"interval '{v}' {hint[len('interval_'):]}"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, str):
        s = v.replace("'", "''")
        return f"'{s}'"
    return repr(v)


def expr(e) -> str:                                   # noqa: C901
    if isinstance(e, ast.Name):
        return ".".join(e.parts)
    if isinstance(e, ast.Literal):
        return _lit(e.value, e.type_hint)
    if isinstance(e, ast.BinOp):
        return f"({expr(e.left)} {e.op} {expr(e.right)})"
    if isinstance(e, ast.UnaryOp):
        return f"({e.op} {expr(e.arg)})"
    if isinstance(e, ast.FuncCall):
        if e.star:
            return f"{e.name}(*)"
        inner = ", ".join(expr(a) for a in e.args)
        return f"{e.name}({'distinct ' if e.distinct else ''}{inner})"
    if isinstance(e, ast.Case):
        parts = ["CASE"]
        if e.operand is not None:
            parts.append(expr(e.operand))
        for (c, r) in e.whens:
            parts.append(f"WHEN {expr(c)} THEN {expr(r)}")
        if e.default is not None:
            parts.append(f"ELSE {expr(e.default)}")
        parts.append("END")
        return " ".join(parts)
    if isinstance(e, ast.Cast):
        return f"cast({expr(e.arg)} as {e.to})"
    if isinstance(e, ast.Between):
        neg = "not " if e.negated else ""
        return (f"({expr(e.arg)} {neg}between {expr(e.lo)} "
                f"and {expr(e.hi)})")
    if isinstance(e, ast.InList):
        neg = "not " if e.negated else ""
        items = ", ".join(expr(x) for x in e.items)
        return f"({expr(e.arg)} {neg}in ({items}))"
    if isinstance(e, ast.InSubquery):
        neg = "not " if e.negated else ""
        return f"({expr(e.arg)} {neg}in ({select(e.query)}))"
    if isinstance(e, ast.Exists):
        neg = "not " if e.negated else ""
        return f"({neg}exists ({select(e.query)}))"
    if isinstance(e, ast.ScalarSubquery):
        return f"({select(e.query)})"
    if isinstance(e, ast.Like):
        neg = "not " if e.negated else ""
        return f"({expr(e.arg)} {neg}like {_lit(e.pattern)})"
    if isinstance(e, ast.IsNull):
        return f"({expr(e.arg)} is {'not ' if e.negated else ''}null)"
    if isinstance(e, ast.Star):
        return f"{e.table}.*" if e.table else "*"
    if isinstance(e, ast.WindowFunc):
        inner = ", ".join(expr(a) for a in e.args)
        over = []
        if e.partition_by:
            over.append("partition by "
                        + ", ".join(expr(p) for p in e.partition_by))
        if e.order_by:
            over.append("order by " + ", ".join(_order(o)
                                                for o in e.order_by))
        if e.frame is not None:
            (_tag, lo, hi) = e.frame

            def bound(b):
                if isinstance(b, tuple):
                    return "unbounded preceding" if b[1] < 0 \
                        else "unbounded following"
                if b == 0:
                    return "current row"
                return f"{-b} preceding" if b < 0 else f"{b} following"
            over.append(f"rows between {bound(lo)} and {bound(hi)}")
        return f"{e.func}({inner}) over ({' '.join(over)})"
    raise TypeError(f"cannot render {type(e).__name__}")


def _order(o: ast.OrderItem) -> str:
    s = expr(o.expr) + ("" if o.ascending else " desc")
    if o.nulls_first is not None:
        s += " nulls first" if o.nulls_first else " nulls last"
    return s


def relation(r) -> str:
    if isinstance(r, ast.TableRef):
        return r.name + (f" {r.alias}" if r.alias else "")
    if isinstance(r, ast.SubqueryRef):
        return f"({select(r.query)}) {r.alias}"
    if isinstance(r, ast.Join):
        if r.kind == "cross":
            return f"{relation(r.left)}, {relation(r.right)}"
        on = f" on {expr(r.on)}" if r.on is not None else ""
        kw = {"inner": "join", "left": "left join",
              "right": "right join", "full": "full join"}[r.kind]
        return f"{relation(r.left)} {kw} {relation(r.right)}{on}"
    raise TypeError(f"cannot render relation {type(r).__name__}")


def select(s: ast.Select) -> str:
    parts = []
    if s.ctes:
        ctes = ", ".join(f"{name} as ({select(q)})" for (name, q) in s.ctes)
        parts.append(f"with {ctes}")
    items = ", ".join(
        expr(it.expr) + (f" as {it.alias}" if it.alias else "")
        for it in s.items)
    parts.append(f"select {'distinct ' if s.distinct else ''}{items}")
    if s.relation is not None:
        parts.append(f"from {relation(s.relation)}")
    if s.where is not None:
        parts.append(f"where {expr(s.where)}")
    if s.group_by:
        parts.append("group by " + ", ".join(expr(g) for g in s.group_by))
    if s.having is not None:
        parts.append(f"having {expr(s.having)}")
    if s.order_by:
        parts.append("order by " + ", ".join(_order(o) for o in s.order_by))
    if s.limit is not None:
        parts.append(f"limit {s.limit}")
    if s.offset:
        parts.append(f"offset {s.offset}")
    return " ".join(parts)
