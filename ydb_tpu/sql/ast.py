"""SQL AST.

The analog of the reference's SQL→AST layer (`ydb/library/yql/sql/v1/` —
ANTLR grammar `SQLv1.g.in` producing `TExprNode` s-expressions). Here the
grammar is hand-written recursive descent (ydb_tpu/sql/parser.py) and the
AST is plain dataclasses consumed by the logical planner
(ydb_tpu/query/planner.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Union


# -- expressions -----------------------------------------------------------


@dataclass(frozen=True)
class Name:
    """Column reference: `x` or `t.x`."""
    parts: tuple                   # ("x",) or ("t", "x")


@dataclass(frozen=True)
class Literal:
    value: Any                     # int | float | str | bool | None
    type_hint: Optional[str] = None  # "date" | "interval_day" | ...


@dataclass(frozen=True)
class BinOp:
    op: str                        # + - * / % and or = <> < <= > >= ||
    left: "Expr"
    right: "Expr"


@dataclass(frozen=True)
class UnaryOp:
    op: str                        # - not
    arg: "Expr"


@dataclass(frozen=True)
class FuncCall:
    name: str                      # lower-cased
    args: tuple                    # tuple[Expr, ...]
    distinct: bool = False         # COUNT(DISTINCT x)
    star: bool = False             # COUNT(*)


@dataclass(frozen=True)
class Case:
    operand: Optional["Expr"]      # CASE <operand> WHEN ... (None: searched)
    whens: tuple                   # tuple[(cond, result), ...]
    default: Optional["Expr"]


@dataclass(frozen=True)
class Cast:
    arg: "Expr"
    to: str                        # type name, lower-cased


@dataclass(frozen=True)
class Between:
    arg: "Expr"
    lo: "Expr"
    hi: "Expr"
    negated: bool = False


@dataclass(frozen=True)
class InList:
    arg: "Expr"
    items: tuple                   # tuple[Expr, ...]
    negated: bool = False


@dataclass(frozen=True)
class InSubquery:
    arg: "Expr"
    query: "Select"
    negated: bool = False


@dataclass(frozen=True)
class Exists:
    query: "Select"
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery:
    query: "Select"


@dataclass(frozen=True)
class Like:
    arg: "Expr"
    pattern: str
    negated: bool = False


@dataclass(frozen=True)
class IsNull:
    arg: "Expr"
    negated: bool = False


@dataclass(frozen=True)
class Star:
    """SELECT * or t.*"""
    table: Optional[str] = None


@dataclass(frozen=True)
class WindowFunc:
    """fn(args) OVER (PARTITION BY ... ORDER BY ...) — no frame clauses
    yet (the reference's window support lives in
    `yql/core/common_opt/yql_window.cpp`)."""
    func: str                      # row_number | rank | dense_rank |
    #                                sum | min | max | count | avg
    args: tuple                    # tuple[Expr, ...] (empty for row_number)
    partition_by: tuple = ()       # tuple[Expr, ...]
    order_by: tuple = ()           # tuple[OrderItem, ...]
    distinct: bool = False         # parsed but rejected (explicit error)
    # ROWS BETWEEN frame: ("rows", lo, hi); bounds are signed row offsets
    # (0 = current row) or ("unbounded", ±1)
    frame: tuple = None            # type: ignore[assignment]


@dataclass(frozen=True)
class BoundParam:
    """Planner-synthesized runtime parameter (uncorrelated scalar subquery
    result). Never produced by the parser."""
    name: str
    dtype: object                  # core.dtypes.DType


Expr = Union[Name, Literal, BinOp, UnaryOp, FuncCall, Case, Cast, Between,
             InList, InSubquery, Exists, ScalarSubquery, Like, IsNull, Star]


# -- relations -------------------------------------------------------------


@dataclass(frozen=True)
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass(frozen=True)
class SubqueryRef:
    query: "Select"
    alias: str


@dataclass(frozen=True)
class Join:
    kind: str                      # inner | left | right | full | cross
    left: "Relation"
    right: "Relation"
    on: Optional[Expr] = None


Relation = Union[TableRef, SubqueryRef, Join]


# -- statements ------------------------------------------------------------


@dataclass(frozen=True)
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass(frozen=True)
class OrderItem:
    expr: Expr
    ascending: bool = True
    nulls_first: Optional[bool] = None   # None = dialect default (last)


@dataclass
class Select:
    items: list = field(default_factory=list)          # list[SelectItem]
    relation: Optional[Relation] = None
    where: Optional[Expr] = None
    group_by: list = field(default_factory=list)       # list[Expr]
    having: Optional[Expr] = None
    order_by: list = field(default_factory=list)       # list[OrderItem]
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False
    ctes: list = field(default_factory=list)           # list[(name, Select)]


@dataclass
class SetOp:
    """UNION / UNION ALL chain; trailing ORDER BY/LIMIT bind to the whole
    set result (the `yql_expr` Extend/UnionAll callables)."""
    op: str          # union | union_all | intersect[_all] | except[_all]
    left: object                   # Select | SetOp
    right: object                  # Select
    order_by: list = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    ctes: list = field(default_factory=list)   # visible to every arm


@dataclass
class CreateTable:
    name: str
    columns: list                     # list[(name, type_str, not_null)]
    primary_key: list                 # list[str]
    partition_count: int = 1
    store: str = "column"             # column | row
    ttl_column: str = ""              # WITH (ttl_column=..., ttl_days=N)
    ttl_days: int = 0
    if_not_exists: bool = False


@dataclass
class DropTable:
    name: str
    if_exists: bool = False


@dataclass
class CreateIndex:
    name: str
    table: str
    column: str


@dataclass
class DropIndex:
    name: str
    table: str


@dataclass
class AlterTable:
    """ADD COLUMN / DROP COLUMN (schemeshard__operation_alter_table
    analog — the v0 of the reference's ~120 suboperation state machines)."""
    name: str
    action: str                       # "add" | "drop"
    column: str = ""
    col_type: str = ""                # for add
    not_null: bool = False            # for add (empty tables only)


@dataclass
class Insert:
    table: str
    columns: list                     # list[str] (may be empty = all)
    rows: list = field(default_factory=list)   # list[list[Literal]]
    query: Optional[Select] = None
    mode: str = "insert"              # insert | upsert | replace


@dataclass
class Delete:
    table: str
    where: Optional[Expr] = None


@dataclass
class Update:
    table: str
    assignments: list = field(default_factory=list)  # list[(col, Expr)]
    where: Optional[Expr] = None


@dataclass
class CreateMaterializedView:
    """CREATE MATERIALIZED VIEW v AS SELECT ... — registers a continuous
    query maintained from the source table's changefeed (ydb_tpu/views/,
    the reference's change-exchange + continuous-query surface)."""
    name: str
    query: "Select"
    sql: str = ""                  # defining SELECT text (restart recompile)


@dataclass
class DropMaterializedView:
    name: str
    if_exists: bool = False


@dataclass(frozen=True)
class Explain:
    query: "Select"
    analyze: bool = False
    sql: str = ""                  # inner statement text (re-run by ANALYZE)


@dataclass(frozen=True)
class Begin:
    pass


@dataclass(frozen=True)
class Commit:
    pass


@dataclass(frozen=True)
class Rollback:
    pass


Statement = Union[Select, CreateTable, DropTable, Insert, Delete, Update,
                  CreateMaterializedView, DropMaterializedView,
                  Explain, Begin, Commit, Rollback]
