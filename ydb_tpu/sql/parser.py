"""Recursive-descent SQL parser.

The analog of `NSQLTranslation::SqlToYql` (`ydb/library/yql/sql/sql.h:18`):
text → AST. Grammar is the YQL-SQL subset the benchmark workloads need
(TPC-H/TPC-DS/ClickBench SELECT shapes, plus DDL/DML for the write path).
"""

from __future__ import annotations

from typing import Optional

from ydb_tpu.sql import ast
from ydb_tpu.sql.lexer import SqlError, Token, tokenize


class Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = tokenize(text)
        self.i = 0

    # -- token helpers -----------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in words

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def accept_kw(self, *words: str) -> Optional[str]:
        if self.at_kw(*words):
            return self.next().value
        return None

    def accept_op(self, *ops: str) -> Optional[str]:
        if self.at_op(*ops):
            return self.next().value
        return None

    def expect_kw(self, word: str) -> None:
        if not self.accept_kw(word):
            raise SqlError(f"expected {word.upper()}, got {self.peek().value!r} "
                           f"at {self.peek().pos}")

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SqlError(f"expected {op!r}, got {self.peek().value!r} "
                           f"at {self.peek().pos}")

    # keywords that stay usable as identifiers/column names (the window-
    # frame words especially: schemas with a `rows` or `current` column
    # predate their reservation)
    _SOFT = ("date", "key", "first", "last", "store", "set", "values",
             "rows", "row", "current", "unbounded", "preceding",
             "following")

    def ident(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            return self.next().value
        # allow non-reserved keywords as identifiers in safe spots
        if t.kind == "kw" and t.value in self._SOFT:
            return self.next().value
        raise SqlError(f"expected identifier, got {t.value!r} at {t.pos}")

    # -- statements --------------------------------------------------------

    def parse_statement(self) -> ast.Statement:
        if self.at_kw("with") or self.at_kw("select"):
            stmt = self.parse_select()
        elif self.at_kw("create"):
            nxt = self.peek(1).value.lower()
            if nxt == "index":
                stmt = self.parse_create_index()
            elif nxt == "materialized":
                stmt = self.parse_create_matview()
            else:
                stmt = self.parse_create_table()
        elif self.at_kw("drop"):
            nxt = self.peek(1).value.lower()
            if nxt == "index":
                stmt = self.parse_drop_index()
            elif nxt == "materialized":
                stmt = self.parse_drop_matview()
            else:
                stmt = self.parse_drop_table()
        elif self.at_kw("alter"):
            stmt = self.parse_alter_table()
        elif self.at_kw("insert", "upsert", "replace"):
            stmt = self.parse_insert()
        elif self.at_kw("delete"):
            stmt = self.parse_delete()
        elif self.at_kw("update"):
            stmt = self.parse_update()
        elif self.accept_kw("explain"):
            analyze = bool(self.accept_kw("analyze"))
            inner_sql = self.text[self.peek().pos:]
            stmt = ast.Explain(self.parse_select(), analyze, inner_sql)
        elif self.accept_kw("begin"):
            self.accept_kw("transaction")
            stmt = ast.Begin()
        elif self.accept_kw("commit"):
            stmt = ast.Commit()
        elif self.accept_kw("rollback"):
            stmt = ast.Rollback()
        else:
            raise SqlError(f"unexpected {self.peek().value!r} at "
                           f"{self.peek().pos}")
        self.accept_op(";")
        if self.peek().kind != "eof":
            raise SqlError(f"trailing input at {self.peek().pos}")
        return stmt

    def parse_select(self):
        """[WITH ...] select possibly chained with UNION [ALL]; the CTEs
        are visible to every arm and the trailing ORDER BY/LIMIT of a
        chain bind to the whole set result."""
        ctes = []
        if self.accept_kw("with"):
            while True:
                name = self.ident()
                self.expect_kw("as")
                self.expect_op("(")
                ctes.append((name, self.parse_select()))
                self.expect_op(")")
                if not self.accept_op(","):
                    break
        node = self._parse_intersect_chain()
        while self.at_kw("union") or self.at_kw("except"):
            kw = self.peek().value
            self.next()
            has_all = self.accept_kw("all")
            op = f"{kw}_all" if has_all else kw
            right = self._parse_intersect_chain()
            node = ast.SetOp(op, node, right)
        if isinstance(node, ast.SetOp):
            # the last arm grabbed the chain's trailing ORDER BY/LIMIT
            # (the rightmost SELECT — the right child may itself be an
            # intersect chain)
            last = node.right
            while isinstance(last, ast.SetOp):
                last = last.right
            node.order_by, node.limit, node.offset = \
                last.order_by, last.limit, last.offset
            last.order_by, last.limit, last.offset = [], None, None
            node.ctes = ctes
        else:
            node.ctes = ctes
        return node

    def _parse_intersect_chain(self):
        """INTERSECT binds tighter than UNION/EXCEPT (SQL precedence)."""
        node = self.parse_select_core()
        while self.at_kw("intersect"):
            self.next()
            op = "intersect_all" if self.accept_kw("all") else "intersect"
            node = ast.SetOp(op, node, self.parse_select_core())
        return node

    def parse_select_core(self) -> ast.Select:
        self.expect_kw("select")
        sel = ast.Select()
        if self.accept_kw("distinct"):
            sel.distinct = True
        sel.items = [self.select_item()]
        while self.accept_op(","):
            sel.items.append(self.select_item())
        if self.accept_kw("from"):
            sel.relation = self.relation()
        if self.accept_kw("where"):
            sel.where = self.expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            sel.group_by = [self.expr()]
            while self.accept_op(","):
                sel.group_by.append(self.expr())
        if self.accept_kw("having"):
            sel.having = self.expr()
        if self.accept_kw("order"):
            self.expect_kw("by")
            sel.order_by = [self.order_item()]
            while self.accept_op(","):
                sel.order_by.append(self.order_item())
        if self.accept_kw("limit"):
            sel.limit = int(self.number_token())
            if self.accept_kw("offset"):
                sel.offset = int(self.number_token())
        return sel

    def number_token(self) -> str:
        t = self.peek()
        # a digit STRING is accepted where a count is required (LIMIT/
        # OFFSET): PG text-protocol clients bind every parameter as text,
        # and pgwire inlines unspecified-type params as string literals
        if t.kind == "string" and t.value.strip().isdigit():
            return self.next().value.strip()
        if t.kind != "number":
            raise SqlError(f"expected number at {t.pos}")
        return self.next().value

    def select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.next()
            return ast.SelectItem(ast.Star())
        e = self.expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident":
            alias = self.next().value
        return ast.SelectItem(e, alias)

    def order_item(self) -> ast.OrderItem:
        e = self.expr()
        asc = True
        if self.accept_kw("desc"):
            asc = False
        else:
            self.accept_kw("asc")
        nulls_first = None
        if self.accept_kw("nulls"):
            w = self.accept_kw("first", "last")
            nulls_first = (w == "first")
        return ast.OrderItem(e, asc, nulls_first)

    # -- relations ---------------------------------------------------------

    def relation(self) -> ast.Relation:
        rel = self.join_chain()
        while self.accept_op(","):          # comma join = cross join
            right = self.join_chain()
            rel = ast.Join("cross", rel, right)
        return rel

    def join_chain(self) -> ast.Relation:
        rel = self.table_factor()
        while True:
            kind = None
            if self.accept_kw("cross"):
                self.expect_kw("join")
                rel = ast.Join("cross", rel, self.table_factor())
                continue
            if self.accept_kw("inner"):
                kind = "inner"
            elif self.accept_kw("left"):
                self.accept_kw("outer")
                kind = "left"
            elif self.accept_kw("right"):
                self.accept_kw("outer")
                kind = "right"
            elif self.accept_kw("full"):
                self.accept_kw("outer")
                kind = "full"
            elif self.at_kw("join"):
                kind = "inner"
            if kind is None:
                return rel
            self.expect_kw("join")
            right = self.table_factor()
            on = None
            if self.accept_kw("on"):
                on = self.expr()
            rel = ast.Join(kind, rel, right, on)

    def table_factor(self) -> ast.Relation:
        if self.accept_op("("):
            q = self.parse_select()
            self.expect_op(")")
            self.accept_kw("as")
            alias = self.ident()
            return ast.SubqueryRef(q, alias)
        name = self.ident()
        while self.accept_op("."):           # schema-qualified: keep last part
            name = self.ident()
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident":
            alias = self.next().value
        return ast.TableRef(name, alias)

    # -- expressions (precedence climbing) ---------------------------------

    def expr(self) -> ast.Expr:
        return self.or_expr()

    def or_expr(self) -> ast.Expr:
        e = self.and_expr()
        while self.accept_kw("or"):
            e = ast.BinOp("or", e, self.and_expr())
        return e

    def and_expr(self) -> ast.Expr:
        e = self.not_expr()
        while self.accept_kw("and"):
            e = ast.BinOp("and", e, self.not_expr())
        return e

    def not_expr(self) -> ast.Expr:
        if self.accept_kw("not"):
            return ast.UnaryOp("not", self.not_expr())
        return self.predicate()

    def predicate(self) -> ast.Expr:
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            q = self.parse_select()
            self.expect_op(")")
            return ast.Exists(q)
        e = self.comparison()
        while True:
            negated = False
            if self.at_kw("not") and self.peek(1).kind == "kw" and \
                    self.peek(1).value in ("in", "like", "between"):
                self.next()
                negated = True
            if self.accept_kw("between"):
                lo = self.comparison()
                self.expect_kw("and")
                hi = self.comparison()
                e = ast.Between(e, lo, hi, negated)
            elif self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select"):
                    q = self.parse_select()
                    self.expect_op(")")
                    e = ast.InSubquery(e, q, negated)
                else:
                    items = [self.expr()]
                    while self.accept_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    e = ast.InList(e, tuple(items), negated)
            elif self.accept_kw("like"):
                pat = self.peek()
                if pat.kind != "string":
                    raise SqlError(f"LIKE needs a string literal at {pat.pos}")
                self.next()
                if self.accept_kw("escape"):
                    self.next()  # ignore custom escapes (unused by benchmarks)
                e = ast.Like(e, pat.value, negated)
            elif self.accept_kw("is"):
                neg = bool(self.accept_kw("not"))
                self.expect_kw("null")
                e = ast.IsNull(e, neg)
            else:
                return e

    _CMP = {"=": "=", "<>": "<>", "!=": "<>", "<": "<", "<=": "<=",
            ">": ">", ">=": ">="}

    def comparison(self) -> ast.Expr:
        e = self.additive()
        t = self.peek()
        if t.kind == "op" and t.value in self._CMP:
            self.next()
            right = self.additive()
            return ast.BinOp(self._CMP[t.value], e, right)
        return e

    def additive(self) -> ast.Expr:
        e = self.multiplicative()
        while True:
            if self.accept_op("+"):
                e = ast.BinOp("+", e, self.multiplicative())
            elif self.accept_op("-"):
                e = ast.BinOp("-", e, self.multiplicative())
            elif self.accept_op("||"):
                e = ast.BinOp("||", e, self.multiplicative())
            else:
                return e

    def multiplicative(self) -> ast.Expr:
        e = self.unary()
        while True:
            if self.accept_op("*"):
                e = ast.BinOp("*", e, self.unary())
            elif self.accept_op("/"):
                e = ast.BinOp("/", e, self.unary())
            elif self.accept_op("%"):
                e = ast.BinOp("%", e, self.unary())
            else:
                return e

    def unary(self) -> ast.Expr:
        if self.accept_op("-"):
            return ast.UnaryOp("-", self.unary())
        self.accept_op("+")
        return self.primary()

    def primary(self) -> ast.Expr:
        t = self.peek()
        if t.kind == "number":
            self.next()
            v = t.value
            if "." in v or "e" in v or "E" in v:
                return ast.Literal(float(v))
            return ast.Literal(int(v))
        if t.kind == "string":
            self.next()
            return ast.Literal(t.value)
        if t.kind == "kw":
            if t.value in ("true", "false"):
                self.next()
                return ast.Literal(t.value == "true")
            if t.value == "null":
                self.next()
                return ast.Literal(None)
            if t.value == "date":
                nxt = self.peek(1)
                if nxt.kind == "string":
                    self.next()
                    self.next()
                    return ast.Literal(nxt.value, "date")
                if nxt.kind == "op" and nxt.value == "(":
                    self.next()
                    self.next()
                    arg = self.expr()
                    self.expect_op(")")
                    return ast.Cast(arg, "date")
            if t.value == "interval":
                self.next()
                lit = self.peek()
                if lit.kind != "string" and lit.kind != "number":
                    raise SqlError(f"INTERVAL needs a quantity at {lit.pos}")
                self.next()
                unit = self.ident().lower()
                return ast.Literal(int(lit.value), f"interval_{unit}")
            if t.value == "case":
                return self.case_expr()
            if t.value == "cast":
                self.next()
                self.expect_op("(")
                arg = self.expr()
                self.expect_kw("as")
                ty = self.type_name()
                self.expect_op(")")
                return ast.Cast(arg, ty)
            if t.value == "substring":
                self.next()
                self.expect_op("(")
                arg = self.expr()
                if self.accept_kw("from"):
                    start = self.expr()
                    length = None
                    if self.accept_kw("for"):
                        length = self.expr()
                else:
                    self.expect_op(",")
                    start = self.expr()
                    length = None
                    if self.accept_op(","):
                        length = self.expr()
                self.expect_op(")")
                args = (arg, start) if length is None else (arg, start, length)
                return ast.FuncCall("substring", args)
            if t.value == "extract":
                self.next()
                self.expect_op("(")
                field = self.ident().lower()
                self.expect_kw("from")
                arg = self.expr()
                self.expect_op(")")
                return ast.FuncCall(field, (arg,))
            if t.value in ("if", "replace") \
                    and self.peek(1).kind == "op" \
                    and self.peek(1).value == "(":
                # keywords that double as function names
                name = self.next().value
                self.expect_op("(")
                args = [self.expr()]
                while self.accept_op(","):
                    args.append(self.expr())
                self.expect_op(")")
                return ast.FuncCall(name, tuple(args))
        if t.kind == "ident" or (t.kind == "kw"
                                 and t.value in self._SOFT):
            nxt = self.peek(1)
            if nxt.kind == "op" and nxt.value == "(":
                return self.func_call()
            return self.name_ref()
        if self.accept_op("("):
            if self.at_kw("select"):
                q = self.parse_select()
                self.expect_op(")")
                return ast.ScalarSubquery(q)
            e = self.expr()
            self.expect_op(")")
            return e
        raise SqlError(f"unexpected {t.value!r} at {t.pos}")

    def name_ref(self) -> ast.Expr:
        parts = [self.ident()]
        while self.at_op("."):
            nxt = self.peek(1)
            if nxt.kind == "op" and nxt.value == "*":   # t.*
                self.next()
                self.next()
                return ast.Star(parts[0])
            if nxt.kind not in ("ident", "kw"):
                break
            self.next()
            parts.append(self.ident())
        return ast.Name(tuple(parts))

    def func_call(self) -> ast.Expr:
        name = self.ident().lower()
        self.expect_op("(")
        if self.accept_op("*"):
            self.expect_op(")")
            return self._maybe_over(ast.FuncCall(name, (), star=True))
        distinct = bool(self.accept_kw("distinct"))
        if self.at_op(")"):
            self.next()
            return self._maybe_over(ast.FuncCall(name, ()))
        args = [self.expr()]
        while self.accept_op(","):
            args.append(self.expr())
        self.expect_op(")")
        call = ast.FuncCall(name, tuple(args), distinct=distinct)
        return self._maybe_over(call)

    def _maybe_over(self, call: ast.FuncCall) -> ast.Expr:
        """`fn(...) OVER (PARTITION BY ... ORDER BY ...)`."""
        if not self.at_kw("over"):
            return call
        self.next()
        self.expect_op("(")
        partition: list = []
        order: list = []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition.append(self.expr())
            while self.accept_op(","):
                partition.append(self.expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            order.append(self.order_item())
            while self.accept_op(","):
                order.append(self.order_item())
        frame = None
        if self.at_kw("rows"):
            self.next()
            self.expect_kw("between")
            lo = self._frame_bound()
            self.expect_kw("and")
            hi = self._frame_bound()
            frame = ("rows", lo, hi)
        self.expect_op(")")
        return ast.WindowFunc(call.name, call.args, tuple(partition),
                              tuple(order), call.distinct, frame)

    def _frame_bound(self):
        """UNBOUNDED PRECEDING/FOLLOWING | N PRECEDING/FOLLOWING |
        CURRENT ROW → signed offset (None = unbounded that direction)."""
        if self.accept_kw("unbounded"):
            if self.accept_kw("preceding"):
                return ("unbounded", -1)
            self.expect_kw("following")
            return ("unbounded", 1)
        if self.accept_kw("current"):
            self.expect_kw("row")
            return 0
        tok = self.next()
        if tok.kind != "number":
            raise SqlError(f"expected frame bound at {tok.pos}")
        n = int(tok.value)
        if self.accept_kw("preceding"):
            return -n
        self.expect_kw("following")
        return n

    def case_expr(self) -> ast.Expr:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expr()
        whens = []
        while self.accept_kw("when"):
            cond = self.expr()
            self.expect_kw("then")
            res = self.expr()
            whens.append((cond, res))
        default = None
        if self.accept_kw("else"):
            default = self.expr()
        self.expect_kw("end")
        return ast.Case(operand, tuple(whens), default)

    def type_name(self) -> str:
        t = self.peek()
        if t.kind in ("ident", "kw"):
            self.next()
            name = t.value.lower()
            if self.accept_op("("):   # decimal(12,2) etc. — ignore params
                while not self.at_op(")"):
                    self.next()
                self.expect_op(")")
            return name
        raise SqlError(f"expected type name at {t.pos}")

    # -- DDL / DML ---------------------------------------------------------

    def parse_create_table(self) -> ast.CreateTable:
        self.expect_kw("create")
        self.expect_kw("table")
        if_not_exists = False
        if self.accept_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            if_not_exists = True
        name = self.ident()
        self.expect_op("(")
        columns: list = []
        pk: list[str] = []
        while True:
            if self.accept_kw("primary"):
                self.expect_kw("key")
                self.expect_op("(")
                pk.append(self.ident())
                while self.accept_op(","):
                    pk.append(self.ident())
                self.expect_op(")")
            else:
                cname = self.ident()
                ctype = self.type_name()
                not_null = False
                if self.accept_kw("not"):
                    self.expect_kw("null")
                    not_null = True
                elif self.accept_kw("null"):
                    pass
                columns.append((cname, ctype, not_null))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        partitions = 1
        store = "column"
        # WITH (STORE = COLUMN, PARTITION_COUNT = n, TTL_COLUMN = c,
        # TTL_DAYS = n) — YQL-flavored options
        ttl_column, ttl_days = "", 0
        if self.accept_kw("with"):
            self.expect_op("(")
            while True:
                opt = self.ident().lower()
                self.expect_op("=")
                val = self.next().value
                if opt in ("partition_count", "auto_partitioning_min_partitions_count"):
                    partitions = int(val)
                elif opt == "store":
                    store = str(val).lower()
                elif opt == "ttl_column":
                    ttl_column = str(val)
                elif opt == "ttl_days":
                    ttl_days = int(val)
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return ast.CreateTable(name, columns, pk, partitions, store,
                               ttl_column=ttl_column, ttl_days=ttl_days,
                               if_not_exists=if_not_exists)

    def parse_drop_table(self) -> ast.DropTable:
        self.expect_kw("drop")
        self.expect_kw("table")
        if_exists = False
        if self.accept_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        return ast.DropTable(self.ident(), if_exists)

    def parse_create_index(self) -> ast.CreateIndex:
        self.expect_kw("create")
        self.next()                       # "index" (contextual ident)
        iname = self.ident()
        self.expect_kw("on")
        table = self.ident()
        self.expect_op("(")
        col = self.ident()
        self.expect_op(")")
        return ast.CreateIndex(iname, table, col)

    def parse_drop_index(self) -> ast.DropIndex:
        self.expect_kw("drop")
        self.next()                       # "index"
        iname = self.ident()
        self.expect_kw("on")
        return ast.DropIndex(iname, self.ident())

    def parse_create_matview(self) -> ast.CreateMaterializedView:
        self.expect_kw("create")
        self.next()                       # "materialized" (contextual)
        if self.next().value.lower() != "view":
            raise SqlError("expected VIEW after MATERIALIZED")
        name = self.ident()
        self.expect_kw("as")
        # capture the defining SELECT verbatim: the view registry persists
        # it and recompiles the fold programs from it at restart
        sql = self.text[self.peek().pos:].rstrip().rstrip(";").rstrip()
        return ast.CreateMaterializedView(name, self.parse_select(), sql)

    def parse_drop_matview(self) -> ast.DropMaterializedView:
        self.expect_kw("drop")
        self.next()                       # "materialized"
        if self.next().value.lower() != "view":
            raise SqlError("expected VIEW after MATERIALIZED")
        if_exists = False
        if self.accept_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        return ast.DropMaterializedView(self.ident(), if_exists)

    def parse_alter_table(self) -> ast.AlterTable:
        self.expect_kw("alter")
        self.expect_kw("table")
        name = self.ident()
        word = self.next().value.lower()   # add (ident) | drop (keyword)
        if word == "add":
            if self.peek().value.lower() == "column":
                self.next()
            col = self.ident()
            ty = self.type_name()
            not_null = False
            if self.accept_kw("not"):
                self.expect_kw("null")
                not_null = True
            return ast.AlterTable(name, "add", col, ty, not_null)
        if word == "drop":
            if self.peek().value.lower() == "column":
                self.next()
            return ast.AlterTable(name, "drop", self.ident())
        raise SqlError(f"ALTER TABLE supports ADD/DROP COLUMN, got "
                       f"{word!r}")

    def parse_insert(self) -> ast.Insert:
        mode = self.next().value   # insert | upsert | replace
        self.expect_kw("into")
        name = self.ident()
        columns: list[str] = []
        if self.accept_op("("):
            columns.append(self.ident())
            while self.accept_op(","):
                columns.append(self.ident())
            self.expect_op(")")
        if self.at_kw("select"):
            return ast.Insert(name, columns, [], self.parse_select(), mode)
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_op("(")
            row = [self.expr()]
            while self.accept_op(","):
                row.append(self.expr())
            self.expect_op(")")
            rows.append(row)
            if not self.accept_op(","):
                break
        return ast.Insert(name, columns, rows, None, mode)

    def parse_delete(self) -> ast.Delete:
        self.expect_kw("delete")
        self.expect_kw("from")
        name = self.ident()
        where = self.expr() if self.accept_kw("where") else None
        return ast.Delete(name, where)

    def parse_update(self) -> ast.Update:
        self.expect_kw("update")
        name = self.ident()
        self.expect_kw("set")
        assignments = []
        while True:
            col = self.ident()
            self.expect_op("=")
            assignments.append((col, self.expr()))
            if not self.accept_op(","):
                break
        where = self.expr() if self.accept_kw("where") else None
        return ast.Update(name, assignments, where)


def parse(text: str) -> ast.Statement:
    return Parser(text).parse_statement()
