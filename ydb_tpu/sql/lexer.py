"""SQL lexer (hand-rolled; analog of the generated ANTLR lexer for
`ydb/library/yql/sql/v1/SQLv1.g.in`)."""

from __future__ import annotations

from dataclasses import dataclass


class SqlError(Exception):
    pass


@dataclass(frozen=True)
class Token:
    kind: str          # kw | ident | number | string | op | eof
    value: str
    pos: int


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "is", "null", "like", "between",
    "case", "when", "then", "else", "end", "cast", "exists", "distinct",
    "join", "inner", "left", "right", "full", "outer", "cross", "on", "asc",
    "desc", "nulls", "first", "last", "date", "interval", "true", "false",
    "create", "table", "primary", "key", "drop", "insert", "upsert",
    "replace", "into", "values", "delete", "update", "set", "if", "with",
    "union", "all", "escape", "substring", "for", "partition", "store",
    "extract", "begin", "commit", "rollback", "transaction", "explain",
    "analyze", "over", "alter", "intersect", "except",
    "rows", "unbounded", "preceding", "following", "current", "row",
}

_OPS = ["<>", "!=", ">=", "<=", "||", "(", ")", ",", "+", "-", "*", "/", "%",
        "=", "<", ">", ".", ";"]


def tokenize(text: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and text[i + 1] == "-":   # line comment
            j = text.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "*":   # block comment
            j = text.find("*/", i + 2)
            if j < 0:
                raise SqlError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n and (text[j].isdigit() or text[j] in ".eE+-"):
                if text[j] == ".":
                    if seen_dot:
                        break
                    seen_dot = True
                elif text[j] in "eE":
                    if seen_exp or j + 1 >= n or not (
                            text[j + 1].isdigit() or text[j + 1] in "+-"):
                        break
                    seen_exp = True
                elif text[j] in "+-" and text[j - 1] not in "eE":
                    break
                j += 1
            toks.append(Token("number", text[i:j], i))
            i = j
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if text[j] == "'":
                    if j + 1 < n and text[j + 1] == "'":   # '' escape
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(text[j])
                j += 1
            if j >= n:
                raise SqlError(f"unterminated string at {i}")
            toks.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == "`" or c == '"':   # quoted identifier
            j = text.find(c, i + 1)
            if j < 0:
                raise SqlError(f"unterminated identifier at {i}")
            toks.append(Token("ident", text[i + 1:j], i))
            i = j + 1
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            word = text[i:j]
            lw = word.lower()
            toks.append(Token("kw" if lw in KEYWORDS else "ident",
                              lw if lw in KEYWORDS else word, i))
            i = j
            continue
        for op in _OPS:
            if text.startswith(op, i):
                toks.append(Token("op", op, i))
                i += len(op)
                break
        else:
            raise SqlError(f"unexpected character {c!r} at {i}")
    toks.append(Token("eof", "", n))
    return toks
