from ydb_tpu.sql.parser import parse  # noqa: F401
