"""Name-based call graph + env-lever index over a Project.

Precision notes (this is a linter, not a compiler): calls resolve by
bare name across the whole package — `F.fused_cache_key(...)` resolves
to any def named `fused_cache_key`. That over-approximates, which is
the right failure mode for reachability of ENV LEVERS (a false
"reachable" produces a finding someone reviews and pragmas; a false
"unreachable" would hide a stale-cache bug). Generic method names that
would wire everything to everything (`get`, `run`, `put`, ...) are
stop-listed; instantiating a class pulls in `__init__`/`__post_init__`
plus its `_build*` methods — the compile-builder convention used by
`DistributedAgg`/`ShuffleJoin` — without dragging in every method."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

LEVER_PREFIX = "YDB_TPU_"

# names too generic to follow across modules
_STOP = frozenset({
    "get", "set", "put", "run", "add", "pop", "inc", "len", "str", "int",
    "float", "bool", "list", "dict", "tuple", "sorted", "close", "open",
    "items", "keys", "values", "append", "update", "join", "split",
    "query", "execute", "render", "snapshot", "observe", "max", "min",
    "range", "zip", "next", "iter", "repr", "type", "print", "format",
})


@dataclass
class FuncInfo:
    name: str                       # bare name
    qual: str                       # Module-relative qualname
    path: str                       # module path
    node: ast.AST = None
    levers: set = field(default_factory=set)    # direct YDB_TPU_* reads
    calls: set = field(default_factory=set)     # bare names called
    jits: bool = False              # contains a jit/shard_map call


def _const_str(node):
    return node.value if isinstance(node, ast.Constant) \
        and isinstance(node.value, str) else None


def lever_reads(node: ast.AST) -> set:
    """YDB_TPU_* names read under `node`: os.environ.get /
    os.environ[...] / os.getenv, plus any lever-name literal passed as
    a call argument (the `_int("YDB_TPU_X", default)` helper idiom).
    Docstrings and bare string statements are NOT reads."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            for a in list(n.args) + [kw.value for kw in n.keywords]:
                name = _const_str(a)
                if name and name.startswith(LEVER_PREFIX):
                    out.add(name)
        elif isinstance(n, ast.Subscript):
            name = _const_str(n.slice)
            if name and name.startswith(LEVER_PREFIX):
                out.add(name)
        elif isinstance(n, ast.Compare):
            for side in [n.left] + list(n.comparators):
                name = _const_str(side)
                if name and name.startswith(LEVER_PREFIX):
                    out.add(name)
    return out


def call_names(node: ast.AST) -> set:
    """Bare names of everything called under `node` (Name calls and
    Attribute-call basenames)."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute):
                out.add(f.attr)
    return out


class CallGraph:
    def __init__(self, project):
        self.funcs: dict[str, list[FuncInfo]] = {}     # bare name -> defs
        self.by_qual: dict[str, FuncInfo] = {}
        # class name -> its OWN method FuncInfos (not globally resolved)
        self.class_methods: dict[str, list[FuncInfo]] = {}

        for mod in project.modules.values():
            self._index(mod)

    def _index(self, mod) -> None:
        def visit(node, prefix, cls_name=None):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{mod.path}::{prefix}{child.name}"
                    # calls to our OWN nested helpers resolve here, not
                    # globally (their bodies are already in this walk);
                    # keeping the bare names would alias every nested
                    # `wrapper`/`per_device` in the package together
                    nested = {d.name for d in ast.walk(child)
                              if isinstance(d, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))
                              and d is not child}
                    fi = FuncInfo(name=child.name, qual=qual,
                                  path=mod.path, node=child,
                                  levers=lever_reads(child),
                                  calls=call_names(child) - nested)
                    fi.jits = bool({"jit", "pjit", "shard_map"}
                                   & fi.calls)
                    self.funcs.setdefault(child.name, []).append(fi)
                    self.by_qual[qual] = fi
                    if cls_name is not None:
                        self.class_methods.setdefault(cls_name, []) \
                            .append(fi)
                    visit(child, prefix + child.name + ".", None)
                elif isinstance(child, ast.ClassDef):
                    visit(child, prefix + child.name + ".", child.name)
                else:
                    visit(child, prefix, cls_name)

        visit(mod.tree, "")

    def _expand(self, name: str) -> list:
        """Defs a bare called name may resolve to: its functions, plus —
        when the name is a known class — the class's builder methods."""
        out = list(self.funcs.get(name, ()))
        for fi in self.class_methods.get(name, ()):
            if fi.name in ("__init__", "__post_init__") \
                    or fi.name.startswith("_build"):
                out.append(fi)
        return out

    def reachable_levers(self, names, _depth: int = 12) -> set:
        """Transitive YDB_TPU_* reads from a set of called bare names."""
        seen: set = set()
        levers: set = set()
        frontier = [n for n in names if n not in _STOP]
        for _ in range(_depth):
            nxt = []
            for name in frontier:
                if name in seen or name in _STOP:
                    continue
                seen.add(name)
                for fi in self._expand(name):
                    levers |= fi.levers
                    nxt.extend(c for c in fi.calls
                               if c not in seen and c not in _STOP)
            if not nxt:
                break
            frontier = nxt
        return levers

    def reaches(self, names, target: str) -> bool:
        """Does any call path from `names` reach a def named `target`?"""
        seen: set = set()
        frontier = [n for n in names if n not in _STOP]
        while frontier:
            name = frontier.pop()
            if name in seen or name in _STOP:
                continue
            seen.add(name)
            if name == target:
                return True
            for fi in self._expand(name):
                frontier.extend(c for c in fi.calls if c not in seen)
        return target in names
