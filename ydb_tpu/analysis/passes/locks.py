"""locks — `# guarded-by:` discipline for shared mutable state.

Shared attributes in the concurrent subsystems (`hive/`, `dq/`,
`cluster/`, `query/`, plus anything else that opts in) declare their
owning lock on the line that initializes them:

    self._nodes: dict = {}        # guarded-by: _mu

Every MUTATION of a guarded attribute anywhere in the class must then
sit inside `with self.<lock>:` (any `with` whose items include the
lock), or inside a method whose name ends in `_locked` (the repo's
"caller already holds it" convention). Conversely a call to a
`*_locked` method must itself happen under a `with`. Reads are not
checked — the sampled-read idiom (snapshot under lock, render outside)
is deliberate here.

Mutations recognized: assignment / augmented assignment to the
attribute or a subscript of it, `del`, and calls of known mutating
container methods (`append`, `pop`, `update`, `add`, ...). `__init__`/
`__post_init__` are exempt (pre-publication, no concurrent observer).
"""

from __future__ import annotations

import ast
import re

from ydb_tpu.analysis.core import Finding, Pass

_GUARD_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "update", "setdefault", "add", "discard",
    "difference_update", "intersection_update", "popitem",
    "move_to_end",
})
_EXEMPT_METHODS = ("__init__", "__post_init__")


def _self_attr(node):
    """'attr' when node is `self.attr`, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class LockDisciplinePass(Pass):
    id = "locks"
    title = "guarded-by annotated state mutated outside its lock"

    def check(self, project) -> list:
        out = []
        for mod in project.modules.values():
            for n in mod.tree.body:
                if isinstance(n, ast.ClassDef):
                    out.extend(self._check_class(mod, n))
        return out

    def _check_class(self, mod, cls):
        guards = self._guards(mod, cls)
        if not guards:
            return []
        out = []
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            exempt = meth.name in _EXEMPT_METHODS
            holds_by_suffix = meth.name.endswith("_locked")
            for node, attr, what in self._mutations(meth, guards):
                if exempt:
                    continue
                lock = guards[attr]
                if holds_by_suffix or self._under_lock(meth, node, lock):
                    continue
                scope = f"{cls.name}.{meth.name}"
                out.append(Finding(
                    self.id, mod.path, node.lineno,
                    key=f"{mod.path}::{scope}::{attr}::{what}",
                    message=f"`self.{attr}` ({what}) is guarded-by "
                            f"`{lock}` but mutated outside `with "
                            f"self.{lock}:` in {scope}"))
            # *_locked callees must be invoked under SOME declared lock
            if not (exempt or holds_by_suffix):
                for node in ast.walk(meth):
                    if isinstance(node, ast.Call):
                        callee = _self_attr(node.func)
                        if callee and callee.endswith("_locked") \
                                and not any(
                                    self._under_lock(meth, node, lk)
                                    for lk in set(guards.values())):
                            scope = f"{cls.name}.{meth.name}"
                            out.append(Finding(
                                self.id, mod.path, node.lineno,
                                key=f"{mod.path}::{scope}::{callee}::call",
                                message=f"`self.{callee}()` requires the "
                                        f"caller to hold a lock (the "
                                        f"_locked convention) but {scope} "
                                        f"calls it outside any `with`"))
        return out

    def _guards(self, mod, cls) -> dict:
        """attr -> lock name from `# guarded-by:` trailing comments on
        `self.<attr> = ...` lines anywhere in the class body."""
        guards: dict[str, str] = {}
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                m = _GUARD_RE.search(mod.comments.get(node.lineno, ""))
                if not m:
                    continue
                for t in targets:
                    attr = _self_attr(t)
                    if attr:
                        guards[attr] = m.group(1)
        return guards

    def _mutations(self, meth, guards):
        """Yield (node, attr, what) for mutations of guarded attrs."""
        for node in ast.walk(meth):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr in guards:
                        yield node, attr, "assign"
                    elif isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr in guards:
                            yield node, attr, "setitem"
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = _self_attr(t) or (
                        _self_attr(t.value)
                        if isinstance(t, ast.Subscript) else None)
                    if attr in guards:
                        yield node, attr, "del"
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                attr = _self_attr(node.func.value)
                if attr in guards:
                    yield node, attr, node.func.attr

    @staticmethod
    def _under_lock(meth, node, lock) -> bool:
        """Is `node` lexically inside `with self.<lock>:` within meth?"""
        for w in ast.walk(meth):
            if isinstance(w, ast.With) \
                    and w.lineno <= node.lineno <= w.end_lineno:
                for item in w.items:
                    if _self_attr(item.context_expr) == lock:
                        return True
        return False
