"""cache-key — completeness of compiled-program cache keys against the
`YDB_TPU_*` levers that can shape a traced program.

The tuning-tuple rule (PR 5/6): any environment lever a traced/compiled
program's builder reads must be a component of the cache key that
decides whether an already-compiled program is reused — otherwise
flipping the lever serves a program traced under the OLD setting: a
silent stale-cache wrong answer (or a lying A/B gate).

Mechanics:

  * A *tuning provider* is a function marked `# lint: tuning-provider`
    on its def line (e.g. `groupby_tuning`, `quant_enabled`). Its
    direct lever reads are the levers it covers.
  * A *cache site* is `<obj>.get(<keyvar>)` where the receiver's name
    looks like a compiled-program cache (`cache`, `_fns`, `_FNS`,
    `_aggs`, `_joins`) and `keyvar` is a local name.
  * The site's *builder closure* = every function transitively callable
    from the `if <entry> is None:` suite that fills the cache (class
    instantiation pulls in `__init__`/`__post_init__`/`_build*` —
    the compile-builder convention), plus levers read directly in the
    enclosing function. Builders that never reach a `jit`/`shard_map`
    are not program caches — skipped.
  * The *key closure* = calls inside every assignment to `keyvar` in
    the enclosing function, chased one hop through local names (so
    `base_key = fused_cache_key(...); key = ("batched", base_key, …)`
    still sees the providers `fused_cache_key` calls).

A lever reachable from the builder but covered by no provider in the
key closure is a finding. Levers read at module import time are exempt:
they are process constants and cannot flip between queries.

Known precision limit: coverage asks whether the key closure CALLS a
provider (directly or transitively, e.g. through `fused_cache_key`),
not whether the provider's VALUE flows into the key — a helper in the
key expression that calls a provider and drops its result would
wrongly count as coverage. Return-value dataflow is out of scope for
an AST pass; key-building helpers must include what they consult (the
`*cache_key*` functions here all do, pinned by the regression tests).
"""

from __future__ import annotations

import ast
import re

from ydb_tpu.analysis.core import Finding, Pass
from ydb_tpu.analysis.callgraph import CallGraph, call_names, lever_reads

_CACHE_NAME = re.compile(r"(cache|_fns|_FNS|_aggs|_joins)", re.IGNORECASE)


def _recv_name(func: ast.Attribute) -> str:
    v = func.value
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Attribute):
        return v.attr
    return ""


def _enclosing_function(mod, node):
    best = None
    for n in ast.walk(mod.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n.lineno <= node.lineno <= n.end_lineno:
            if best is None or n.lineno > best.lineno:
                best = n
    return best


class CacheKeyPass(Pass):
    id = "cache-key"
    title = "YDB_TPU_* levers missing from compiled-program cache keys"

    def _providers(self, project) -> dict:
        """provider bare name -> set of levers it covers."""
        out: dict[str, set] = {}
        for mod in project.modules.values():
            for n in ast.walk(mod.tree):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and mod.marker_on_def(n, "tuning-provider"):
                    out[n.name] = lever_reads(n)
        return out

    def check(self, project) -> list:
        graph = CallGraph(project)
        providers = self._providers(project)
        out = []
        for mod in project.modules.values():
            for site in self._cache_sites(mod):
                out.extend(self._check_site(mod, graph, providers, *site))
        return out

    # -- site discovery ----------------------------------------------------

    def _cache_sites(self, mod):
        """Yield (get_call, keyvar, entryvar) for cache lookups."""
        for n in ast.walk(mod.tree):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and isinstance(n.value, ast.Call)):
                continue
            call = n.value
            f = call.func
            if not (isinstance(f, ast.Attribute) and f.attr == "get"
                    and _CACHE_NAME.search(_recv_name(f))
                    and call.args
                    and isinstance(call.args[0], ast.Name)):
                continue
            yield n, call.args[0].id, n.targets[0].id

    # -- per-site analysis -------------------------------------------------

    def _check_site(self, mod, graph, providers, assign, keyvar, entryvar):
        fn = _enclosing_function(mod, assign)
        if fn is None:
            return []

        # builder closure: calls in the `if <entry> is None:` suite(s)
        builder_calls: set = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.If) and self._tests_none(n.test, entryvar):
                for stmt in n.body:
                    builder_calls |= call_names(stmt)
        if not builder_calls:
            return []
        # only compiled-program caches matter: the builder must reach a
        # jit/shard_map trace boundary
        if not (graph.reaches(builder_calls, "jit")
                or graph.reaches(builder_calls, "shard_map")
                or graph.reaches(builder_calls, "pjit")):
            return []

        levers = graph.reachable_levers(builder_calls)
        levers |= lever_reads(fn)     # enclosing-function direct reads
        # a provider CALLED in the enclosing function counts as a read
        # of its levers: its value typically feeds the builder as an
        # argument (quant_enabled() → quant_names → _build_shuffle_fn),
        # shaping the traced program just the same
        fn_calls = call_names(fn)
        for pname, plevers in providers.items():
            if pname in fn_calls:
                levers |= plevers
        if not levers:
            return []

        # key closure: calls in every assignment to keyvar, one hop
        # through locally assigned names
        local_assigns: dict[str, list] = {}
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        local_assigns.setdefault(t.id, []).append(n.value)
        key_calls: set = set()
        seen_names: set = set()
        frontier = [keyvar]
        for _hop in range(3):
            nxt = []
            for name in frontier:
                if name in seen_names:
                    continue
                seen_names.add(name)
                for value in local_assigns.get(name, ()):
                    key_calls |= call_names(value)
                    nxt.extend(x.id for x in ast.walk(value)
                               if isinstance(x, ast.Name))
            frontier = nxt
        covered: set = set()
        for pname, plevers in providers.items():
            if pname in key_calls or graph.reaches(key_calls, pname):
                covered |= plevers

        missing = sorted(levers - covered)
        out = []
        scope = mod.scope_of(assign)
        for lever in missing:
            out.append(Finding(
                self.id, mod.path, assign.lineno,
                key=f"{mod.path}::{scope}::{keyvar}::{lever}",
                message=f"cache key `{keyvar}` (scope {scope}) omits "
                        f"lever {lever}: the builder's traced program "
                        f"depends on it — add the tuning provider to "
                        f"the key or pragma with the reason it cannot "
                        f"go stale"))
        return out

    @staticmethod
    def _tests_none(test, entryvar) -> bool:
        return (isinstance(test, ast.Compare)
                and isinstance(test.left, ast.Name)
                and test.left.id == entryvar
                and len(test.ops) == 1
                and isinstance(test.ops[0], ast.Is)
                and isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value is None)
