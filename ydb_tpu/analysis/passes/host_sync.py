"""host-sync — device→host escape detector for the device-resident
modules (`ops/`, `dq/`, `parallel/`).

ROADMAP item 1's gate is "zero `to_pandas` calls inside a multi-stage
plan": every implicit device→host synchronization inside the modules
that are supposed to stay device-resident is debt this pass ratchets.
Flagged forms:

  * `<x>.to_pandas()` — the client-boundary materialization
  * `<x>.item()` — scalar sync
  * `np.asarray(<x>)` — implicit transfer when <x> is a device value
    (undecidable statically, so EVERY np.asarray in these modules is
    counted; host-only lanes carry a file pragma, upload paths a line
    pragma — the point is that each one is either burned down or
    visibly excused)
  * `float(jnp...)` / `int(jnp...)` / `bool(np.any(...))` — builtin
    cast directly wrapping a jnp/jax call

The blessed escape is `jax.device_get(<pytree>)` — ONE batched
transfer, visible at the call site — which this pass deliberately does
not flag; burning down a baseline entry usually means folding N
per-column `np.asarray` syncs into one `device_get`.

Suppression vocabulary: `# lint: transfer-ok(reason)` on the line (or
the line above) excuses a site as a legitimate boundary transfer — the
SAME pragma the runtime flight recorder (`utils/memledger.py
record_transfer(boundary=True)`) uses to classify a transfer as
excused, so static excusal and runtime classification cannot drift
apart. The generic `# lint: allow-host-sync(reason)` form keeps
working (the central pragma machinery), but transfer-ok is the one
vocabulary both sides speak.
"""

from __future__ import annotations

import ast
import re

from ydb_tpu.analysis.core import Finding, Pass

MODULES = ("ydb_tpu/ops/", "ydb_tpu/dq/", "ydb_tpu/parallel/")

# analysis-side modules: pure host-side consumers of already-recorded
# observability data (span trees, profile records) with NO device code
# reachable — they never need transfer pragmas even if they land inside
# a scanned prefix someday. `utils/critpath.py` walks span dicts;
# `utils/chrometrace.py` renders them to JSON; `utils/progstats.py`
# reads compiler-side cost/memory analysis at compile time (plus a
# one-shot peak micro-probe) — never in a per-row hot loop.
ANALYSIS_SIDE = frozenset((
    "ydb_tpu/utils/critpath.py",
    "ydb_tpu/utils/chrometrace.py",
    "ydb_tpu/utils/progstats.py",
))
_CASTS = ("float", "int", "bool")
_TRANSFER_OK_RE = re.compile(r"lint:\s*transfer-ok\(([^)]*)\)")


def transfer_ok_reason(mod, line: int):
    """The `# lint: transfer-ok(reason)` pragma on `line` or the line
    directly above it (same placement rule as every other pragma), or
    None. Shared with tests so the two honoring sides stay aligned."""
    for ln in (line, line - 1):
        m = _TRANSFER_OK_RE.search(mod.comments.get(ln, ""))
        if m:
            return m.group(1)
    return None


def _numpy_aliases(tree: ast.AST) -> set:
    out = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
    return out


def _has_jnp_call(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            root = n.func.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in ("jnp", "jax",
                                                          "lax"):
                return True
    return False


class HostSyncPass(Pass):
    id = "host-sync"
    title = "device→host escapes in device-resident modules"

    def check(self, project) -> list:
        out = []
        for mod in project.under(*MODULES):
            if mod.path in ANALYSIS_SIDE:
                continue
            np_names = _numpy_aliases(mod.tree)
            for n in ast.walk(mod.tree):
                if not isinstance(n, ast.Call):
                    continue
                f = n.func
                token = None
                if isinstance(f, ast.Attribute) \
                        and f.attr in ("to_pandas", "item") \
                        and not n.args:
                    token = f".{f.attr}()"
                elif isinstance(f, ast.Attribute) and f.attr == "asarray" \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in np_names:
                    token = f"{f.value.id}.asarray"
                elif isinstance(f, ast.Name) and f.id in _CASTS and n.args \
                        and _has_jnp_call(n.args[0]):
                    token = f"{f.id}(device)"
                if token is None:
                    continue
                if transfer_ok_reason(mod, n.lineno) is not None:
                    # excused boundary transfer — the flight recorder
                    # counts it under hostsync/boundary_transfers
                    continue
                scope = mod.scope_of(n)
                out.append(Finding(
                    self.id, mod.path, n.lineno,
                    key=f"{mod.path}::{scope}::{token}",
                    message=f"host sync `{token}` in device-resident "
                            f"module (scope {scope}) — stay on device or "
                            f"batch through one jax.device_get"))
        return out
