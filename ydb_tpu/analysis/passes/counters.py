"""counters — every counter/histogram name must exist in the registry.

`utils/metrics.py` holds `COUNTER_REGISTRY`, the machine-readable map
of every counter family the dashboards and gates read. This pass walks
every `GLOBAL.inc / .set / .set_max` and `GLOBAL_HIST.observe` call
(and the injected-`counters` equivalents the hive uses) and checks the
name literal against the registry:

  * exact entries match exactly;
  * entries ending `/*` match any name under that namespace, including
    the head of an f-string name (`f"slow_query/{kind}"` matches
    `slow_query/*`);
  * a fully dynamic name (variable) needs a line pragma naming the
    family it lands in.

The reverse direction ratchets documentation drift: an exact registry
entry that no code ever emits is a finding too (a dashboard reading it
sees permanent zeros — exactly the typo'd-dashboard failure mode this
pass exists to kill).
"""

from __future__ import annotations

import ast

from ydb_tpu.analysis.core import Finding, Pass

REGISTRY_MODULE = "ydb_tpu/utils/metrics.py"
REGISTRY_NAME = "COUNTER_REGISTRY"
_METHODS = ("inc", "set", "set_max", "observe")


def _recv_tail(func: ast.Attribute) -> str:
    v = func.value
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Attribute):
        return v.attr
    return ""


def _counter_receiver(func: ast.Attribute) -> bool:
    tail = _recv_tail(func)
    if func.attr == "observe":
        return tail == "GLOBAL_HIST" or tail.endswith("hist")
    return tail == "GLOBAL" or tail == "counters" \
        or tail.endswith("_counters")


def load_registry(project) -> dict:
    """name -> doc from the COUNTER_REGISTRY literal; None if absent."""
    mod = project.get(REGISTRY_MODULE)
    if mod is None:
        return None
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and n.targets[0].id == REGISTRY_NAME:
            try:
                return dict(ast.literal_eval(n.value))
            except (ValueError, SyntaxError):
                return None
    return None


def _match(name: str, registry: dict) -> bool:
    if name in registry:
        return True
    return any(name.startswith(entry[:-1])
               for entry in registry if entry.endswith("/*"))


class CounterRegistryPass(Pass):
    id = "counters"
    title = "counter names absent from COUNTER_REGISTRY"

    def check(self, project) -> list:
        registry = load_registry(project)
        out = []
        if registry is None:
            out.append(Finding(
                self.id, REGISTRY_MODULE, 1,
                key=f"{REGISTRY_MODULE}::<module>::registry-missing",
                message=f"{REGISTRY_NAME} dict literal not found in "
                        f"{REGISTRY_MODULE}"))
            return out
        used_exact: set = set()
        for mod in project.modules.values():
            for n in ast.walk(mod.tree):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in _METHODS
                        and _counter_receiver(n.func) and n.args):
                    continue
                arg = n.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    name = arg.value
                    used_exact.add(name)
                    if not _match(name, registry):
                        scope = mod.scope_of(n)
                        out.append(Finding(
                            self.id, mod.path, n.lineno,
                            key=f"{mod.path}::{scope}::{name}",
                            message=f"counter {name!r} is not in "
                                    f"{REGISTRY_NAME} — register it in "
                                    f"utils/metrics.py (typo'd names "
                                    f"feed dashboards nobody reads)"))
                elif isinstance(arg, ast.JoinedStr) and arg.values \
                        and isinstance(arg.values[0], ast.Constant):
                    # the literal head must lie INSIDE some family
                    # (head startswith prefix). The reverse — a short
                    # head like "engine/" that a family merely starts
                    # with — proves nothing about where the full name
                    # lands and must flag.
                    head = str(arg.values[0].value)
                    if not any(head.startswith(e[:-1])
                               for e in registry if e.endswith("/*")):
                        scope = mod.scope_of(n)
                        out.append(Finding(
                            self.id, mod.path, n.lineno,
                            key=f"{mod.path}::{scope}::f\"{head}…\"",
                            message=f"f-string counter head {head!r} "
                                    f"matches no wildcard family in "
                                    f"{REGISTRY_NAME}"))
                else:
                    scope = mod.scope_of(n)
                    out.append(Finding(
                        self.id, mod.path, n.lineno,
                        key=f"{mod.path}::{scope}::<dynamic>",
                        message="dynamic counter name — pragma it with "
                                "the registry family it lands in"))
        # reverse: exact registry entries nothing emits — skipping
        # wildcards and entries declared "(dynamic)" (emitted through a
        # variable, pragma'd at the site) or "(derived)" (computed in
        # QueryEngine.counters(), not emitted through Counters)
        reg_mod = project.get(REGISTRY_MODULE)
        for entry in sorted(registry):
            doc = str(registry[entry])
            if "(dynamic)" in doc or "(derived)" in doc:
                continue
            if not entry.endswith("/*") and entry not in used_exact:
                out.append(Finding(
                    self.id, REGISTRY_MODULE,
                    self._entry_line(reg_mod, entry),
                    key=f"{REGISTRY_MODULE}::{REGISTRY_NAME}::{entry}",
                    message=f"registry entry {entry!r} is emitted "
                            f"nowhere — stale doc or a typo at the "
                            f"emit site"))
        return out

    @staticmethod
    def _entry_line(mod, entry: str) -> int:
        needle = f'"{entry}"'
        for i, line in enumerate(mod.lines, 1):
            if needle in line:
                return i
        return 1
