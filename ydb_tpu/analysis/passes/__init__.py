from ydb_tpu.analysis.passes.cache_key import CacheKeyPass
from ydb_tpu.analysis.passes.counters import CounterRegistryPass
from ydb_tpu.analysis.passes.host_sync import HostSyncPass
from ydb_tpu.analysis.passes.locks import LockDisciplinePass
from ydb_tpu.analysis.passes.rpc_surface import RpcSurfacePass

ALL_PASSES = (HostSyncPass, CacheKeyPass, LockDisciplinePass,
              CounterRegistryPass, RpcSurfacePass)
