"""rpc-surface — the three control surfaces must not drift.

`server/service.py QueryServicer` defines the RPC set; `server/
service.py Client` is the caller every OS-cluster component uses; `dq/
runner.py LocalWorker` is the SAME surface in-process (the 1-worker
degenerate case and every single-process multi-engine test). A servicer
method without a Client method is an RPC nothing can call; one without
a LocalWorker method means in-process clusters silently diverge from OS
clusters — the class of bug where a feature works in tests and fails
the moment a real gRPC worker joins.

Known renames and deliberate N/A holes are declared here (visible,
reviewed) rather than inferred:

  * `execute_query` ↔ Client.execute / LocalWorker.execute
  * `exchange_put`  ↔ ExchangeClient.put / LocalWorker._land
  * session/tx/hive-membership RPCs have no LocalWorker seat — the
    in-process cluster has no session table, runs 2PC through the
    coordinator directly, and registers with a Hive object, not over
    its own loopback.
"""

from __future__ import annotations

import ast

from ydb_tpu.analysis.core import Finding, Pass

SERVICE = "ydb_tpu/server/service.py"
RUNNER = "ydb_tpu/dq/runner.py"

# servicer method -> (client method | None, worker method | None);
# None = deliberately absent on that surface, with the reason above
NAME_MAP = {
    "execute_query": ("execute", "execute"),
    "exchange_put": ("put", "_land"),
    "close_session": ("close", None),
    # program-store stats is a node-local monitoring poll, like
    # Counters — the in-process cluster reads `.sys/progstore` directly
    "prog_store_stats": ("prog_store_stats", None),
    "tx_prepare": ("tx_prepare", None),
    "tx_decide": ("tx_decide", None),
    "tx_resolve": ("tx_resolve", None),
    "tx_in_doubt": ("tx_in_doubt", None),
    "hive_register": ("hive_register", None),
    "hive_heartbeat": ("hive_heartbeat", None),
    "hive_nodes": ("hive_nodes", None),
}


def _class_methods(mod, cls_name: str):
    for n in mod.tree.body:
        if isinstance(n, ast.ClassDef) and n.name == cls_name:
            return {m.name: m for m in n.body
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
    return None


def _rpc_methods(servicer: dict) -> dict:
    """Handlers with the (self, request, context) gRPC signature."""
    out = {}
    for name, node in servicer.items():
        if name.startswith("_"):
            continue
        args = [a.arg for a in node.args.args]
        if len(args) == 3 and args[0] == "self" and args[2] == "context":
            out[name] = node
    return out


class RpcSurfacePass(Pass):
    id = "rpc-surface"
    title = "servicer / Client / LocalWorker surface drift"

    def check(self, project) -> list:
        svc_mod = project.get(SERVICE)
        run_mod = project.get(RUNNER)
        if svc_mod is None or run_mod is None:
            return []
        servicer = _class_methods(svc_mod, "QueryServicer")
        client = _class_methods(svc_mod, "Client")
        exch_client = _class_methods(svc_mod, "ExchangeClient") or {}
        worker = _class_methods(run_mod, "LocalWorker")
        if servicer is None or client is None or worker is None:
            return []

        out = []
        for rpc, node in sorted(_rpc_methods(servicer).items()):
            want_client, want_worker = NAME_MAP.get(rpc, (rpc, rpc))
            if want_client is not None and want_client not in client \
                    and want_client not in exch_client:
                out.append(Finding(
                    self.id, SERVICE, node.lineno,
                    key=f"{SERVICE}::QueryServicer.{rpc}::client",
                    message=f"RPC `{rpc}` has no Client method "
                            f"`{want_client}` — nothing can call it"))
            if want_worker is not None and want_worker not in worker:
                out.append(Finding(
                    self.id, RUNNER, node.lineno,
                    key=f"{SERVICE}::QueryServicer.{rpc}::worker",
                    message=f"RPC `{rpc}` has no LocalWorker method "
                            f"`{want_worker}` — in-process clusters "
                            f"diverge from OS clusters"))
        return out
