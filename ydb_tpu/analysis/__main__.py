"""CLI: `python -m ydb_tpu.analysis [--write-baseline] [--json] [...]`.

Exit codes: 0 = clean (findings ⊆ baseline), 1 = new findings, 2 =
setup error. `--strict-shrink` also fails when the tree has LESS debt
than the baseline records — CI uses it so the ratchet file is tightened
in the same PR that burns debt down.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ydb_tpu.analysis.core import Baseline, Project, load_passes, run

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ydb_tpu.analysis",
        description="graftlint: AST invariant checks with a baseline "
                    "ratchet")
    ap.add_argument("--root", default=None,
                    help="repo root (default: parent of the package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline to the current findings")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--strict-shrink", action="store_true",
                    help="fail when current debt < baseline (tighten "
                         "the ratchet file in the same change)")
    ap.add_argument("--pass", dest="only", default=None,
                    help="run a single pass by id")
    args = ap.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if not os.path.isdir(os.path.join(root, "ydb_tpu")):
        print(f"error: {root} has no ydb_tpu/ package", file=sys.stderr)
        return 2

    project = Project.from_dir(root)
    passes = load_passes()
    if args.only:
        passes = [p for p in passes if p.id == args.only]
        if not passes:
            print(f"error: no pass named {args.only!r}", file=sys.stderr)
            return 2

    if args.write_baseline:
        if args.only:
            # a single-pass rewrite would silently drop every OTHER
            # pass's recorded debt from the file — refuse
            print("error: --write-baseline regenerates ALL passes; "
                  "drop --pass", file=sys.stderr)
            return 2
        findings = []
        for p in passes:
            findings.extend(p.run(project))
        Baseline.from_findings(findings).save(args.baseline)
        print(f"baseline: {len(findings)} findings -> {args.baseline}")
        return 0

    baseline = Baseline.load(args.baseline)
    report = run(project, passes, baseline)
    new, shrunk = report["new"], report["shrunk"]

    if args.as_json:
        print(json.dumps({
            "findings": len(report["findings"]),
            "excused": report["excused"],
            "new": [f.__dict__ for f in new],
            "shrunk": {p: {k: list(v) for k, v in ks.items()}
                       for p, ks in shrunk.items()},
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        for pass_id, keys in sorted(shrunk.items()):
            for key, (allowed, have) in sorted(keys.items()):
                print(f"ratchet: [{pass_id}] {key}: baseline {allowed} "
                      f"-> now {have} (tighten baseline.json)")
        print(f"graftlint: {len(report['findings'])} findings "
              f"({report['excused']} baselined, {len(new)} new)")

    if new:
        return 1
    if args.strict_shrink and shrunk:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
