"""graftlint core — parsed-module project, pragma suppression, baseline
ratchet, and the pass runner.

Design notes:

* Findings carry a line number for humans but are IDENTIFIED by a
  line-free key `path::scope::token` (scope = enclosing def/class
  qualname). The baseline stores `{pass: {key: count}}`, so unrelated
  edits that move code around do not invalidate it; growth of the same
  debt in the same function does.
* Pragmas are read from real COMMENT tokens (tokenize), not regexed out
  of source lines, so a `# lint:` inside a string literal never
  suppresses anything.
* A pass is project-scoped (it sees every parsed module at once) —
  cross-module checks (cache-key reachability, RPC surface drift) need
  the whole tree anyway.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

_PRAGMA_RE = re.compile(
    r"lint:\s*allow-(?P<file>file-)?(?P<pass>[a-z][a-z0-9-]*)"
    r"\((?P<reason>[^)]*)\)")
_MARKER_RE = re.compile(r"lint:\s*(?P<marker>[a-z][a-z0-9-]*)\s*$")


@dataclass(frozen=True)
class Finding:
    pass_id: str
    path: str          # project-relative, forward slashes
    line: int          # 1-based, for humans
    key: str           # stable identity: path::scope::token
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


class ModuleInfo:
    """One parsed source file: AST + per-line comments + pragmas."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        # line -> comment text (without leading '#'), from real tokens
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string.lstrip("#")
        except tokenize.TokenError:
            pass
        # pass -> reason for whole-file suppressions
        self.file_pragmas: dict[str, str] = {}
        # line -> {pass: reason} for single-line suppressions
        self.line_pragmas: dict[int, dict[str, str]] = {}
        # line -> marker name ("tuning-provider", ...)
        self.markers: dict[int, str] = {}
        for ln, text in self.comments.items():
            for m in _PRAGMA_RE.finditer(text):
                if m.group("file"):
                    self.file_pragmas[m.group("pass")] = m.group("reason")
                else:
                    self.line_pragmas.setdefault(ln, {})[m.group("pass")] \
                        = m.group("reason")
            m = _MARKER_RE.search(text)
            if m:
                self.markers[ln] = m.group("marker")

    def suppressed(self, pass_id: str, line: int) -> bool:
        """A finding at `line` is excused by a pragma on the same line,
        on the line directly above, or by a file-level pragma."""
        if pass_id in self.file_pragmas:
            return True
        for ln in (line, line - 1):
            if pass_id in self.line_pragmas.get(ln, {}):
                return True
        return False

    def marker_on_def(self, node: ast.AST, marker: str) -> bool:
        """Is `# lint: <marker>` on the def line or the line above it?"""
        ln = getattr(node, "lineno", 0)
        return (self.markers.get(ln) == marker
                or self.markers.get(ln - 1) == marker)

    def scope_of(self, node: ast.AST) -> str:
        """Qualname-ish enclosing scope of a node (for stable keys)."""
        target_ln = getattr(node, "lineno", 0)
        best = "<module>"
        best_ln = 0

        def walk(n, prefix):
            nonlocal best, best_ln
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    name = f"{prefix}{child.name}"
                    if child.lineno <= target_ln \
                            and child.end_lineno >= target_ln \
                            and child.lineno >= best_ln:
                        best, best_ln = name, child.lineno
                    walk(child, name + ".")
                else:
                    walk(child, prefix)

        walk(self.tree, "")
        return best


class Project:
    """Every parsed module under the package root."""

    def __init__(self, modules: dict[str, ModuleInfo]):
        self.modules = modules        # rel path -> ModuleInfo

    @classmethod
    def from_dir(cls, root: str, package: str = "ydb_tpu") -> "Project":
        mods: dict[str, ModuleInfo] = {}
        pkg_root = os.path.join(root, package)
        for dirpath, _dirs, files in os.walk(pkg_root):
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                with open(full, encoding="utf-8") as f:
                    mods[rel] = ModuleInfo(rel, f.read())
        return cls(mods)

    @classmethod
    def from_sources(cls, sources: dict[str, str]) -> "Project":
        """In-memory project for fixture tests."""
        return cls({p: ModuleInfo(p, s) for p, s in sources.items()})

    def get(self, path: str):
        return self.modules.get(path)

    def under(self, *prefixes: str):
        """Modules whose path starts with any prefix."""
        for path in sorted(self.modules):
            if any(path.startswith(p) for p in prefixes):
                yield self.modules[path]


class Pass:
    """One invariant. Subclasses set `id`/`title` and implement
    `check(project) -> [Finding]` WITHOUT worrying about pragmas — the
    runner drops suppressed findings centrally."""

    id = "base"
    title = "base pass"

    def check(self, project: Project) -> list:
        raise NotImplementedError

    def run(self, project: Project) -> list:
        out = []
        for f in self.check(project):
            mod = project.get(f.path)
            if mod is not None and mod.suppressed(self.id, f.line):
                continue
            out.append(f)
        return out


class Baseline:
    """The ratchet file: `{pass: {key: count}}`. Existing debt passes;
    NEW keys or growth of an existing key fail; shrinkage is reported so
    the file can be tightened in the same change."""

    def __init__(self, entries: dict | None = None):
        self.entries: dict[str, dict[str, int]] = entries or {}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls({})
        with open(path, encoding="utf-8") as f:
            return cls(json.load(f))

    @classmethod
    def from_findings(cls, findings: list) -> "Baseline":
        entries: dict[str, dict[str, int]] = {}
        for f in findings:
            per = entries.setdefault(f.pass_id, {})
            per[f.key] = per.get(f.key, 0) + 1
        return cls(entries)

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({p: dict(sorted(ks.items()))
                       for p, ks in sorted(self.entries.items())},
                      f, indent=1, sort_keys=True)
            f.write("\n")

    def compare(self, findings: list) -> tuple:
        """→ (new_findings, excused_count, shrunk) where `shrunk` maps
        pass -> {key: (baselined, current)} for ratchet tightening."""
        current: dict[str, dict[str, list]] = {}
        for f in findings:
            current.setdefault(f.pass_id, {}).setdefault(f.key, []).append(f)
        new: list = []
        excused = 0
        for pass_id, per_key in current.items():
            base = self.entries.get(pass_id, {})
            for key, fs in per_key.items():
                allowed = base.get(key, 0)
                excused += min(allowed, len(fs))
                if len(fs) > allowed:
                    new.extend(sorted(fs, key=lambda x: x.line)[allowed:])
        shrunk: dict[str, dict[str, tuple]] = {}
        for pass_id, base in self.entries.items():
            per_key = current.get(pass_id, {})
            for key, allowed in base.items():
                have = len(per_key.get(key, []))
                if have < allowed:
                    shrunk.setdefault(pass_id, {})[key] = (allowed, have)
        return new, excused, shrunk


def load_passes() -> list:
    from ydb_tpu.analysis.passes import ALL_PASSES
    return [cls() for cls in ALL_PASSES]


def run(project: Project, passes=None, baseline: Baseline | None = None):
    """→ dict report: findings, new (vs baseline), excused, shrunk."""
    passes = passes if passes is not None else load_passes()
    findings: list = []
    for p in passes:
        findings.extend(p.run(project))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    report = {"findings": findings, "new": findings, "excused": 0,
              "shrunk": {}}
    if baseline is not None:
        new, excused, shrunk = baseline.compare(findings)
        report.update(new=sorted(new, key=lambda f: (f.path, f.line)),
                      excused=excused, shrunk=shrunk)
    return report
