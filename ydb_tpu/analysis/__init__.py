"""graftlint — project-specific AST invariant checks with a baseline
ratchet.

The system's correctness rests on conventions no general-purpose linter
knows about: program-shaping `YDB_TPU_*` levers must ride in every
compiled-program cache key (a missed lever is a silent stale-cache
wrong answer), shared state must be mutated under its owning lock,
counters must exist in the registry the dashboards read, host-sync
escapes must not creep back into the device-resident modules, and the
three RPC surfaces (servicer / Client / LocalWorker) must not drift
apart. Each convention is one `Pass` here; `python -m ydb_tpu.analysis`
runs them all and compares against the checked-in baseline
(`ydb_tpu/analysis/baseline.json`): existing debt is excused, any NEW
finding fails — the compile-time-over-runtime stance of arxiv
2112.01075 applied to our own invariants.

Suppression grammar (a reason is mandatory):

    x = np.asarray(d)   # lint: allow-host-sync(client result boundary)
    # lint: allow-file-host-sync(host execution lane, never on device)

The first form excuses one line (same line or the line directly
above); the `allow-file-` form anywhere in a module excuses the whole
file for that pass. `# lint: tuning-provider` on a `def` line marks a
function as a cache-key tuning provider (see passes/cache_key.py).
"""

from ydb_tpu.analysis.core import (Baseline, Finding, Project, load_passes,
                                   run)

__all__ = ["Baseline", "Finding", "Project", "load_passes", "run"]
