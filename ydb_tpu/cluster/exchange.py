"""Worker↔worker data channels — the DQ output-channel analog.

The r4 cluster seam scattered SQL TEXT star-wise and merged in the
router; workers never exchanged data, so a join between two sharded
tables was impossible without replicating one side. This module is the
data plane the reference's DQ channels provide
(`ydb/library/yql/dq/runtime/dq_output_channel.cpp:31`, task graph
`dq_tasks_graph.h:43-165`): a *channel* is a named set of hash
partitions in flight between workers; a *frame* is one partition's rows
as an npz payload behind a JSON header, shipped over the workers' gRPC
front (DCN seam). Hash routing uses the shared splitmix64/crc32
definitions, so every producer routes a key to the same consumer
(`utils/hashing.py` — host and device agree bit-for-bit).

Frame wire format: 4-byte big-endian header length | header JSON
{channel, part, src, seq, n_rows} | npz bytes (one array per column;
object columns allow-pickle within the trusted cluster, the Interconnect
trust model).

The DQ runtime (`ydb_tpu/dq/`) adds two disciplines on top of the raw
frame plane:

  * idempotent delivery — every frame carries a (src, seq) pair unique
    within its channel; the receiving `ExchangeBuffer` drops duplicates,
    so a producer may RETRY a failed `ExchangePut` blindly (the reply
    may have been lost after the frame landed);
  * flow control — `ChannelWriter` splits a task's output into bounded
    frames and caps the bytes in flight per channel, so one fat shuffle
    cannot balloon sender memory or saturate a peer's buffer in one
    burst (the output-channel watermarks of `dq_output_channel.cpp`).
"""

from __future__ import annotations

import io
import json
import struct
import zlib

import numpy as np
import pandas as pd

from ydb_tpu.utils.hashing import splitmix64


def hash_partition(df: pd.DataFrame, key: str, n_parts: int,
                   kind: str = None) -> list:
    """Split rows by key hash into n_parts frames (NULL keys drop — an
    inner-join shuffle never matches them).

    `kind` ("int" | "string" | None) is the TABLE SCHEMA's verdict on
    the key type, passed by the DQ task core (`ydb_tpu/dq/task.py
    run_task`) from the stage result's schema. Deciding from the pandas
    dtype alone (the r5 behavior) is
    wrong for nullable integer keys: `to_pandas` widens them to object
    dtype, so one producer hashed `str(7)` with crc32 while a NOT NULL
    producer hashed `7` with splitmix64 — the same key routed to two
    different consumers and sharded×sharded joins silently dropped
    matches. With kind="int", object-dtype values coerce to int64 and
    take the splitmix64 route every producer agrees on."""
    col = df[key]
    notna = col.notna()
    if not notna.all():
        df = df[notna]
        col = df[key]
    part = key_buckets(col.to_numpy(), n_parts, kind)
    return [df[part == p] for p in range(n_parts)]


def key_buckets(vals: np.ndarray, n_parts: int, kind: str = None
                ) -> np.ndarray:
    """Per-value consumer bucket for a NULL-free key array — the ONE
    routing function every channel plane shares. The host plane's
    `hash_partition` splits frames by it; the ICI plane
    (`ydb_tpu/dq/ici.py`) feeds the same buckets into the device
    all_to_all, so a key hashes to the same consumer no matter which
    plane its edge lowered to (and the two sides of a join agree even
    when their edges took different planes)."""
    if kind is None:                  # no schema available: dtype guess
        if vals.dtype == object or vals.dtype.kind in ("U", "S", "T"):
            kind = "string"
        elif vals.dtype.kind == "f":
            kind = "float"
        else:
            kind = "int"
    if kind == "float":
        raise ValueError("float join keys are not hash-partitionable "
                         "(equality on floats is ill-defined across the "
                         "wire)")
    if kind == "string":
        h = np.fromiter((zlib.crc32(str(v).encode()) for v in vals),
                        np.uint64, count=len(vals))
    else:
        # schema-int keys: nullable columns arrive as object (python
        # ints — exact, numpy raises on int64 overflow) or float64
        # (NaN-widened). Float widening is only exact up to 2^53: a
        # value that doesn't round-trip would hash differently than on
        # an int64-dtype producer — the exact misroute this path
        # exists to prevent — so refuse loudly instead
        arr = np.asarray(vals)
        if arr.dtype.kind == "f":
            # any |v| >= 2^53 may have COLLIDED during the int→float
            # widening (2^53 and 2^53+1 are the same float64) — the loss
            # happened upstream, so a round-trip check can't see it;
            # refuse by magnitude, plus round-trip for fractional values
            iv = arr.astype(np.int64)
            if (len(arr) and np.abs(arr).max() >= float(2**53)) \
                    or not np.array_equal(iv.astype(arr.dtype), arr):
                raise ValueError(
                    "int key column arrived float-widened with values "
                    "at or above 2^53 (or fractional) — not exactly "
                    "representable, cannot hash-partition consistently "
                    "across producers")
            arr = iv
        else:
            arr = arr.astype(np.int64)
        h = splitmix64(np, arr)
    return (h % np.uint64(n_parts)).astype(np.int64)


def pack_frame(header: dict, df: pd.DataFrame) -> bytes:
    buf = io.BytesIO()
    arrays = {}
    for c in df.columns:
        a = df[c].to_numpy()
        if a.dtype.kind in ("U", "S", "T"):
            a = a.astype(object)
        arrays[c] = a
    np.savez(buf, **arrays)
    header = dict(header, columns=list(df.columns), n_rows=len(df))
    hj = json.dumps(header).encode()
    return struct.pack("!I", len(hj)) + hj + buf.getvalue()


def unpack_header(data: bytes) -> dict:
    """Parse ONLY the JSON header — safe on untrusted bytes. Callers
    must authenticate against it BEFORE touching the npz payload
    (np.load with allow_pickle executes pickle payloads)."""
    (hlen,) = struct.unpack_from("!I", data, 0)
    return json.loads(data[4:4 + hlen].decode())


def unpack_frame(data: bytes):
    (hlen,) = struct.unpack_from("!I", data, 0)
    header = json.loads(data[4:4 + hlen].decode())
    z = np.load(io.BytesIO(data[4 + hlen:]), allow_pickle=True)
    cols = {c: z[c] for c in header["columns"]}
    df = pd.DataFrame(cols, columns=header["columns"])
    return header, df


class ExchangeBuffer:
    """Per-worker in-memory landing zone for incoming channel frames
    (the input-channel buffer of a DQ compute actor). Frames carrying a
    (src, seq) identity are deduplicated per channel, making a retried
    `ExchangePut` idempotent — the retry may race a first attempt whose
    reply was lost after the frame landed."""

    def __init__(self, budget_bytes: int = 1 << 30):
        import threading
        self._frames: dict = {}           # guarded-by: _mu
        self._seen: dict = {}             # guarded-by: _mu
        self.bytes = 0                    # guarded-by: _mu
        self.dup_frames = 0               # guarded-by: _mu
        self.budget = budget_bytes
        self._mu = threading.Lock()

    def put(self, channel: str, df: pd.DataFrame, nbytes: int,
            src: str = "", seq=None) -> bool:
        """Land one frame; returns False for a (src, seq) duplicate."""
        with self._mu:
            seen = None
            if seq is not None:
                seen = self._seen.setdefault(channel, set())
                if (src, seq) in seen:
                    self.dup_frames += 1
                    return False
            if self.bytes + nbytes > self.budget:
                # NOT marked seen: a budget-rejected frame never landed,
                # so the producer's retry must not dedup into a no-op
                raise MemoryError(
                    f"exchange buffer over budget "
                    f"({self.bytes + nbytes} > {self.budget})")
            if seen is not None:
                seen.add((src, seq))
            self._frames.setdefault(channel, []).append((df, nbytes))
            self.bytes += nbytes
            return True

    def take(self, channel: str) -> pd.DataFrame:
        """Drain and concatenate every frame of a channel."""
        df, _nb = self.take2(channel)
        return df

    def take2(self, channel: str):
        """`take` plus the drained byte count — the consumer-side channel
        stat (`dq_input_channel` bytes) the profile subsystem records."""
        with self._mu:
            frames = self._frames.pop(channel, [])
            self._seen.pop(channel, None)
            nbytes = sum(nb for (_f, nb) in frames)
            self.bytes -= nbytes
        if not frames:
            return pd.DataFrame(), 0
        return (pd.concat([f for (f, _nb) in frames], ignore_index=True),
                nbytes)

    def drop(self, channel: str) -> None:
        with self._mu:
            frames = self._frames.pop(channel, None)
            self._seen.pop(channel, None)
            if frames:
                self.bytes -= sum(nb for (_f, nb) in frames)


class ChannelWriter:
    """Producer side of one output channel: splits DataFrames into
    bounded frames, stamps each with (src, seq), and ships them with a
    cap on in-flight bytes plus per-frame retry (safe — the receiver
    dedups on (src, seq)).

    `send(peer_idx, frame_bytes)` is the transport (gRPC ExchangePut to
    a real peer, a direct buffer put for in-process workers)."""

    def __init__(self, channel: str, src: str, send, n_peers: int,
                 token: str = "", frame_rows: int = None,
                 inflight_bytes: int = None, retries: int = 2,
                 counters=None, trace=None):
        import itertools
        import os
        import threading
        from concurrent.futures import ThreadPoolExecutor
        self.channel = channel
        self.src = src
        self.token = token
        self._send = send
        self.frame_rows = frame_rows or int(os.environ.get(
            "YDB_TPU_DQ_FRAME_ROWS", 1 << 16))
        self.inflight_budget = inflight_bytes or int(os.environ.get(
            "YDB_TPU_DQ_INFLIGHT_BYTES", 32 << 20))
        self.retries = retries
        self._counters = counters
        # trace context carried in every frame header ({trace_id,
        # parent_span_id} — utils/tracing): a consumer-side debugger can
        # attribute any landed frame back to its query's span tree
        self._trace = {k: trace[k] for k in ("trace_id", "parent_span_id")
                       if trace and trace.get(k) is not None} \
            if trace else {}
        self._seq = itertools.count()
        self._inflight = 0
        self.peak_inflight = 0
        self.bytes_sent = 0
        self.frames_sent = 0
        self.rows_sent = 0
        self.wait_ms = 0.0               # backpressure: flow-control stalls
        self._cv = threading.Condition()
        self._pool = ThreadPoolExecutor(
            max_workers=min(8, max(2, n_peers)))
        self._futures: list = []

    def ship(self, peer: int, df: pd.DataFrame) -> None:
        """Queue one peer's partition, split into flow-controlled frames.
        An empty partition still ships one frame: the consumer learns the
        channel's columns even when it received no rows."""
        nrows = len(df)
        lo = 0
        while True:
            chunk = df.iloc[lo:lo + self.frame_rows]
            seq = next(self._seq)
            frame = pack_frame({"channel": self.channel, "part": peer,
                                "src": self.src, "seq": seq,
                                "token": self.token, **self._trace}, chunk)
            self._acquire(len(frame))
            self._futures.append(
                self._pool.submit(self._send_one, peer, frame))
            self.rows_sent += len(chunk)
            lo += self.frame_rows
            if lo >= nrows:
                break

    def _acquire(self, nbytes: int) -> None:
        import time
        with self._cv:
            # a frame larger than the whole budget still passes alone
            if self._inflight and \
                    self._inflight + nbytes > self.inflight_budget:
                t0 = time.perf_counter()
                while self._inflight and \
                        self._inflight + nbytes > self.inflight_budget:
                    self._cv.wait()
                self.wait_ms += (time.perf_counter() - t0) * 1000.0
            self._inflight += nbytes
            self.peak_inflight = max(self.peak_inflight, self._inflight)

    def stats(self) -> dict:
        """Per-channel producer stats (the dq_output_channel stats view):
        what run_task ships back for the cross-worker profile."""
        return {"channel": self.channel, "frames": self.frames_sent,
                "rows": self.rows_sent, "bytes": self.bytes_sent,
                "backpressure_wait_ms": round(self.wait_ms, 3)}

    def _send_one(self, peer: int, frame: bytes) -> None:
        import time
        try:
            last = None
            for attempt in range(self.retries + 1):
                try:
                    self._send(peer, frame)
                    break
                except Exception as e:       # noqa: BLE001 — retried
                    last = e
                    time.sleep(0.05 * (attempt + 1))
            else:
                raise last
            with self._cv:
                self.bytes_sent += len(frame)
                self.frames_sent += 1
        finally:
            with self._cv:
                self._inflight -= len(frame)
                self._cv.notify_all()

    def close(self) -> None:
        """Wait for every queued frame; raise the first transport error."""
        err = None
        for f in self._futures:
            try:
                f.result()
            except Exception as e:           # noqa: BLE001
                err = err or e
        self._pool.shutdown(wait=True)
        if self._counters is not None:
            self._counters.set_max("dq/channel_inflight_peak_bytes",
                                   self.peak_inflight)
        if err is not None:
            raise err
