"""Worker↔worker data channels — the DQ output-channel analog.

The r4 cluster seam scattered SQL TEXT star-wise and merged in the
router; workers never exchanged data, so a join between two sharded
tables was impossible without replicating one side. This module is the
data plane the reference's DQ channels provide
(`ydb/library/yql/dq/runtime/dq_output_channel.cpp:31`, task graph
`dq_tasks_graph.h:43-165`): a *channel* is a named set of hash
partitions in flight between workers; a *frame* is one partition's rows
as an npz payload behind a JSON header, shipped over the workers' gRPC
front (DCN seam). Hash routing uses the shared splitmix64/crc32
definitions, so every producer routes a key to the same consumer
(`utils/hashing.py` — host and device agree bit-for-bit).

Frame wire format: 4-byte big-endian header length | header JSON
{channel, part, src, n_rows} | npz bytes (one array per column; object
columns allow-pickle within the trusted cluster, the Interconnect trust
model).
"""

from __future__ import annotations

import io
import json
import struct
import zlib

import numpy as np
import pandas as pd

from ydb_tpu.utils.hashing import splitmix64


def hash_partition(df: pd.DataFrame, key: str, n_parts: int,
                   kind: str = None) -> list:
    """Split rows by key hash into n_parts frames (NULL keys drop — an
    inner-join shuffle never matches them).

    `kind` ("int" | "string" | None) is the TABLE SCHEMA's verdict on
    the key type, passed by `shuffle_write` from the stage result's
    schema. Deciding from the pandas dtype alone (the r5 behavior) is
    wrong for nullable integer keys: `to_pandas` widens them to object
    dtype, so one producer hashed `str(7)` with crc32 while a NOT NULL
    producer hashed `7` with splitmix64 — the same key routed to two
    different consumers and sharded×sharded joins silently dropped
    matches. With kind="int", object-dtype values coerce to int64 and
    take the splitmix64 route every producer agrees on."""
    col = df[key]
    notna = col.notna()
    if not notna.all():
        df = df[notna]
        col = df[key]
    vals = col.to_numpy()
    if kind is None:                  # no schema available: dtype guess
        if vals.dtype == object or vals.dtype.kind in ("U", "S", "T"):
            kind = "string"
        elif vals.dtype.kind == "f":
            kind = "float"
        else:
            kind = "int"
    if kind == "float":
        raise ValueError("float join keys are not hash-partitionable "
                         "(equality on floats is ill-defined across the "
                         "wire)")
    if kind == "string":
        h = np.fromiter((zlib.crc32(str(v).encode()) for v in vals),
                        np.uint64, count=len(vals))
    else:
        # schema-int keys: nullable columns arrive as object (python
        # ints — exact, numpy raises on int64 overflow) or float64
        # (NaN-widened). Float widening is only exact up to 2^53: a
        # value that doesn't round-trip would hash differently than on
        # an int64-dtype producer — the exact misroute this path
        # exists to prevent — so refuse loudly instead
        arr = np.asarray(vals)
        if arr.dtype.kind == "f":
            # any |v| >= 2^53 may have COLLIDED during the int→float
            # widening (2^53 and 2^53+1 are the same float64) — the loss
            # happened upstream, so a round-trip check can't see it;
            # refuse by magnitude, plus round-trip for fractional values
            iv = arr.astype(np.int64)
            if (len(arr) and np.abs(arr).max() >= float(2**53)) \
                    or not np.array_equal(iv.astype(arr.dtype), arr):
                raise ValueError(
                    "int key column arrived float-widened with values "
                    "at or above 2^53 (or fractional) — not exactly "
                    "representable, cannot hash-partition consistently "
                    "across producers")
            arr = iv
        else:
            arr = arr.astype(np.int64)
        h = splitmix64(np, arr)
    part = (h % np.uint64(n_parts)).astype(np.int64)
    return [df[part == p] for p in range(n_parts)]


def pack_frame(header: dict, df: pd.DataFrame) -> bytes:
    buf = io.BytesIO()
    arrays = {}
    for c in df.columns:
        a = df[c].to_numpy()
        if a.dtype.kind in ("U", "S", "T"):
            a = a.astype(object)
        arrays[c] = a
    np.savez(buf, **arrays)
    header = dict(header, columns=list(df.columns), n_rows=len(df))
    hj = json.dumps(header).encode()
    return struct.pack("!I", len(hj)) + hj + buf.getvalue()


def unpack_header(data: bytes) -> dict:
    """Parse ONLY the JSON header — safe on untrusted bytes. Callers
    must authenticate against it BEFORE touching the npz payload
    (np.load with allow_pickle executes pickle payloads)."""
    (hlen,) = struct.unpack_from("!I", data, 0)
    return json.loads(data[4:4 + hlen].decode())


def unpack_frame(data: bytes):
    (hlen,) = struct.unpack_from("!I", data, 0)
    header = json.loads(data[4:4 + hlen].decode())
    z = np.load(io.BytesIO(data[4 + hlen:]), allow_pickle=True)
    cols = {c: z[c] for c in header["columns"]}
    df = pd.DataFrame(cols, columns=header["columns"])
    return header, df


class ExchangeBuffer:
    """Per-worker in-memory landing zone for incoming channel frames
    (the input-channel buffer of a DQ compute actor)."""

    def __init__(self, budget_bytes: int = 1 << 30):
        import threading
        self._frames: dict = {}           # channel -> [(DataFrame, bytes)]
        self.bytes = 0
        self.budget = budget_bytes
        self._mu = threading.Lock()

    def put(self, channel: str, df: pd.DataFrame, nbytes: int) -> None:
        with self._mu:
            if self.bytes + nbytes > self.budget:
                raise MemoryError(
                    f"exchange buffer over budget "
                    f"({self.bytes + nbytes} > {self.budget})")
            self._frames.setdefault(channel, []).append((df, nbytes))
            self.bytes += nbytes

    def take(self, channel: str) -> pd.DataFrame:
        """Drain and concatenate every frame of a channel."""
        with self._mu:
            frames = self._frames.pop(channel, [])
            self.bytes -= sum(nb for (_f, nb) in frames)
        if not frames:
            return pd.DataFrame()
        return pd.concat([f for (f, _nb) in frames], ignore_index=True)

    def drop(self, channel: str) -> None:
        with self._mu:
            frames = self._frames.pop(channel, None)
            if frames:
                self.bytes -= sum(nb for (_f, nb) in frames)
