"""Distributed two-phase commit across worker processes.

The r4 cluster routed a multi-worker INSERT as independent per-worker
statements — a crash between them left the cluster half-written. This
module is the cross-process commit protocol the reference runs through
its coordinator tablet + DataShard readsets
(`ydb/core/tx/coordinator/coordinator_impl.h:209`,
`datashard_outreadset.cpp`), collapsed to the router-as-coordinator
shape:

  PREPARE   every involved worker stages the statements in a held
            session and appends a durable `prepared {gtx, sqls}` record
            (logical logging — the statements re-execute on recovery);
  DECIDE    the router appends commit/abort to ITS durable decision log
            before telling anyone (the coordinator's plan-step log);
  COMMIT    workers append `decision`, apply the held session's commit
            (one local plan step), then append `done`;
  RESOLVE   a worker that crashed between prepare and done re-executes
            the logged statements when the router re-delivers a commit
            decision — UPSERT-style idempotence makes the re-execution
            safe whether or not the local commit had landed.

Journals are JSON-lines with fsync per record; a torn tail (crash mid
append) drops only the partial line.
"""

from __future__ import annotations

import json
import os
from typing import Optional


class DtxJournal:
    """Append-only prepared-transaction journal (worker side), and the
    decision log (router side) — same format, different record kinds.

    `sink` (a `cluster/replica.py` sink): every record ships SYNCHRONOUSLY
    to the standby after the local fsync, mirrored as a JSON-lines file
    under the standby root (this journal's basename). A lost router disk
    then no longer strands prepared workers in-doubt: boot a new router
    with `dtx_log=<standby>/<basename>` and `resolve_in_doubt()`
    re-delivers every logged decision (re-shipping a record after a
    crash-before-ack duplicates a line, which the `decisions()` /
    `in_doubt()` folds absorb — both are last-record-wins per gtx)."""

    def __init__(self, path: str, sink=None):
        self.path = path
        self.sink = sink
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def append(self, rec: dict) -> None:
        # torn-tail repair: a crash mid-append leaves a partial line with
        # no newline — terminating it BEFORE the new record keeps it
        # isolated (records() skips it) instead of merging it with this
        # append into one unparsable line that would hide every later
        # record
        needs_nl = False
        try:
            with open(self.path, "rb") as rf:
                rf.seek(-1, os.SEEK_END)
                needs_nl = rf.read(1) != b"\n"
        except (FileNotFoundError, OSError):
            pass
        with open(self.path, "ab") as f:
            if needs_nl:
                f.write(b"\n")
            f.write(json.dumps(rec).encode() + b"\n")
            f.flush()
            os.fsync(f.fileno())
        if self.sink is not None:
            # after the local fsync, before the caller proceeds: a
            # decision the protocol acts on is on both sides first
            self.sink.ship({"op": "jsonl_append",
                            "path": os.path.basename(self.path),
                            "data": rec})

    def records(self) -> list:
        try:
            with open(self.path) as f:
                out = []
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue         # torn line (crash mid-append)
                return out
        except FileNotFoundError:
            return []

    def in_doubt(self) -> dict:
        """{gtx: prepared record} for every prepared without done."""
        open_tx: dict = {}
        for rec in self.records():
            if rec["op"] == "prepared":
                open_tx[rec["gtx"]] = rec
            elif rec["op"] == "done":
                open_tx.pop(rec["gtx"], None)
        return open_tx

    def decisions(self) -> dict:
        """Router log fold: {gtx: "commit" | "abort"}."""
        out: dict = {}
        for rec in self.records():
            if rec["op"] == "decision":
                out[rec["gtx"]] = rec["decision"]
        return out
