"""Multi-node seam: a shard-aware SQL router over worker engine processes.

The minimal cross-host story SURVEY §5.8 calls for ("ICI intra-pod, gRPC
across"): N independent engine processes each own a shard of every
sharded table's rows; a router scatters rewritten SQL over the workers'
ordinary gRPC front (DCN seam — `ydb/core/grpc_services` +
TxProxy/Hive routing, radically simplified) and gathers:

  * DDL broadcasts to every worker;
  * INSERT routes each VALUES row by primary-key hash (the DataShard
    key-range analog, hash instead of ranges);
  * aggregating SELECTs decompose into per-worker PARTIAL queries
    (sum→sum, count→count, avg→sum+count, min/max→min/max) merged by a
    local merge query over the gathered partials — the same
    partial/final split the in-process mesh path uses, with SQL text as
    the wire format instead of pickled plans;
  * non-aggregating SELECTs push limit+offset down and re-sort the
    union.

Dimension tables can be created replicated (`replicated=` in
create_table/ShardedCluster.execute routing): every worker holds a full
copy, so joins against them stay worker-local (broadcast-join
co-location, as the reference expects for reference tables).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import pandas as pd

from ydb_tpu.sql import ast, parse, render

AGGS = ("sum", "count", "min", "max", "avg")


class ClusterError(Exception):
    pass


class _AggCollector:
    """Collect distinct aggregate calls in an expression tree and the
    substitution from each call to its merge-side expression."""

    def __init__(self):
        self.partial_items: list = []     # [(alias, ast expr)]
        self.merge_map: dict = {}         # FuncCall -> merge expr (ast)
        self.has_distinct = False         # seen a DISTINCT aggregate
        self._n = 0

    def _alias(self) -> str:
        self._n += 1
        return f"__a{self._n}"

    def visit(self, e):
        if isinstance(e, ast.FuncCall) and e.name in AGGS:
            if e in self.merge_map:
                return
            if e.distinct:
                # recorded, not raised: detection passes (_has_agg) walk
                # the same tree; only actual decomposition refuses
                self.has_distinct = True
                return
            if e.name == "avg":
                a_s, a_c = self._alias(), self._alias()
                self.partial_items.append(
                    (a_s, ast.FuncCall("sum", e.args)))
                self.partial_items.append(
                    (a_c, ast.FuncCall("count", e.args)))
                self.merge_map[e] = ast.BinOp(
                    "/",
                    ast.FuncCall("sum", (ast.Name((a_s,)),)),
                    ast.FuncCall("sum", (ast.Name((a_c,)),)))
                return
            a = self._alias()
            self.partial_items.append((a, e))
            merge_fn = {"sum": "sum", "count": "sum",
                        "min": "min", "max": "max"}[e.name]
            self.merge_map[e] = ast.FuncCall(merge_fn, (ast.Name((a,)),))
            return
        for f in getattr(e, "__dataclass_fields__", ()):
            v = getattr(e, f)
            if isinstance(v, tuple):
                for x in v:
                    if hasattr(x, "__dataclass_fields__"):
                        self.visit(x)
            elif hasattr(v, "__dataclass_fields__"):
                self.visit(v)


def _substitute(e, mapping: dict):
    """Replace subtrees by the mapping (dataclass equality), recursively."""
    if e in mapping:
        return mapping[e]
    if not hasattr(e, "__dataclass_fields__"):
        return e

    def rw(v):
        if isinstance(v, tuple):
            return tuple(rw(x) for x in v)
        if hasattr(v, "__dataclass_fields__"):
            return _substitute(v, mapping)
        return v
    try:
        return dataclasses.replace(
            e, **{f: rw(getattr(e, f)) for f in e.__dataclass_fields__})
    except TypeError:
        return e


def _has_agg(sel: ast.Select) -> bool:
    c = _AggCollector()
    for it in sel.items:
        c.visit(it.expr)
    if sel.having is not None:
        c.visit(sel.having)
    return bool(c.merge_map) or c.has_distinct or bool(sel.group_by)


def _contains_subquery(node) -> bool:
    """Any nested SELECT (CTE, derived table, IN/EXISTS/scalar subquery):
    shipping those verbatim would compute their aggregates shard-locally
    — silently wrong — so the router refuses them."""
    if isinstance(node, (ast.InSubquery, ast.Exists, ast.ScalarSubquery,
                         ast.SubqueryRef)):
        return True
    if isinstance(node, ast.Select) and node.ctes:
        return True
    for fname in getattr(node, "__dataclass_fields__", ()):
        v = getattr(node, fname)
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if isinstance(x, tuple):
                if any(_contains_subquery(y) for y in x
                       if hasattr(y, "__dataclass_fields__")):
                    return True
            elif hasattr(x, "__dataclass_fields__") \
                    and _contains_subquery(x):
                return True
    return False


def _table_names(rel) -> list:
    if isinstance(rel, ast.TableRef):
        return [rel.name]
    if isinstance(rel, ast.Join):
        return _table_names(rel.left) + _table_names(rel.right)
    return []


# -- shuffle-join plan helpers ---------------------------------------------


def _has_outer_join(rel) -> bool:
    if isinstance(rel, ast.Join):
        return (rel.kind not in ("inner", "cross")
                or _has_outer_join(rel.left) or _has_outer_join(rel.right))
    return False


def _relation_binds(rel) -> dict:
    """FROM bindings: {bind name (alias or table): table name}."""
    out: dict = {}
    if isinstance(rel, ast.TableRef):
        out[rel.alias or rel.name] = rel.name
    elif isinstance(rel, ast.Join):
        out.update(_relation_binds(rel.left))
        out.update(_relation_binds(rel.right))
    return out


def _collect_names(node, out=None) -> list:
    if out is None:
        out = []
    if isinstance(node, ast.Name):
        out.append(node.parts)
        return out
    for f in getattr(node, "__dataclass_fields__", ()):
        v = getattr(node, f)
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if isinstance(x, tuple):
                for y in x:
                    if hasattr(y, "__dataclass_fields__"):
                        _collect_names(y, out)
            elif hasattr(x, "__dataclass_fields__"):
                _collect_names(x, out)
    return out


def _attribute(parts: tuple, binds: dict, table_cols: dict):
    """Which TABLE a column reference binds to (None = unresolvable)."""
    if len(parts) == 2:
        t = binds.get(parts[0])
        return t
    hits = [t for t in set(binds.values())
            if parts[-1] in table_cols.get(t, ())]
    if len(hits) == 1:
        return hits[0]
    if len(hits) > 1:
        raise ClusterError(f"ambiguous column {parts[-1]!r} across "
                           f"{sorted(hits)} — qualify it")
    return None


def _conjuncts(e) -> list:
    if e is None:
        return []
    if isinstance(e, ast.BinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _join_ons(rel) -> list:
    if isinstance(rel, ast.Join):
        return (_conjuncts(rel.on) + _join_ons(rel.left)
                + _join_ons(rel.right))
    return []


def _expr_tables(e, binds: dict, table_cols: dict) -> set:
    out = set()
    for parts in _collect_names(e):
        t = _attribute(parts, binds, table_cols)
        if t is not None:
            out.add(t)
    return out


def _only_tables(e, allowed: set, binds: dict, table_cols: dict) -> bool:
    ts = _expr_tables(e, binds, table_cols)
    return bool(ts) and ts <= allowed


def _cross_equality(e, a: str, b: str, binds: dict, table_cols: dict):
    """`A.x = B.y` (either orientation) → (x, y); else None."""
    if not (isinstance(e, ast.BinOp) and e.op == "="
            and isinstance(e.left, ast.Name)
            and isinstance(e.right, ast.Name)):
        return None
    lt = _attribute(e.left.parts, binds, table_cols)
    rt = _attribute(e.right.parts, binds, table_cols)
    if lt == a and rt == b:
        return (e.left.parts[-1], e.right.parts[-1])
    if lt == b and rt == a:
        return (e.right.parts[-1], e.left.parts[-1])
    return None


def _rewrite_relation(rel, temp_of: dict):
    """Swap sharded TableRefs for their shuffle-temp names, keeping the
    original bind name as the alias so every column reference resolves
    unchanged."""
    if isinstance(rel, ast.TableRef):
        if rel.name in temp_of:
            return ast.TableRef(temp_of[rel.name],
                                rel.alias or rel.name)
        return rel
    if isinstance(rel, ast.Join):
        return dataclasses.replace(
            rel, left=_rewrite_relation(rel.left, temp_of),
            right=_rewrite_relation(rel.right, temp_of))
    return rel


class ShardedCluster:
    """Router over worker gRPC endpoints (one engine process per shard)."""

    def __init__(self, endpoints: list, merge_engine=None,
                 dtx_log: Optional[str] = None):
        from ydb_tpu.query import QueryEngine
        from ydb_tpu.server import Client
        self.workers = [Client(ep) for ep in endpoints]
        # local engine used for the merge stage (schema-free: merge runs
        # over the gathered partial frame registered as a temp table)
        self.engine = merge_engine or QueryEngine(block_rows=1 << 16)
        self.replicated: set = set()        # table names on every worker
        self.key_columns: dict = {}         # table -> [pk col]
        # durable coordinator decision log for cross-worker 2PC
        # (cluster/dtx.py). None = single-statement routing only.
        from ydb_tpu.cluster.dtx import DtxJournal
        self.dtx_log = DtxJournal(dtx_log) if dtx_log else None

    # -- DDL / DML ----------------------------------------------------------

    def execute(self, sql: str, replicated: bool = False):
        """DDL: broadcast. INSERT ... VALUES: route rows by pk hash
        (replicated tables broadcast rows instead)."""
        stmt = parse(sql)
        if isinstance(stmt, ast.Insert):
            return self._route_insert(stmt, sql)
        for w in self.workers:
            w.execute(sql)
        if isinstance(stmt, ast.CreateTable):
            # remember pk for insert routing
            self.key_columns[stmt.name] = list(stmt.primary_key)
            if replicated:
                self.replicated.add(stmt.name)
        return {"ok": True}

    def _route_insert(self, stmt: ast.Insert, sql: str):
        import zlib

        from ydb_tpu.utils.hashing import splitmix64
        if stmt.query is not None and stmt.table not in self.replicated:
            raise ClusterError(
                "INSERT ... SELECT into a sharded table is not supported "
                "(broadcasting would duplicate every row per worker)")
        if stmt.table in self.replicated:
            if self.dtx_log is not None and stmt.mode == "upsert" \
                    and len(self.workers) > 1:
                # replicated UPSERT: all-or-nothing across every copy
                return self._commit_2pc([(w, [sql])
                                         for w in self.workers])
            for w in self.workers:
                w.execute(sql)
            return {"ok": True}
        pk = self.key_columns.get(stmt.table)
        if not pk:
            raise ClusterError(f"unknown sharded table {stmt.table!r}")
        if not stmt.columns:
            raise ClusterError("routed inserts need an explicit column "
                               "list (INSERT INTO t (cols...) VALUES ...)")
        ki = stmt.columns.index(pk[0])
        nw = len(self.workers)
        per: list = [[] for _ in range(nw)]
        for row in stmt.rows:
            v = row[ki].value if isinstance(row[ki], ast.Literal) else None
            if v is None:
                raise ClusterError("insert routing needs literal pk values")
            # deterministic across router processes (builtin hash() is
            # PYTHONHASHSEED-randomized). Only int/str pk literals route:
            # a float would silently truncate through the int64 hash
            # (10.5 and 10 co-routing — ADVICE r4) and bool is almost
            # certainly a mistyped pk.
            if isinstance(v, str):
                h = zlib.crc32(v.encode())
            elif isinstance(v, int) and not isinstance(v, bool):
                h = int(splitmix64(np, np.array([v], np.int64))[0])
            else:
                raise ClusterError(
                    f"insert routing needs int or string pk literals, "
                    f"got {type(v).__name__} ({v!r})")
            per[h % nw].append(row)
        cols = ", ".join(stmt.columns)
        per_sql = []
        for w, rows in zip(self.workers, per):
            if not rows:
                per_sql.append(None)
                continue
            vals = ", ".join(
                "(" + ", ".join(render.expr(v) for v in row) + ")"
                for row in rows)
            per_sql.append(f"{stmt.mode} into {stmt.table} ({cols}) "
                           f"values {vals}")
        touched = [(w, s) for (w, s) in zip(self.workers, per_sql)
                   if s is not None]
        # 2PC applies to UPSERT only: crash recovery RE-EXECUTES the
        # journaled statements, which is exactly-once only under upsert
        # semantics (a replayed plain INSERT into a column table would
        # append duplicates)
        if len(touched) > 1 and self.dtx_log is not None \
                and stmt.mode == "upsert":
            return self._commit_2pc([(w, [s]) for (w, s) in touched])
        for (w, s) in touched:
            w.execute(s)
        return {"ok": True}

    def _commit_2pc(self, work: list) -> dict:
        """Two-phase commit of per-worker statement lists: prepare all →
        durable decision → decide all (cluster/dtx.py; the coordinator
        plan-step protocol, `coordinator_impl.h:209`). A worker that
        dies after the decision is healed later by `resolve_in_doubt`
        re-delivering the logged decision."""
        import uuid
        gtx = uuid.uuid4().hex
        self.dtx_log.append({"op": "begin", "gtx": gtx,
                             "workers": [w.endpoint for (w, _s) in work]})
        prepared = []
        failed = None
        for (w, sqls) in work:
            try:
                w.tx_prepare(gtx, sqls)
                prepared.append(w)
            except Exception as e:           # noqa: BLE001
                failed = e
                break
        decision = "abort" if failed is not None else "commit"
        self.dtx_log.append({"op": "decision", "gtx": gtx,
                             "decision": decision})
        outcome_ok = True
        crash_points = getattr(self, "dtx_test_crash", {})
        for w in prepared:
            try:
                extra = {}
                cp = crash_points.get(w.endpoint)
                if cp:
                    extra["crash_point"] = cp
                w.tx_decide(gtx, decision, **extra)
            except Exception:                # noqa: BLE001
                outcome_ok = False           # healed by resolve_in_doubt
        if failed is not None:
            raise ClusterError(f"2PC aborted: {failed}")
        self.dtx_log.append({"op": "done", "gtx": gtx})
        return {"ok": True, "gtx": gtx, "healed_later": not outcome_ok}

    def resolve_in_doubt(self) -> dict:
        """Re-deliver durable decisions for transactions a worker holds
        in doubt (post-restart recovery). Unknown gtx (prepared on the
        worker, no decision logged — the router died first) resolve to
        abort: presumed-abort, the coordinator never promised commit."""
        if self.dtx_log is None:
            return {"resolved": 0}
        decisions = self.dtx_log.decisions()
        n = 0
        unreachable = []
        for w in self.workers:
            # heal the reachable subset: one down worker must not block
            # every other worker's recovery
            try:
                for gtx in w.tx_in_doubt():
                    w.tx_resolve(gtx, decisions.get(gtx, "abort"))
                    n += 1
            except Exception as e:           # noqa: BLE001
                unreachable.append((w.endpoint, str(e)[:80]))
        return {"resolved": n, "unreachable": unreachable}

    # -- SELECT -------------------------------------------------------------

    def query(self, sql: str) -> pd.DataFrame:
        from ydb_tpu.query.window import has_window
        stmt = parse(sql)
        if not isinstance(stmt, ast.Select):
            raise ClusterError("the router distributes SELECT; use "
                               "execute() for DDL/DML")
        if has_window(stmt):
            raise ClusterError("window functions are not distributable "
                               "over shards yet (per-shard windows would "
                               "be silently wrong)")
        if _contains_subquery(stmt):
            raise ClusterError("CTEs/subqueries are not distributable "
                               "over shards yet (their aggregates would "
                               "compute shard-locally)")
        # two sharded tables: hash-shuffle both sides worker<->worker so
        # the join runs co-partitioned (the DQ HashShuffle connection,
        # `dq_tasks_graph.h:43` / `dq_output_channel.cpp:31`); more than
        # two still refuses (needs a multi-stage graph)
        sharded = [n for n in _table_names(stmt.relation)
                   if n not in self.replicated and n in self.key_columns]
        if len(set(sharded)) == 2:
            return self._shuffle_join_query(stmt, sorted(set(sharded)))
        if len(set(sharded)) > 2:
            raise ClusterError(
                f"joining {len(set(sharded))} sharded tables "
                f"({sorted(set(sharded))}) is not supported yet — at most "
                "two shuffle; create dimensions with replicated=True")
        if _has_agg(stmt):
            return self._scatter_agg(stmt)
        return self._scatter_scan(stmt)

    # -- sharded x sharded shuffle join ------------------------------------

    def _table_columns(self, table: str) -> list:
        """Column names of a worker table (cached; schema probe)."""
        cache = self.__dict__.setdefault("_col_cache", {})
        cols = cache.get(table)
        if cols is None:
            resp = self.workers[0].execute(f"select * from {table} limit 0")
            cols = cache[table] = list(resp["columns"])
        return cols

    def _shuffle_join_query(self, sel: ast.Select,
                            sharded: list) -> pd.DataFrame:
        """Join two sharded tables with a worker<->worker hash shuffle:

          stage 1  each worker projects its shard of A and B (single-
                   table WHERE conjuncts pushed down) and ships each
                   row to hash(join key) % n_workers over the exchange
                   channels — after the barrier every worker holds
                   co-partitioned rows of BOTH tables;
          stage 2  the channels materialize as transient tables aliased
                   to the original names, and the ORIGINAL query —
                   relation rewritten — runs through the normal
                   scatter/merge paths (now a worker-local join).

        Neither worker ever holds the other's full shard set, let alone
        a replicated build — the contract the reference's ShuffleJoin
        exists for (`dq_opt_join.cpp`)."""
        import uuid

        if any(isinstance(it.expr, ast.Star) for it in sel.items):
            raise ClusterError("SELECT * is not supported in a shuffle "
                               "join — name the columns")
        if _has_outer_join(sel.relation):
            # the shuffle drops NULL join keys (inner semantics); a
            # LEFT/FULL join would silently lose its NULL-extended rows
            raise ClusterError("outer joins between two sharded tables "
                               "are not supported yet (inner only)")
        binds = _relation_binds(sel.relation)       # bind name -> table
        # column attribution for every Name in the statement
        table_cols = {t: self._table_columns(t) for t in
                      {tbl for tbl in binds.values()}}
        refs = _collect_names(sel)
        used: dict = {t: set() for t in binds.values()}
        for parts in refs:
            t = _attribute(parts, binds, table_cols)
            if t is not None:
                used[t].add(parts[-1])

        # join key: the first WHERE/ON equality linking the two sharded
        # tables (additional equalities stay as local filters — rows
        # co-partitioned by the first key still satisfy them locally)
        conjs = _conjuncts(sel.where) + _join_ons(sel.relation)
        a, b = sharded
        key_a = key_b = None
        for c in conjs:
            pair = _cross_equality(c, a, b, binds, table_cols)
            if pair is not None:
                key_a, key_b = pair
                break
        if key_a is None:
            raise ClusterError(
                f"no equality join condition between sharded tables "
                f"{a!r} and {b!r} — a cross join cannot shuffle")
        used[a].add(key_a)
        used[b].add(key_b)

        # stage 1: project + push down single-table conjuncts; every
        # worker partitions its shard of both tables over the channels
        from concurrent.futures import ThreadPoolExecutor
        tag = uuid.uuid4().hex[:10]
        endpoints = [w.endpoint for w in self.workers]
        plans = {}
        for t, key in ((a, key_a), (b, key_b)):
            alias = next(al for al, tbl in binds.items() if tbl == t)
            local = [c for c in _conjuncts(sel.where)
                     if _only_tables(c, {t}, binds, table_cols)]
            where = None
            for c in local:
                where = c if where is None else ast.BinOp("and", where, c)
            items = [ast.SelectItem(ast.Name((alias, col)), col)
                     for col in sorted(used[t])]
            stage = ast.Select(items=items,
                               relation=ast.TableRef(t, alias),
                               where=where)
            plans[t] = (render.select(stage), key, f"__xch_{tag}_{t}")

        temp_of = {t: f"__xj_{tag}_{t}" for t in sharded}
        try:
            for t, (sql, key, channel) in plans.items():
                with ThreadPoolExecutor(
                        max_workers=len(self.workers)) as pool:
                    resps = list(pool.map(
                        lambda w: w.shuffle_write(sql, key, channel,
                                                  endpoints),
                        self.workers))
                dtypes: dict = {}
                for r in resps:
                    dtypes.update(r.get("dtypes") or {})
                cols = [(c, dtypes.get(c, "float64"))
                        for c in sorted(used[t])]
                # barrier: every producer finished before any consumer
                # drains its channel (the stage boundary of the graph)
                with ThreadPoolExecutor(
                        max_workers=len(self.workers)) as pool:
                    list(pool.map(
                        lambda w: w.channel_open(channel, temp_of[t],
                                                 columns=cols),
                        self.workers))
            final = dataclasses.replace(
                sel, relation=_rewrite_relation(sel.relation, temp_of))
            return self.query(render.select(final))
        finally:
            for w in self.workers:
                try:
                    w.channel_close(tables=list(temp_of.values()),
                                    channels=[ch for (_s, _k, ch)
                                              in plans.values()])
                except Exception:            # noqa: BLE001 — best effort
                    pass

    def _gather(self, worker_sql: str) -> pd.DataFrame:
        """Scatter one SQL text over every worker CONCURRENTLY (they are
        separate processes — a sequential loop would serialize the very
        work the router distributes) and union the frames."""
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=len(self.workers)) as pool:
            resps = list(pool.map(lambda w: w.execute(worker_sql),
                                  self.workers))
        frames = [pd.DataFrame(r["rows"], columns=r["columns"])
                  for r in resps]
        return pd.concat(frames, ignore_index=True)

    def _scatter_scan(self, sel: ast.Select) -> pd.DataFrame:
        from ydb_tpu.query.window import apply_order_limit
        lim = None if sel.limit is None else sel.limit + (sel.offset or 0)
        worker_sel = dataclasses.replace(sel, limit=lim, offset=None)
        df = self._gather(render.select(worker_sel))
        if sel.distinct:
            # per-shard DISTINCT leaves cross-shard duplicates
            df = df.drop_duplicates(ignore_index=True)
        # ORDER BY the pre-alias expression: rewrite to the output alias
        # (the merge sorts the gathered frame by column name)
        alias_of = {it.expr: it.alias for it in sel.items if it.alias}
        order = [dataclasses.replace(o, expr=ast.Name((alias_of[o.expr],)))
                 if o.expr in alias_of else o for o in sel.order_by]
        try:
            return apply_order_limit(df, order, sel.limit, sel.offset)
        except ValueError as e:
            raise ClusterError(str(e)) from e

    def _scatter_agg(self, sel: ast.Select) -> pd.DataFrame:
        if sel.distinct or sel.ctes:
            raise ClusterError("DISTINCT/CTE SELECTs are not "
                               "distributable over shards yet")
        cd = self._try_count_distinct(sel)
        if cd is not None:
            return cd
        col = _AggCollector()
        for it in sel.items:
            col.visit(it.expr)
        if sel.having is not None:
            col.visit(sel.having)
        for o in sel.order_by:
            col.visit(o.expr)
        if col.has_distinct:
            # the distinct-only shape was handled above; mixtures of
            # DISTINCT and plain aggregates need a per-agg shuffle plan
            raise ClusterError(
                "mixing DISTINCT aggregates with other aggregates is "
                "not distributable over shards yet")

        # group keys become named partial columns
        gmap = {}
        gitems = []
        for i, g in enumerate(sel.group_by):
            a = f"__g{i}"
            gmap[g] = ast.Name((a,))
            gitems.append(ast.SelectItem(g, a))
        items = gitems + [ast.SelectItem(e, a)
                          for (a, e) in col.partial_items]
        worker_sel = ast.Select(
            items=items, relation=sel.relation, where=sel.where,
            group_by=list(sel.group_by), ctes=list(sel.ctes))
        partial = self._gather(render.select(worker_sel))

        # merge locally: substitute agg calls and group exprs, run over
        # the gathered frame as a temp table
        sub = {**col.merge_map, **gmap}
        def _label(it, i):
            if it.alias:
                return it.alias
            if isinstance(it.expr, ast.Name):     # single-node naming
                return it.expr.parts[-1]
            return f"column{i}"

        mitems = [ast.SelectItem(_substitute(it.expr, sub), _label(it, i))
                  for i, it in enumerate(sel.items)]
        morder = [dataclasses.replace(o, expr=_substitute(o.expr, sub))
                  for o in sel.order_by]
        mhaving = _substitute(sel.having, sub) \
            if sel.having is not None else None
        mgroup = [gmap[g] for g in sel.group_by]

        from ydb_tpu.core.block import HostBlock
        eng = self.engine
        block = HostBlock.from_pandas(partial)
        return self._merge_over_temp(block, sel, mitems, mgroup, mhaving,
                                     morder)

    def _try_count_distinct(self, sel: ast.Select):
        """COUNT(DISTINCT x) distribution (the two-level distinct
        shuffle): supported when every aggregate is a distinct count —
        workers return SELECT DISTINCT keys+args, the merge counts.
        Returns None when the shape doesn't apply."""
        aggs = []
        for it in sel.items:
            if isinstance(it.expr, ast.FuncCall) \
                    and it.expr.name in AGGS:
                if not (it.expr.name == "count" and it.expr.distinct):
                    return None
                aggs.append(it)
            elif it.expr not in sel.group_by:
                return None
        if not aggs:
            return None
        gitems = [ast.SelectItem(g, f"__g{i}")
                  for i, g in enumerate(sel.group_by)]
        ditems = [ast.SelectItem(a.expr.args[0], f"__d{k}")
                  for k, a in enumerate(aggs)]
        worker_sel = ast.Select(items=gitems + ditems,
                                relation=sel.relation, where=sel.where,
                                distinct=True)
        partial = self._gather(render.select(worker_sel)) \
            .drop_duplicates(ignore_index=True)     # cross-shard dups
        gmap = {g: ast.Name((f"__g{i}",))
                for i, g in enumerate(sel.group_by)}
        mitems, k = [], 0
        for i, it in enumerate(sel.items):
            if it in aggs:
                e = ast.FuncCall("count", (ast.Name((f"__d{k}",)),),
                                 distinct=True)
                k += 1
            else:
                e = _substitute(it.expr, gmap)
            alias = it.alias or (it.expr.parts[-1]
                                 if isinstance(it.expr, ast.Name)
                                 else f"column{i}")
            mitems.append(ast.SelectItem(e, alias))
        morder = [dataclasses.replace(o, expr=_substitute(o.expr, gmap))
                  for o in sel.order_by]
        from ydb_tpu.core.block import HostBlock
        block = HostBlock.from_pandas(partial)
        return self._merge_over_temp(block, sel, mitems,
                                     [gmap[g] for g in sel.group_by],
                                     None, morder)

    def _merge_over_temp(self, block, sel, mitems, mgroup, mhaving,
                         morder) -> pd.DataFrame:
        eng = self.engine
        temps: list = []
        try:
            tname = eng._register_temp(block, temps)
            merge_sel = ast.Select(
                items=mitems, relation=ast.TableRef(tname),
                group_by=mgroup, having=mhaving, order_by=morder,
                limit=sel.limit, offset=sel.offset)
            return eng.query(render.select(merge_sel))
        finally:
            for tn in temps:
                if eng.catalog.has(tn):
                    eng.catalog.drop_table(tn)
