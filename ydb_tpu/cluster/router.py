"""Multi-node seam: a shard-aware SQL router over worker engine processes.

The minimal cross-host story SURVEY §5.8 calls for ("ICI intra-pod, gRPC
across"): N independent engine processes each own a shard of every
sharded table's rows; a router scatters work over the workers' ordinary
gRPC front (DCN seam — `ydb/core/grpc_services` + TxProxy/Hive routing,
radically simplified) and gathers:

  * DDL broadcasts to every worker;
  * INSERT routes each VALUES row by primary-key hash (the DataShard
    key-range analog, hash instead of ranges), with two-phase commit for
    multi-worker UPSERTs (`cluster/dtx.py`);
  * every SELECT lowers to a DQ STAGE GRAPH (`ydb_tpu/dq/`): partial/
    merge aggregation, two-level distinct, order/limit scatter scans and
    sharded×sharded hash-shuffle joins are all graph lowerings executed
    by one task runner over the workers — the per-shape scatter/gather
    rewrites this module used to hand-roll live in `dq/lower.py` now.

Dimension tables can be created replicated (`replicated=` in
create_table/ShardedCluster.execute routing): every worker holds a full
copy, so joins against them stay worker-local (broadcast-join
co-location, as the reference expects for reference tables).

Workers may be gRPC endpoints ("host:port" → `server.Client`) or any
object exposing the worker surface directly — `dq.runner.LocalWorker`
wraps an in-process engine, making single-process execution the
1-worker degenerate case of the same graph path.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pandas as pd

from ydb_tpu.dq.lower import AGGS  # noqa: F401  (back-compat export)
from ydb_tpu.sql import ast, parse, render


class ClusterError(Exception):
    pass


class ShardedCluster:
    """Router over worker gRPC endpoints (one engine process per shard)."""

    def __init__(self, endpoints: list, merge_engine=None,
                 dtx_log: Optional[str] = None, dtx_replica=None):
        from ydb_tpu.query import QueryEngine
        from ydb_tpu.server import Client
        self.workers = [ep if hasattr(ep, "execute") else Client(ep)
                        for ep in endpoints]
        # local engine used for the merge stage (schema-free: merge runs
        # over the gathered partial frame registered as a temp table)
        self.engine = merge_engine or QueryEngine(block_rows=1 << 16)
        self.replicated: set = set()        # table names on every worker
        self.key_columns: dict = {}         # table -> [pk col]
        # durable coordinator decision log for cross-worker 2PC
        # (cluster/dtx.py). None = single-statement routing only.
        # `dtx_replica` (a replica sink / directory / endpoint,
        # cluster/replica.py) mirrors every decision record to a standby
        # so a lost router disk cannot strand prepared workers in-doubt.
        from ydb_tpu.cluster.dtx import DtxJournal
        sink = None
        if dtx_replica is not None:
            from ydb_tpu.cluster.replica import make_sink
            sink = make_sink(dtx_replica)
        self.dtx_log = DtxJournal(dtx_log, sink=sink) if dtx_log else None

    # -- DDL / DML ----------------------------------------------------------

    def execute(self, sql: str, replicated: bool = False):
        """DDL: broadcast. INSERT ... VALUES: route rows by pk hash
        (replicated tables broadcast rows instead)."""
        stmt = parse(sql)
        if isinstance(stmt, ast.Insert):
            return self._route_insert(stmt, sql)
        for w in self.workers:
            w.execute(sql)
        if isinstance(stmt, ast.CreateTable):
            # remember pk for insert routing
            self.key_columns[stmt.name] = list(stmt.primary_key)
            if replicated:
                self.replicated.add(stmt.name)
        return {"ok": True}

    def _route_insert(self, stmt: ast.Insert, sql: str):
        import zlib

        from ydb_tpu.utils.hashing import splitmix64
        if stmt.query is not None and stmt.table not in self.replicated:
            raise ClusterError(
                "INSERT ... SELECT into a sharded table is not supported "
                "(broadcasting would duplicate every row per worker)")
        if stmt.table in self.replicated:
            if self.dtx_log is not None and stmt.mode == "upsert" \
                    and len(self.workers) > 1:
                # replicated UPSERT: all-or-nothing across every copy
                return self._commit_2pc([(w, [sql])
                                         for w in self.workers])
            for w in self.workers:
                w.execute(sql)
            return {"ok": True}
        pk = self.key_columns.get(stmt.table)
        if not pk:
            raise ClusterError(f"unknown sharded table {stmt.table!r}")
        if not stmt.columns:
            raise ClusterError("routed inserts need an explicit column "
                               "list (INSERT INTO t (cols...) VALUES ...)")
        ki = stmt.columns.index(pk[0])
        nw = len(self.workers)
        per: list = [[] for _ in range(nw)]
        for row in stmt.rows:
            v = row[ki].value if isinstance(row[ki], ast.Literal) else None
            if v is None:
                raise ClusterError("insert routing needs literal pk values")
            # deterministic across router processes (builtin hash() is
            # PYTHONHASHSEED-randomized). Only int/str pk literals route:
            # a float would silently truncate through the int64 hash
            # (10.5 and 10 co-routing — ADVICE r4) and bool is almost
            # certainly a mistyped pk.
            if isinstance(v, str):
                h = zlib.crc32(v.encode())
            elif isinstance(v, int) and not isinstance(v, bool):
                h = int(splitmix64(np, np.array([v], np.int64))[0])
            else:
                raise ClusterError(
                    f"insert routing needs int or string pk literals, "
                    f"got {type(v).__name__} ({v!r})")
            per[h % nw].append(row)
        cols = ", ".join(stmt.columns)
        per_sql = []
        for w, rows in zip(self.workers, per):
            if not rows:
                per_sql.append(None)
                continue
            vals = ", ".join(
                "(" + ", ".join(render.expr(v) for v in row) + ")"
                for row in rows)
            per_sql.append(f"{stmt.mode} into {stmt.table} ({cols}) "
                           f"values {vals}")
        touched = [(w, s) for (w, s) in zip(self.workers, per_sql)
                   if s is not None]
        # 2PC applies to UPSERT only: crash recovery RE-EXECUTES the
        # journaled statements, which is exactly-once only under upsert
        # semantics (a replayed plain INSERT into a column table would
        # append duplicates)
        if len(touched) > 1 and self.dtx_log is not None \
                and stmt.mode == "upsert":
            return self._commit_2pc([(w, [s]) for (w, s) in touched])
        for (w, s) in touched:
            w.execute(s)
        return {"ok": True}

    def _commit_2pc(self, work: list) -> dict:
        """Two-phase commit of per-worker statement lists: prepare all →
        durable decision → decide all (cluster/dtx.py; the coordinator
        plan-step protocol, `coordinator_impl.h:209`). A worker that
        dies after the decision is healed later by `resolve_in_doubt`
        re-delivering the logged decision."""
        import uuid
        gtx = uuid.uuid4().hex
        self.dtx_log.append({"op": "begin", "gtx": gtx,
                             "workers": [w.endpoint for (w, _s) in work]})
        prepared = []
        failed = None
        for (w, sqls) in work:
            try:
                w.tx_prepare(gtx, sqls)
                prepared.append(w)
            except Exception as e:           # noqa: BLE001
                failed = e
                break
        decision = "abort" if failed is not None else "commit"
        self.dtx_log.append({"op": "decision", "gtx": gtx,
                             "decision": decision})
        outcome_ok = True
        crash_points = getattr(self, "dtx_test_crash", {})
        for w in prepared:
            try:
                extra = {}
                cp = crash_points.get(w.endpoint)
                if cp:
                    extra["crash_point"] = cp
                w.tx_decide(gtx, decision, **extra)
            except Exception:                # noqa: BLE001
                outcome_ok = False           # healed by resolve_in_doubt
        if failed is not None:
            raise ClusterError(f"2PC aborted: {failed}")
        self.dtx_log.append({"op": "done", "gtx": gtx})
        return {"ok": True, "gtx": gtx, "healed_later": not outcome_ok}

    def resolve_in_doubt(self) -> dict:
        """Re-deliver durable decisions for transactions a worker holds
        in doubt (post-restart recovery). Unknown gtx (prepared on the
        worker, no decision logged — the router died first) resolve to
        abort: presumed-abort, the coordinator never promised commit."""
        if self.dtx_log is None:
            return {"resolved": 0}
        decisions = self.dtx_log.decisions()
        n = 0
        unreachable = []
        for w in self.workers:
            # heal the reachable subset: one down worker must not block
            # every other worker's recovery
            try:
                for gtx in w.tx_in_doubt():
                    w.tx_resolve(gtx, decisions.get(gtx, "abort"))
                    n += 1
            except Exception as e:           # noqa: BLE001
                unreachable.append((w.endpoint, str(e)[:80]))
        return {"resolved": n, "unreachable": unreachable}

    # -- SELECT (DQ stage-graph path) ---------------------------------------

    def _table_columns(self, table: str) -> list:
        """Column names of a worker table (cached; schema probe)."""
        cache = self.__dict__.setdefault("_col_cache", {})
        cols = cache.get(table)
        if cols is None:
            resp = self.workers[0].execute(f"select * from {table} limit 0")
            cols = cache[table] = list(resp["columns"])
        return cols

    def _lower(self, stmt: ast.Select):
        from ydb_tpu.dq.lower import DqLowerError, DqTopology, lower_select
        topo = DqTopology(n_workers=len(self.workers),
                          replicated=set(self.replicated),
                          key_columns=dict(self.key_columns))
        try:
            return lower_select(stmt, topo, self._table_columns)
        except DqLowerError as e:
            raise ClusterError(str(e)) from e

    def plan(self, sql: str):
        """Lower a SELECT to its DQ stage graph without running it
        (EXPLAIN for the distributed plan)."""
        stmt = parse(sql)
        if not isinstance(stmt, ast.Select):
            raise ClusterError("only SELECT lowers to a stage graph")
        return self._lower(stmt)

    def query(self, sql: str) -> pd.DataFrame:
        """Distribute one SELECT: lower to a StageGraph, execute it with
        the task runner (one task per (stage, worker), channels between
        stages), merge router-side. The whole graph runs under ONE trace
        on the merge engine's tracer — worker task spans propagate back
        over the DqRunTask RPCs and assemble into a single cross-worker
        span tree (`engine.last_trace`, `.sys/query_profiles`).

        `EXPLAIN ANALYZE <select>` returns the distributed profile: the
        stage graph, per-(stage, worker) task stats (rows/bytes/frames/
        waits) and the assembled span tree, as a one-column frame."""
        stmt = parse(sql)
        if isinstance(stmt, ast.Explain):
            if not isinstance(stmt.query, ast.Select):
                raise ClusterError("EXPLAIN distributes SELECT only")
            return self._explain(stmt)
        if not isinstance(stmt, ast.Select):
            raise ClusterError("the router distributes SELECT; use "
                               "execute() for DDL/DML")
        df, _runner = self._run_traced(stmt, sql)
        return df

    def _run_traced(self, stmt: ast.Select, sql: str,
                    force_trace: bool = False, graph=None):
        import time as _time

        from ydb_tpu.dq.runner import DqError, DqTaskRunner
        from ydb_tpu.utils.metrics import GLOBAL_HIST
        if graph is None:
            graph = self._lower(stmt)
        runner = DqTaskRunner(self.workers, self.engine)
        eng = self.engine
        sampled = force_trace or eng._sample_decision(sql)
        eng.tracer.begin_trace(sampled=sampled)
        t0 = _time.perf_counter()
        rows_out = None
        try:
            with eng.tracer.span("dq-query", sql=sql[:60],
                                 workers=len(self.workers),
                                 stages=len(graph.stages)):
                df = runner.run(graph)
            rows_out = len(df)
            return df, runner
        except DqError as e:
            raise ClusterError(str(e)) from e
        finally:
            total_ms = (_time.perf_counter() - t0) * 1000.0
            if rows_out is not None:
                # successes only — the local path records latency in
                # _finish_stats, which a failed statement never reaches;
                # a timed-out DQ run would otherwise inject a 600 s
                # timeout artifact into p99/max
                GLOBAL_HIST.observe("query/latency_ms", total_ms)
                eng._note_slow(sql, total_ms, "dq-select")
            spans = eng.tracer.end_trace()
            if spans:
                eng.last_trace = spans
                # the DQ wall/rows pass explicitly: last_stats only
                # covers the router-merge statement (or a previous one)
                eng._record_profile(
                    sql, spans, stage_stats=runner.stage_stats,
                    total_ms=round(total_ms, 3),
                    rows_out=rows_out or 0,
                    # a failed run must not masquerade as a successful
                    # empty-result query (the local path marks these
                    # "error" the same way)
                    kind="dq-select" if rows_out is not None
                    else "dq-error")

    def _explain(self, stmt: ast.Explain) -> pd.DataFrame:
        """Distributed EXPLAIN [ANALYZE]: the stage graph, and with
        ANALYZE the per-stage/per-channel profile of an actual run."""
        graph = self._lower(stmt.query)
        lines = [f"DQ stage graph: {len(graph.stages)} stages, "
                 f"{len(graph.channels)} channels, "
                 f"{len(self.workers)} workers"]
        for stage in graph.stages:
            lines.append(f"  stage {stage.id} on={stage.on} "
                         f"in={list(stage.inputs)} "
                         f"out={list(stage.outputs)}")
        if not stmt.analyze:
            return pd.DataFrame({"plan": lines})
        # run the SAME lowered graph the listing above describes —
        # re-lowering could diverge from the plan this output claims
        # to profile (and pays the lowering twice)
        df, runner = self._run_traced(stmt.query,
                                      render.select(stmt.query),
                                      force_trace=True, graph=graph)
        lines.append(f"-- rows out: {len(df)}")
        lines.append("-- stage stats (per task):")
        for r in runner.stage_stats:
            lines.append(
                f"  {r['stage']}@{r['worker']}: rows {r['rows']} | "
                f"bytes {r['bytes']} | frames {r['frames']} | "
                f"exec {r['exec_ms']:.1f}ms | flush {r['flush_ms']:.1f}ms"
                f" | input-wait {r['input_wait_ms']:.1f}ms | "
                f"backpressure {r['backpressure_wait_ms']:.1f}ms | "
                f"attempts {r['attempts']}")
        tr = self.engine.tracer.render(self.engine.last_trace)
        if tr:
            lines += ["-- trace:"] + tr.split("\n")
        return pd.DataFrame({"plan": lines})
