"""Multi-node seam: a shard-aware SQL router over worker engine processes.

The minimal cross-host story SURVEY §5.8 calls for ("ICI intra-pod, gRPC
across"): N independent engine processes each own a shard of every
sharded table's rows; a router scatters work over the workers' ordinary
gRPC front (DCN seam — `ydb/core/grpc_services` + TxProxy/Hive routing,
radically simplified) and gathers:

  * DDL broadcasts to every worker;
  * INSERT routes each VALUES row by primary-key hash (the DataShard
    key-range analog, hash instead of ranges), with two-phase commit for
    multi-worker UPSERTs (`cluster/dtx.py`);
  * every SELECT lowers to a DQ STAGE GRAPH (`ydb_tpu/dq/`): partial/
    merge aggregation, two-level distinct, order/limit scatter scans and
    sharded×sharded hash-shuffle joins are all graph lowerings executed
    by one task runner over the workers — the per-shape scatter/gather
    rewrites this module used to hand-roll live in `dq/lower.py` now.

Dimension tables can be created replicated (`replicated=` in
create_table/ShardedCluster.execute routing): every worker holds a full
copy, so joins against them stay worker-local (broadcast-join
co-location, as the reference expects for reference tables).

Workers may be gRPC endpoints ("host:port" → `server.Client`) or any
object exposing the worker surface directly — `dq.runner.LocalWorker`
wraps an in-process engine, making single-process execution the
1-worker degenerate case of the same graph path.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pandas as pd

from ydb_tpu.dq.lower import AGGS  # noqa: F401  (back-compat export)
from ydb_tpu.sql import ast, parse, render


class ClusterError(Exception):
    pass


class ShardedCluster:
    """Router over worker gRPC endpoints (one engine process per shard)."""

    def __init__(self, endpoints: list, merge_engine=None,
                 dtx_log: Optional[str] = None, dtx_replica=None,
                 hive=None, failover_rounds: int = 1):
        """`hive`: a `ydb_tpu.hive.Hive` control plane. When attached,
        the worker list is no longer static: each query consults the
        Hive's placement (alive, non-stale workers), a transport-dead
        worker triggers lease expiry + shard re-placement (the Hive's
        adopt hook replays the shard's standby image onto a survivor),
        and the statement re-lowers onto the surviving placement — up to
        `failover_rounds` times — instead of erroring out."""
        import threading
        from ydb_tpu.query import QueryEngine
        from ydb_tpu.server import Client
        self.workers = [ep if hasattr(ep, "execute")     # guarded-by: _fo_mu
                        else Client(ep)
                        for ep in endpoints]
        self.hive = hive
        self.failover_rounds = failover_rounds
        # endpoint -> worker cache: failover swaps the live list, but a
        # surviving worker keeps its Client (gRPC channel reuse) or its
        # in-process LocalWorker object
        self._worker_pool = {w.endpoint: w for w in self.workers}
        # the endpoint layout pk-hash insert routing was loaded against
        # (the post-failover upsert refusal compares against it)
        self._initial_endpoints = [w.endpoint for w in self.workers]
        # placement barrier: queries arriving while a re-placement is in
        # flight wait for it instead of racing a half-adopted shard;
        # failovers themselves serialize on _fo_mu (two queries blaming
        # the same dead worker run ONE re-placement, the second finds
        # the lease already expired and just re-resolves placement)
        self._placement_settled = threading.Event()
        self._placement_settled.set()
        # RLock: _failover holds it across _refresh_placement, and
        # refresh itself takes it so sweep-driven (lease-expiry)
        # adoption serializes with query traffic exactly like the
        # observed-transport-error path
        self._fo_mu = threading.RLock()
        # local engine used for the merge stage (schema-free: merge runs
        # over the gathered partial frame registered as a temp table)
        self.engine = merge_engine or QueryEngine(block_rows=1 << 16)
        if hive is not None:
            # the merge engine serves `.sys/cluster_nodes` off this hive
            self.engine.hive = hive
        self.replicated: set = set()        # table names on every worker
        self.key_columns: dict = {}         # table -> [pk col]
        # durable coordinator decision log for cross-worker 2PC
        # (cluster/dtx.py). None = single-statement routing only.
        # `dtx_replica` (a replica sink / directory / endpoint,
        # cluster/replica.py) mirrors every decision record to a standby
        # so a lost router disk cannot strand prepared workers in-doubt.
        from ydb_tpu.cluster.dtx import DtxJournal
        sink = None
        if dtx_replica is not None:
            from ydb_tpu.cluster.replica import make_sink
            sink = make_sink(dtx_replica)
        self.dtx_log = DtxJournal(dtx_log, sink=sink) if dtx_log else None

    # -- DDL / DML ----------------------------------------------------------

    def execute(self, sql: str, replicated: bool = False):
        """DDL: broadcast. INSERT ... VALUES: route rows by pk hash
        (replicated tables broadcast rows instead)."""
        stmt = parse(sql)
        if isinstance(stmt, ast.Insert):
            return self._route_insert(stmt, sql)
        for w in self.workers:
            w.execute(sql)
        if isinstance(stmt, ast.CreateTable):
            # remember pk for insert routing
            self.key_columns[stmt.name] = list(stmt.primary_key)
            if replicated:
                self.replicated.add(stmt.name)
        return {"ok": True}

    def _route_insert(self, stmt: ast.Insert, sql: str):
        import zlib

        from ydb_tpu.utils.hashing import splitmix64
        if stmt.query is not None and stmt.table not in self.replicated:
            raise ClusterError(
                "INSERT ... SELECT into a sharded table is not supported "
                "(broadcasting would duplicate every row per worker)")
        if stmt.table in self.replicated:
            if self.dtx_log is not None and stmt.mode == "upsert" \
                    and len(self.workers) > 1:
                # replicated UPSERT: all-or-nothing across every copy
                return self._commit_2pc([(w, [sql])
                                         for w in self.workers])
            for w in self.workers:
                w.execute(sql)
            return {"ok": True}
        pk = self.key_columns.get(stmt.table)
        if not pk:
            raise ClusterError(f"unknown sharded table {stmt.table!r}")
        if self.hive is not None and [w.endpoint for w in self.workers] \
                != self._initial_endpoints:
            # pk-hash routing is modulo the worker LIST — after a
            # failover shrank/changed it, ANY routed write of an
            # existing key can land beside a different worker's copy
            # (duplicate, divergent pk rows; a worker-local dup-pk
            # check cannot see the adopted copy). Refuse every mode
            # loudly until placement-aware write routing exists
            # (ROADMAP item 5c).
            raise ClusterError(
                f"{stmt.mode} into a sharded table after a topology "
                "change is not supported yet (pk-hash routing would "
                "diverge from the surviving placement)")
        if not stmt.columns:
            raise ClusterError("routed inserts need an explicit column "
                               "list (INSERT INTO t (cols...) VALUES ...)")
        ki = stmt.columns.index(pk[0])
        nw = len(self.workers)
        per: list = [[] for _ in range(nw)]
        for row in stmt.rows:
            v = row[ki].value if isinstance(row[ki], ast.Literal) else None
            if v is None:
                raise ClusterError("insert routing needs literal pk values")
            # deterministic across router processes (builtin hash() is
            # PYTHONHASHSEED-randomized). Only int/str pk literals route:
            # a float would silently truncate through the int64 hash
            # (10.5 and 10 co-routing — ADVICE r4) and bool is almost
            # certainly a mistyped pk.
            if isinstance(v, str):
                h = zlib.crc32(v.encode())
            elif isinstance(v, int) and not isinstance(v, bool):
                h = int(splitmix64(np, np.array([v], np.int64))[0])
            else:
                raise ClusterError(
                    f"insert routing needs int or string pk literals, "
                    f"got {type(v).__name__} ({v!r})")
            per[h % nw].append(row)
        cols = ", ".join(stmt.columns)
        per_sql = []
        for w, rows in zip(self.workers, per):
            if not rows:
                per_sql.append(None)
                continue
            vals = ", ".join(
                "(" + ", ".join(render.expr(v) for v in row) + ")"
                for row in rows)
            per_sql.append(f"{stmt.mode} into {stmt.table} ({cols}) "
                           f"values {vals}")
        touched = [(w, s) for (w, s) in zip(self.workers, per_sql)
                   if s is not None]
        # 2PC applies to UPSERT only: crash recovery RE-EXECUTES the
        # journaled statements, which is exactly-once only under upsert
        # semantics (a replayed plain INSERT into a column table would
        # append duplicates)
        if len(touched) > 1 and self.dtx_log is not None \
                and stmt.mode == "upsert":
            return self._commit_2pc([(w, [s]) for (w, s) in touched])
        for (w, s) in touched:
            w.execute(s)
        return {"ok": True}

    def _commit_2pc(self, work: list) -> dict:
        """Two-phase commit of per-worker statement lists: prepare all →
        durable decision → decide all (cluster/dtx.py; the coordinator
        plan-step protocol, `coordinator_impl.h:209`). A worker that
        dies after the decision is healed later by `resolve_in_doubt`
        re-delivering the logged decision."""
        import uuid
        gtx = uuid.uuid4().hex
        self.dtx_log.append({"op": "begin", "gtx": gtx,
                             "workers": [w.endpoint for (w, _s) in work]})
        prepared = []
        failed = None
        for (w, sqls) in work:
            try:
                w.tx_prepare(gtx, sqls)
                prepared.append(w)
            except Exception as e:           # noqa: BLE001
                failed = e
                break
        decision = "abort" if failed is not None else "commit"
        self.dtx_log.append({"op": "decision", "gtx": gtx,
                             "decision": decision})
        outcome_ok = True
        crash_points = getattr(self, "dtx_test_crash", {})
        for w in prepared:
            try:
                extra = {}
                cp = crash_points.get(w.endpoint)
                if cp:
                    extra["crash_point"] = cp
                w.tx_decide(gtx, decision, **extra)
            except Exception:                # noqa: BLE001
                outcome_ok = False           # healed by resolve_in_doubt
        if failed is not None:
            raise ClusterError(f"2PC aborted: {failed}")
        self.dtx_log.append({"op": "done", "gtx": gtx})
        return {"ok": True, "gtx": gtx, "healed_later": not outcome_ok}

    def resolve_in_doubt(self) -> dict:
        """Re-deliver durable decisions for transactions a worker holds
        in doubt (post-restart recovery). Unknown gtx (prepared on the
        worker, no decision logged — the router died first) resolve to
        abort: presumed-abort, the coordinator never promised commit."""
        if self.dtx_log is None:
            return {"resolved": 0}
        decisions = self.dtx_log.decisions()
        n = 0
        unreachable = []
        for w in self.workers:
            # heal the reachable subset: one down worker must not block
            # every other worker's recovery
            try:
                for gtx in w.tx_in_doubt():
                    w.tx_resolve(gtx, decisions.get(gtx, "abort"))
                    n += 1
            except Exception as e:           # noqa: BLE001
                unreachable.append((w.endpoint, str(e)[:80]))
        return {"resolved": n, "unreachable": unreachable}

    # -- SELECT (DQ stage-graph path) ---------------------------------------

    def _table_columns(self, table: str) -> list:
        """Column names of a worker table (cached; schema probe)."""
        cache = self.__dict__.setdefault("_col_cache", {})
        cols = cache.get(table)
        if cols is None:
            resp = self.workers[0].execute(f"select * from {table} limit 0")
            cols = cache[table] = list(resp["columns"])
        return cols

    def _ici_devices(self) -> int:
        """Devices of ONE JAX mesh the DQ runner can drive directly: the
        worker set must be entirely in-process (`LocalWorker` — gRPC
        endpoints are separate OS processes with separate meshes, DCN
        seam) and this process must expose at least one device per
        worker. 0 = host plane only."""
        if not self.workers or \
                not all(hasattr(w, "ici_land") for w in self.workers):
            return 0
        try:
            import jax
            n = len(jax.devices())
        except Exception:                    # noqa: BLE001 — no backend,
            return 0                         # no device plane
        return n if n >= len(self.workers) else 0

    def _lower(self, stmt: ast.Select):
        from ydb_tpu.dq.lower import DqLowerError, DqTopology, lower_select
        if self.hive is not None:
            topo = DqTopology.from_hive(
                self.hive, replicated=set(self.replicated),
                key_columns=dict(self.key_columns),
                ici_devices=self._ici_devices())
        else:
            topo = DqTopology(n_workers=len(self.workers),
                              replicated=set(self.replicated),
                              key_columns=dict(self.key_columns),
                              ici_devices=self._ici_devices())
        try:
            return lower_select(stmt, topo, self._table_columns)
        except DqLowerError as e:
            raise ClusterError(str(e)) from e

    # -- Hive placement / failover -----------------------------------------

    def _client_for(self, endpoint: str):
        from ydb_tpu.server import Client
        w = self._worker_pool.get(endpoint)
        if w is None:
            w = self._worker_pool[endpoint] = Client(endpoint)
        return w

    def _refresh_placement(self) -> None:
        """Rebuild the live worker list from the Hive's placement (alive,
        non-stale shard owners). Endpoints the router already knows keep
        their RELATIVE order (push agents race to register, and the
        operator's endpoint order is what pk-hash insert routing was
        loaded against — a silent reorder would re-route writes); only
        genuinely new endpoints append. No-op without a hive — the
        static endpoint list stays authoritative."""
        if self.hive is None:
            return
        with self._fo_mu:
            # under the failover lock: a lease-expiry sweep can run the
            # seconds-long image replay inline, and concurrent queries
            # must serialize behind it here (the same hold the
            # _failover path gives observed transport deaths) instead
            # of racing a half-adopted shard into a spurious error
            self.hive.sweep()
            alive = set(self.hive.query_endpoints())
            if not alive:
                return
            cur = [w.endpoint for w in self.workers]
            eps = [ep for ep in cur if ep in alive] \
                + [ep for ep in self.hive.query_endpoints()
                   if ep not in cur]
            if eps != cur:
                self.workers = [self._client_for(ep) for ep in eps]

    def _probe_lost(self, hint=(), kinds=None) -> list:
        """Which workers are transport-dead RIGHT NOW? The runner's view
        (`hint`) can blame a live sender whose peer died mid-frame, so
        every worker is ping-probed and the probe decides — EXCEPT for
        hang-shaped failures (`kinds[ep] == "timeout"`): a wedged worker
        still answers ping, so its RPC deadline is the only honest
        signal. A transient connection blip on a now-healthy worker
        must NOT evict it (eviction marks a rejoiner stale — an
        operator-level cost)."""
        from concurrent.futures import ThreadPoolExecutor
        kinds = kinds or {}

        def probe(w):
            try:
                return None if w.ping(timeout=5) else w.endpoint
            except Exception:                # noqa: BLE001 — dead is dead
                return w.endpoint
        with ThreadPoolExecutor(max_workers=len(self.workers)) as pool:
            lost = [ep for ep in pool.map(probe, self.workers)
                    if ep is not None]
        here = {w.endpoint for w in self.workers}
        # conscious trade-off: the "timeout" hint evicts a worker whose
        # RPC blew its deadline even though ping succeeds — that is the
        # wedged-engine shape. It assumes rpc_timeout (default 600 s)
        # is far above honest query time; operators who tighten it opt
        # into aggressive failover of merely-slow workers.
        return lost or [ep for ep in hint
                        if ep in here and kinds.get(ep) == "timeout"]

    def _failover(self, lost: list) -> None:
        """Expire the dead workers' leases, re-place their shards (the
        Hive's adopt hook replays each shard's standby image onto a
        survivor), and swap the worker list. Concurrent queries hold at
        the placement barrier while this runs."""
        from ydb_tpu.utils.metrics import GLOBAL
        with self._fo_mu:
            self._placement_settled.clear()
            try:
                self.hive.fail_workers(lost)
                GLOBAL.inc("dq/retry_rerouted")
                self._refresh_placement()
            finally:
                self._placement_settled.set()

    def plan(self, sql: str):
        """Lower a SELECT to its DQ stage graph without running it
        (EXPLAIN for the distributed plan)."""
        stmt = parse(sql)
        if not isinstance(stmt, ast.Select):
            raise ClusterError("only SELECT lowers to a stage graph")
        return self._lower(stmt)

    def query(self, sql: str) -> pd.DataFrame:
        """Distribute one SELECT: lower to a StageGraph, execute it with
        the task runner (one task per (stage, worker), channels between
        stages), merge router-side. The whole graph runs under ONE trace
        on the merge engine's tracer — worker task spans propagate back
        over the DqRunTask RPCs and assemble into a single cross-worker
        span tree (`engine.last_trace`, `.sys/query_profiles`).

        `EXPLAIN ANALYZE <select>` returns the distributed profile: the
        stage graph, per-(stage, worker) task stats (rows/bytes/frames/
        waits) and the assembled span tree, as a one-column frame."""
        from ydb_tpu.utils.metrics import GLOBAL
        stmt = parse(sql)
        if isinstance(stmt, ast.Explain):
            if not isinstance(stmt.query, ast.Select):
                raise ClusterError("EXPLAIN distributes SELECT only")
            return self._explain(stmt)
        if not isinstance(stmt, ast.Select):
            raise ClusterError("the router distributes SELECT; use "
                               "execute() for DDL/DML")
        from ydb_tpu.dq.lower import table_names
        refs = table_names(stmt.relation) if stmt.relation is not None \
            else []
        if refs and all(t.startswith(".sys/") for t in refs):
            # sysviews are router-local runtime state (`.sys/
            # cluster_nodes` reads THIS router's hive) — scattering them
            # over workers would be wrong twice over
            return self.engine.query(sql)
        if not self._placement_settled.is_set():
            # a re-placement is in flight: hold admission until the
            # adopted shard is queryable rather than racing it
            GLOBAL.inc("hive/failover_holds")
            self._placement_settled.wait(timeout=120)
        rounds = self.failover_rounds if self.hive is not None else 0
        for round_ in range(rounds + 1):
            self._refresh_placement()
            try:
                df, _runner = self._run_traced(stmt, sql)
                return df
            except ClusterError as e:
                if self.hive is None or round_ >= rounds:
                    raise
                lost = self._probe_lost(getattr(e, "lost_workers", ()),
                                        getattr(e, "lost_kinds", None))
                if not lost:
                    if self.hive.orphaned_shards():
                        # a concurrent failover is mid-re-placement (or
                        # a failed replay awaits its sweep retry): wait
                        # it out and re-resolve rather than failing a
                        # query a second earlier would have answered
                        GLOBAL.inc("hive/failover_holds")
                        self._placement_settled.wait(timeout=120)
                        with self._fo_mu:
                            pass       # drain any active failover
                        continue
                    raise              # a query error, not a dead worker
                self._failover(lost)
        raise AssertionError("unreachable: the failover loop returns a "
                             "frame or raises")

    def _run_traced(self, stmt: ast.Select, sql: str,
                    force_trace: bool = False, graph=None):
        import time as _time

        from ydb_tpu.dq.runner import DqError, DqTaskRunner
        from ydb_tpu.utils.metrics import GLOBAL_HIST
        if graph is None:
            graph = self._lower(stmt)
        elif self.hive is not None \
                and graph.placement_epoch != self.hive.epoch:
            # a pre-lowered graph (EXPLAIN ANALYZE reuses the one it
            # printed) whose placement went stale would task dead
            # workers / the wrong peer count — re-lower on the current
            # epoch instead
            graph = self._lower(stmt)
        runner = DqTaskRunner(self.workers, self.engine)
        eng = self.engine
        sampled = force_trace or eng._sample_decision(sql)
        eng.tracer.begin_trace(sampled=sampled)
        t0 = _time.perf_counter()
        rows_out = None
        try:
            with eng.tracer.span("dq-query", sql=sql[:60],
                                 workers=len(self.workers),
                                 stages=len(graph.stages)):
                df = runner.run(graph)
            rows_out = len(df)
            return df, runner
        except DqError as e:
            ce = ClusterError(str(e))
            # the failover loop reads which endpoints died at the
            # transport level (DqWorkerLost and accumulated task errors)
            ce.lost_workers = sorted(
                set(getattr(e, "endpoints", ()))
                | runner.transport_failed)
            ce.lost_kinds = dict(runner.transport_kinds)
            raise ce from e
        finally:
            total_ms = (_time.perf_counter() - t0) * 1000.0
            if rows_out is not None:
                # successes only — the local path records latency in
                # _finish_stats, which a failed statement never reaches;
                # a timed-out DQ run would otherwise inject a 600 s
                # timeout artifact into p99/max
                GLOBAL_HIST.observe("query/latency_ms", total_ms)
                eng._note_slow(sql, total_ms, "dq-select")
            spans = eng.tracer.end_trace()
            if spans:
                eng.last_trace = spans
                # the DQ wall/rows pass explicitly: last_stats only
                # covers the router-merge statement (or a previous one)
                eng._record_profile(
                    sql, spans, stage_stats=runner.stage_stats,
                    total_ms=round(total_ms, 3),
                    rows_out=rows_out or 0,
                    # a failed run must not masquerade as a successful
                    # empty-result query (the local path marks these
                    # "error" the same way)
                    kind="dq-select" if rows_out is not None
                    else "dq-error",
                    # the graph run's closed ledger: critical-path
                    # extraction costs its transferred/padded bytes
                    # next to the blocking milliseconds
                    memory=runner.mem_summary)

    def _explain(self, stmt: ast.Explain) -> pd.DataFrame:
        """Distributed EXPLAIN [ANALYZE]: the stage graph, and with
        ANALYZE the per-stage/per-channel profile of an actual run."""
        graph = self._lower(stmt.query)
        lines = [f"DQ stage graph: {len(graph.stages)} stages, "
                 f"{len(graph.channels)} channels, "
                 f"{len(self.workers)} workers"]
        for stage in graph.stages:
            lines.append(f"  stage {stage.id} on={stage.on} "
                         f"in={list(stage.inputs)} "
                         f"out={list(stage.outputs)}")
        # per-channel data plane: which edges go device-resident (ICI
        # collective) vs host gRPC frames — the operator-facing half of
        # the pluggable-plane lowering
        for ch in graph.channels.values():
            lines.append(
                f"  channel {ch.id} kind={ch.kind} plane={ch.plane}"
                + (f" key={ch.key}" if ch.key else "")
                + (f" quant_cols={ch.quant_cols}" if ch.quant_cols
                   else ""))
        if not stmt.analyze:
            return pd.DataFrame({"plan": lines})
        # run the SAME lowered graph the listing above describes —
        # re-lowering could diverge from the plan this output claims
        # to profile (and pays the lowering twice)
        df, runner = self._run_traced(stmt.query,
                                      render.select(stmt.query),
                                      force_trace=True, graph=graph)
        lines.append(f"-- rows out: {len(df)}")
        lines.append("-- stage stats (per task):")
        for r in runner.stage_stats:
            lines.append(
                f"  {r['stage']}@{r['worker']}: rows {r['rows']} | "
                f"bytes {r['bytes']} | frames {r['frames']} | "
                f"plane {r.get('plane', 'host')} | "
                f"ici-bytes {r.get('ici_bytes', 0)} | "
                f"exec {r['exec_ms']:.1f}ms | flush {r['flush_ms']:.1f}ms"
                f" | input-wait {r['input_wait_ms']:.1f}ms | "
                f"backpressure {r['backpressure_wait_ms']:.1f}ms | "
                f"attempts {r['attempts']}")
        tr = self.engine.tracer.render(self.engine.last_trace)
        if tr:
            lines += ["-- trace:"] + tr.split("\n")
        # the distributed critical path (extracted in _record_profile
        # from the SAME assembled tree rendered above): per-class % of
        # the graph wall + the dominant span — the worklist line
        from ydb_tpu.utils import critpath
        prof = self.engine.profiles[-1] if self.engine.profiles else {}
        lines += critpath.render_lines(prof.get("critical_path") or {})
        return pd.DataFrame({"plan": lines})
